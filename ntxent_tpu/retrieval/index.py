"""One searchable vector index: segments + IVF + telemetry.

``VectorIndex`` ties the durable substrate (segments.py) to the search
structure (ivf.py) behind a single lock:

* inserts append to the mutable segment AND to the live search
  structure, so a row is searchable the moment ``insert`` returns;
* below ``train_rows`` total rows the search is exact brute force —
  recall is perfect while the index is small, and there is nothing to
  train centroids on yet ("exact brute-force fallback below the
  training threshold");
* at ``train_rows`` the next maintenance pass trains k-means centroids
  on everything inserted so far and switches to IVF-``nprobe`` search
  (an ``index`` event with ``action="build"`` marks the cut);
* ``maintain()`` also runs the segment lifecycle — seal the mutable
  tail past ``seal_rows``, compact past ``compact_at`` sealed segments
  — and refreshes the recall-probe gauge, so one periodic call (the
  manager's maintenance thread, or a test) drives everything
  background about the index.

Telemetry rides a shared ``RetrievalMetrics`` (one per manager — the
counters are fleet-lifetime totals across index versions, the gauges
describe the ACTIVE version) and typed ``index`` events through the
process-wide obs hub.
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from ..obs import events as _events
from ..obs.registry import MetricsRegistry
from .ivf import IVFIndex, brute_force_topk, kmeans
from .segments import SegmentStore

logger = logging.getLogger(__name__)

__all__ = ["RetrievalMetrics", "VectorIndex"]


class RetrievalMetrics:
    """The retrieval tier's metric family on a shared registry.

    One instance serves every index version a manager retains:
    counters accumulate across versions (a promote must not zero the
    fleet's insert history), gauges are overwritten to describe the
    active version (``IndexManager.publish``).
    """

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        r = self.registry
        self.rows = r.gauge("retrieval_index_rows",
                            "vectors in the active index version")
        self.segments = r.gauge("retrieval_index_segments",
                                "segments (sealed + mutable tail) in "
                                "the active index version")
        self.version = r.gauge("retrieval_index_version",
                               "checkpoint step the active index was "
                               "built under (-1 = none)")
        self.version.set(-1)
        self.stale = r.gauge("retrieval_index_stale",
                             "1 while the active index is marked stale "
                             "(embedding-space drift) pending rebuild")
        self.versions = r.gauge("retrieval_index_versions",
                                "index versions currently retained")
        self.docstore_rows = r.gauge("retrieval_docstore_rows",
                                     "input rows retained for rebuild")
        self.recall = r.gauge("retrieval_recall_probe",
                              "last probed recall@k of ANN search vs "
                              "brute force on sampled stored rows")
        self.inserts = r.counter("retrieval_inserts_total",
                                 "vector rows inserted")
        self.searches = r.counter("retrieval_searches_total",
                                  "query rows searched")
        self.docstore_evictions = r.counter(
            "retrieval_docstore_evictions_total",
            "input rows evicted from the rebuild store (bound hit)")
        self.rebuilt_rows = r.counter(
            "retrieval_rebuilt_rows_total",
            "rows re-embedded into a rebuilt index version")
        self._ops: dict[str, object] = {}
        self._ops_lock = threading.Lock()
        # search/insert are the index-internal scans; search_request is
        # the router's end-to-end /search (embed forward + scan).
        self.latency = {
            stage: r.histogram("retrieval_latency_ms",
                               "retrieval op latency by stage",
                               labels={"stage": stage})
            for stage in ("search", "insert", "search_request")
        }

    def op(self, kind: str) -> None:
        """Bump ``retrieval_ops_total{kind=...}`` (build/seal/compact/
        promote/rollback/stale/rebuild — the index lifecycle)."""
        with self._ops_lock:
            counter = self._ops.get(kind)
            if counter is None:
                counter = self._ops[kind] = self.registry.counter(
                    "retrieval_ops_total",
                    "index lifecycle actions by kind",
                    labels={"kind": kind})
        counter.inc()


class VectorIndex:
    """Thread-safe searchable index over one embedding space.

    ``step`` is the checkpoint step whose model produced the vectors —
    purely a label here; the version semantics live in
    ``IndexManager``.
    """

    def __init__(self, dim: int, step: int | None = None,
                 root=None, train_rows: int = 2048,
                 n_centroids: int = 64, nprobe: int = 16,
                 seal_rows: int = 4096, compact_at: int = 4,
                 seed: int = 0,
                 metrics: RetrievalMetrics | None = None):
        self.dim = int(dim)
        self.step = step
        self.train_rows = max(1, int(train_rows))
        self.n_centroids = max(1, int(n_centroids))
        self.nprobe = max(1, int(nprobe))
        self.seed = int(seed)
        self.metrics = metrics
        self._lock = threading.Lock()
        # Serializes maintainers (the manager's thread, a test, an
        # eager caller) — heavy maintenance work runs OUTSIDE
        # ``_lock`` so searches never stall behind an fsync, a
        # compaction merge, or a k-means pass.
        self._maint_lock = threading.Lock()
        self.store = SegmentStore(self.dim, root=root,
                                  seal_rows=seal_rows,
                                  compact_at=compact_at)
        # Set by the manager when this instance is replaced/dropped:
        # maintenance becomes a no-op, so a deleter can barrier on
        # ``_maint_lock`` and then remove the segment directory
        # without an in-flight seal recreating it underneath.
        self.retired = False
        self._ivf: IVFIndex | None = None
        if self.store.rows >= self.train_rows:
            # Reopened with enough durable rows: train immediately so
            # a restart serves ANN search from the first query.
            self.maintain()

    # -- writes ------------------------------------------------------------
    def insert(self, ids, vectors, count_metrics: bool = True) -> int:
        """Append rows (searchable immediately); returns rows added.
        ``count_metrics=False`` is the rebuild path's spelling: a
        background re-embed replay must not inflate the client-facing
        insert counters/latency (it has its own
        ``retrieval_rebuilt_rows_total``)."""
        vecs = np.asarray(vectors, np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None]
        if vecs.shape[1] != self.dim:
            raise ValueError(f"expected dim {self.dim}, got "
                             f"{vecs.shape[1]}")
        ids = np.asarray(ids, np.int64)
        t0 = time.monotonic()
        with self._lock:
            self.store.append(ids, vecs)
            if self._ivf is not None:
                self._ivf.add(ids, vecs)
        if self.metrics is not None and count_metrics:
            self.metrics.inserts.inc(int(vecs.shape[0]))
            self.metrics.latency["insert"].observe(
                (time.monotonic() - t0) * 1e3)
        return int(vecs.shape[0])

    # -- reads (all LOCK-FREE — see ``search`` for the argument) -----------
    @property
    def rows(self) -> int:
        return self.store.rows

    @property
    def trained(self) -> bool:
        return self._ivf is not None

    def search(self, queries, k: int = 10,
               nprobe: int | None = None) -> tuple[np.ndarray,
                                                   np.ndarray]:
        """Top-k ``(ids [Q,k], scores [Q,k])``; brute force until
        trained, IVF after. Missing slots carry id -1.

        LOCK-FREE: searches take no lock at all — under concurrent
        insert+query a shared lock convoys with the GIL and measured
        as a ~50 ms search p99 (vs a sub-ms p50). Safety comes from
        the single-writer discipline (``_lock`` serializes all
        mutation) plus write ordering: every append writes row data
        BEFORE bumping the visible count, and buffer growth copies the
        committed prefix before the pointer swap — so any interleaving
        of attribute reads yields a valid prefix of committed rows,
        never torn data. A search may simply miss rows committed after
        it started, which is the semantics a concurrent reader expects
        anyway."""
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None]
        t0 = time.monotonic()
        ivf = self._ivf
        if ivf is None:
            ids, vecs = self.store.all_rows()
            out = brute_force_topk(q, ids, vecs, k)
        else:
            out = ivf.search(q, k,
                             self.nprobe if nprobe is None else nprobe)
        if self.metrics is not None:
            self.metrics.searches.inc(int(q.shape[0]))
            self.metrics.latency["search"].observe(
                (time.monotonic() - t0) * 1e3)
        return out

    def search_exact(self, queries, k: int = 10) -> tuple[np.ndarray,
                                                          np.ndarray]:
        """Brute-force top-k regardless of training state (the recall
        probe's ground truth). Lock-free like ``search``."""
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None]
        ids, vecs = self.store.all_rows()
        return brute_force_topk(q, ids, vecs, k)

    def recall_probe(self, k: int = 10, sample: int = 32,
                     seed: int = 1) -> float | None:
        """recall@k of ANN search vs brute force on ``sample`` stored
        rows; None below 2k rows (nothing meaningful to probe). Updates
        the gauge."""
        ids, vecs = self.store.all_rows()
        n = vecs.shape[0]
        if n < 2 * k:
            return None
        rng = np.random.RandomState(seed)
        pick = rng.choice(n, size=min(int(sample), n), replace=False)
        q = np.asarray(vecs[pick], np.float32)
        # Bypass ``search``'s metrics: synthetic probe queries must
        # not inflate retrieval_searches_total or the stage=search
        # latency series a dashboard reads as client traffic.
        ivf = self._ivf
        if ivf is None:
            ann_ids, _ = brute_force_topk(q, ids, vecs, k)
        else:
            ann_ids, _ = ivf.search(q, k, self.nprobe)
        exact_ids, _ = brute_force_topk(q, ids, vecs, k)
        hit = sum(len(set(a.tolist()) & set(e.tolist()))
                  for a, e in zip(ann_ids, exact_ids))
        recall = hit / float(exact_ids.shape[0] * k)
        if self.metrics is not None:
            self.metrics.recall.set(recall)
        return recall

    # -- maintenance -------------------------------------------------------
    def maintain(self) -> bool:
        """One maintenance pass: train at threshold, seal past
        ``seal_rows``, compact past ``compact_at``. Returns True when
        anything happened (the manager's thread backs off when idle).

        TWO-PHASE under ``_maint_lock``: every copy/IO-heavy step
        (k-means, the freeze's fsyncs, the compaction merge) runs
        OUTSIDE the index lock, which is held only for pointer swaps —
        the cost of background upkeep must never appear as a search
        p99 spike. Searches keep answering throughout: brute force
        while centroids train, the pending tail stays visible while a
        seal's bytes hit disk, old segments serve until the merged one
        swaps in."""
        did = False
        with self._maint_lock:
            if self.retired:
                # Replaced by a rebuild/rollback: no further segment
                # writes — the manager may be deleting our directory.
                return False
            # 1) training cut: k-means AND the full list build run
            #    outside the index lock over a bounded snapshot
            #    (sealed + pending + the mutable tail's first n0
            #    rows — all stable here: only this _maint_lock-
            #    serialized pass seals/compacts, and lock-free reads
            #    of committed prefixes are safe by the view
            #    discipline). Under the lock only the DELTA rows that
            #    arrived mid-training are added before the publish —
            #    a full in-lock build at a large train_rows was
            #    exactly the search-stall this two-phase contract
            #    forbids.
            if self._ivf is None:
                mut0 = self.store.mutable
                n0 = mut0.rows
                parts = [s.view() if hasattr(s, "view")
                         else (s.ids, s.vectors)
                         for s in list(self.store.sealed)]
                pending = self.store.pending
                if pending is not None and pending.rows:
                    parts.append(pending.view())
                mids0, mvecs0 = mut0.view()
                parts.append((mids0[:n0], mvecs0[:n0]))
                ids1 = np.concatenate([np.asarray(i)
                                       for i, _ in parts])
                vecs1 = np.concatenate([np.asarray(v)
                                        for _, v in parts])
                if ids1.shape[0] >= self.train_rows:
                    k = min(self.n_centroids, max(1, vecs1.shape[0]))
                    centroids = kmeans(vecs1, k, seed=self.seed)
                    ivf = IVFIndex(centroids)
                    ivf.add(ids1, vecs1)
                    with self._lock:
                        # Only maintain swaps the mutable tail, and we
                        # ARE maintain — the identity check is a
                        # safety net, not an expected path.
                        if self.store.mutable is mut0:
                            mids, mvecs = mut0.view()
                            if mids.shape[0] > n0:
                                ivf.add(mids[n0:], mvecs[n0:])
                            self._ivf = ivf
                            trained_rows = int(
                                ids1.shape[0]
                                + max(0, mids.shape[0] - n0))
                        else:  # pragma: no cover — retry next pass
                            trained_rows = None
                    if trained_rows is not None:
                        did = True
                        _events.emit("index", action="build",
                                     step=self.step,
                                     rows=trained_rows,
                                     centroids=int(k),
                                     nprobe=self.nprobe)
                        if self.metrics is not None:
                            self.metrics.op("build")
                        logger.info("retrieval: trained %d centroids "
                                    "over %d rows (step %s)", k,
                                    trained_rows, self.step)
            # 2) seal: pointer-take under the lock, freeze (disk or
            #    in-memory trim) outside, publish under the lock.
            frozen = None
            with self._lock:
                if self.store.should_seal():
                    frozen = self.store.take_mutable()
            if frozen is not None and frozen.rows:
                seg = self.store.freeze(frozen)
                with self._lock:
                    self.store.publish(seg)
                did = True
                _events.emit("index", action="seal", step=self.step,
                             segment=seg.name, rows=seg.rows)
                if self.metrics is not None:
                    self.metrics.op("seal")
            # 3) compact: merge outside the lock, swap in, delete the
            #    inputs after no reader can pick them up.
            olds = None
            with self._lock:
                if self.store.should_compact():
                    olds = list(self.store.sealed)
            if olds:
                merged = self.store.merge(olds)
                with self._lock:
                    self.store.swap_sealed(olds, merged)
                self.store.delete_segments(olds)
                did = True
                _events.emit("index", action="compact", step=self.step,
                             segment=merged.name, rows=merged.rows)
                if self.metrics is not None:
                    self.metrics.op("compact")
        return did
