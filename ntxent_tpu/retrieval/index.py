"""One searchable vector index: segments + IVF + telemetry.

``VectorIndex`` ties the durable substrate (segments.py) to the search
structure (ivf.py) behind a single lock:

* inserts append to the mutable segment AND to the live search
  structure, so a row is searchable the moment ``insert`` returns;
* below ``train_rows`` total rows the search is exact brute force —
  recall is perfect while the index is small, and there is nothing to
  train centroids on yet ("exact brute-force fallback below the
  training threshold");
* at ``train_rows`` the next maintenance pass trains k-means centroids
  on everything inserted so far and switches to IVF-``nprobe`` search
  (an ``index`` event with ``action="build"`` marks the cut);
* ``maintain()`` also runs the segment lifecycle — seal the mutable
  tail past ``seal_rows``, compact past ``compact_at`` sealed segments
  — and refreshes the recall-probe gauge, so one periodic call (the
  manager's maintenance thread, or a test) drives everything
  background about the index.

Telemetry rides a shared ``RetrievalMetrics`` (one per manager — the
counters are fleet-lifetime totals across index versions, the gauges
describe the ACTIVE version) and typed ``index`` events through the
process-wide obs hub.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from pathlib import Path

import numpy as np

from ..obs import events as _events
from ..obs.registry import MetricsRegistry
from .ivf import IVFIndex, brute_force_topk, kmeans
from .pq import PQCodec
from .scan import CodedLists, ScanBatcher, batched_scan
from .segments import SegmentStore, _fsync_path

logger = logging.getLogger(__name__)

__all__ = ["RetrievalMetrics", "VectorIndex"]

_STATE_DIR = "state"
_STATE_META = "state.json"
_CENTROIDS = "centroids.f32"


def _save_state(root, centroids: np.ndarray) -> None:
    """Persist trained IVF centroids under ``root/state`` with the
    stage-fsync-rename idiom (crash leaves old state or new, never a
    torn mix) — the codec persists itself the same way (pq.save)."""
    root = Path(root)
    tmp = root / f".tmp-state-{uuid.uuid4().hex[:8]}"
    tmp.mkdir(parents=True)
    arr = np.ascontiguousarray(centroids, np.float32)
    with open(tmp / _CENTROIDS, "wb") as f:
        f.write(arr.tobytes())
        f.flush()
        os.fsync(f.fileno())
    with open(tmp / _STATE_META, "w") as f:
        json.dump({"n_centroids": int(arr.shape[0]),
                   "dim": int(arr.shape[1])}, f)
        f.flush()
        os.fsync(f.fileno())
    _fsync_path(tmp)
    final = root / _STATE_DIR
    if final.exists():
        import shutil
        old = root / f".old-state-{uuid.uuid4().hex[:8]}"
        os.rename(final, old)
        os.rename(tmp, final)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.rename(tmp, final)
    _fsync_path(root)


def _load_state(root) -> np.ndarray | None:
    """Reopen persisted centroids; None when absent/unreadable (the
    caller falls back to retraining — never an exception out of an
    index open)."""
    path = Path(root) / _STATE_DIR
    try:
        meta = json.loads((path / _STATE_META).read_text())
        raw = np.fromfile(path / _CENTROIDS, dtype=np.float32)
        return raw.reshape(int(meta["n_centroids"]),
                           int(meta["dim"])).copy()
    except (OSError, ValueError, KeyError, TypeError):
        return None


class _StoreCoder:
    """The ``SegmentStore.coder`` protocol over a trained codec +
    centroids: seals and compactions call this to stamp segments with
    PQ sidecars (encode-on-seal)."""

    def __init__(self, codec: PQCodec, centroids: np.ndarray):
        self.codec = codec
        self.centroids = np.ascontiguousarray(centroids, np.float32)

    def encode(self, vecs: np.ndarray) -> np.ndarray:
        return self.codec.encode(vecs)

    def assign(self, vecs: np.ndarray) -> np.ndarray:
        return np.argmax(np.asarray(vecs, np.float32)
                         @ self.centroids.T, axis=1).astype(np.int32)

    @property
    def gen(self) -> int:
        return self.codec.gen


class RetrievalMetrics:
    """The retrieval tier's metric family on a shared registry.

    One instance serves every index version a manager retains:
    counters accumulate across versions (a promote must not zero the
    fleet's insert history), gauges are overwritten to describe the
    active version (``IndexManager.publish``).
    """

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        r = self.registry
        self.rows = r.gauge("retrieval_index_rows",
                            "vectors in the active index version")
        self.segments = r.gauge("retrieval_index_segments",
                                "segments (sealed + mutable tail) in "
                                "the active index version")
        self.version = r.gauge("retrieval_index_version",
                               "checkpoint step the active index was "
                               "built under (-1 = none)")
        self.version.set(-1)
        self.stale = r.gauge("retrieval_index_stale",
                             "1 while the active index is marked stale "
                             "(embedding-space drift) pending rebuild")
        self.versions = r.gauge("retrieval_index_versions",
                                "index versions currently retained")
        self.docstore_rows = r.gauge("retrieval_docstore_rows",
                                     "input rows retained for rebuild")
        self.recall = r.gauge("retrieval_recall_probe",
                              "last probed recall@k of ANN search vs "
                              "brute force on sampled stored rows")
        # Memory economy (ISSUE 17): what the PQ codes buy. The bytes
        # gauge is the active version's RESIDENT scan structure (codes
        # + locators + the raw insert tail), bytes_per_row its per-row
        # quotient — raw IVF-flat residency is dim*4+8 for comparison.
        self.index_bytes = r.gauge(
            "retrieval_index_bytes",
            "resident bytes of the active version's scan structure")
        self.bytes_per_row = r.gauge(
            "retrieval_index_bytes_per_row",
            "resident scan-structure bytes per stored row")
        self.inserts = r.counter("retrieval_inserts_total",
                                 "vector rows inserted")
        self.searches = r.counter("retrieval_searches_total",
                                  "query rows searched")
        self.docstore_evictions = r.counter(
            "retrieval_docstore_evictions_total",
            "input rows evicted from the rebuild store (bound hit)")
        self.rebuilt_rows = r.counter(
            "retrieval_rebuilt_rows_total",
            "rows re-embedded into a rebuilt index version")
        # The fused-scan economy counters (scan.batched_scan stats):
        # code bytes are the compact gather the ADC pass touches,
        # rerank bytes the raw rows the exact re-rank touches — their
        # ratio IS the memory-bandwidth win the DLRM analysis names.
        self.scan_bytes = {
            kind: r.counter("retrieval_scan_bytes_total",
                            "bytes touched by the fused scan by kind",
                            labels={"kind": kind})
            for kind in ("codes", "rerank")
        }
        self.scan_batches = r.counter(
            "retrieval_scan_batches_total",
            "fused scan passes executed")
        self.scan_fused_queries = r.counter(
            "retrieval_scan_queries_total",
            "query rows answered by fused scan passes")
        self._ops: dict[str, object] = {}
        self._ops_lock = threading.Lock()
        # search/insert are the index-internal scans; search_request is
        # the router's end-to-end /search (embed forward + scan).
        self.latency = {
            stage: r.histogram("retrieval_latency_ms",
                               "retrieval op latency by stage",
                               labels={"stage": stage})
            for stage in ("search", "insert", "search_request")
        }

    def op(self, kind: str) -> None:
        """Bump ``retrieval_ops_total{kind=...}`` (build/seal/compact/
        promote/rollback/stale/rebuild — the index lifecycle)."""
        with self._ops_lock:
            counter = self._ops.get(kind)
            if counter is None:
                counter = self._ops[kind] = self.registry.counter(
                    "retrieval_ops_total",
                    "index lifecycle actions by kind",
                    labels={"kind": kind})
        counter.inc()


class VectorIndex:
    """Thread-safe searchable index over one embedding space.

    ``step`` is the checkpoint step whose model produced the vectors —
    purely a label here; the version semantics live in
    ``IndexManager``.
    """

    def __init__(self, dim: int, step: int | None = None,
                 root=None, train_rows: int = 2048,
                 n_centroids: int = 64, nprobe: int = 16,
                 seal_rows: int = 4096, compact_at: int = 4,
                 seed: int = 0,
                 metrics: RetrievalMetrics | None = None,
                 pq_m: int = 8, pq_ksub: int = 256,
                 pq_rerank: int = 512, opq_iters: int = 0,
                 pq_train_rows: int = 65536):
        self.dim = int(dim)
        self.step = step
        self.train_rows = max(1, int(train_rows))
        self.n_centroids = max(1, int(n_centroids))
        self.nprobe = max(1, int(nprobe))
        self.seed = int(seed)
        self.metrics = metrics
        # PQ knobs (ISSUE 17): pq_m=0 disables the coded path and
        # restores the PR 14 IVF-flat structure. pq_rerank is the ADC
        # candidate pool re-scored exactly per query (the effective
        # pool is max(pq_rerank, 4k)) — at m=8 the ADC ordering is too
        # coarse for within-cluster fine ranking, so the pool must be
        # hundreds, not tens (measured: top-512 holds 99%+ of the true
        # top-10; top-64 barely 55%). pq_train_rows caps the codebook
        # training sample so a huge index never pays a huge k-means.
        self.pq_m = max(0, int(pq_m))
        self.pq_ksub = int(pq_ksub)
        self.pq_rerank = max(1, int(pq_rerank))
        self.opq_iters = max(0, int(opq_iters))
        self.pq_train_rows = max(256, int(pq_train_rows))
        self._lock = threading.Lock()
        # Serializes maintainers (the manager's thread, a test, an
        # eager caller) — heavy maintenance work runs OUTSIDE
        # ``_lock`` so searches never stall behind an fsync, a
        # compaction merge, or a k-means pass.
        self._maint_lock = threading.Lock()
        self.store = SegmentStore(self.dim, root=root,
                                  seal_rows=seal_rows,
                                  compact_at=compact_at)
        # Set by the manager when this instance is replaced/dropped:
        # maintenance becomes a no-op, so a deleter can barrier on
        # ``_maint_lock`` and then remove the segment directory
        # without an in-flight seal recreating it underneath.
        self.retired = False
        self._ivf: IVFIndex | None = None
        # The coded plane: a trained PQCodec, the coded inverted lists
        # over every SEALED segment (the raw insert tail stays exact-
        # scanned until it seals), and the leader-coalescing batcher
        # that fuses concurrent searches into shared list passes.
        self._codec: PQCodec | None = None
        self._coded: CodedLists | None = None
        self._batcher: ScanBatcher | None = None
        # Parallel to ``CodedLists.sources``: (segment name, start row
        # within that segment) per source — what compaction needs to
        # rebase each source onto a row-aligned slice of the merged
        # mmap without touching a single locator.
        self._src_meta: list[tuple[str, int]] = []
        # True when this instance reopened its trained state (codec +
        # centroids + sidecars) from disk — zero re-clustering.
        self.trained_from_snapshot = False
        if self._load_trained():
            self.trained_from_snapshot = True
        elif self.store.rows >= self.train_rows:
            # Reopened with enough durable rows but no usable trained
            # snapshot: train immediately so a restart serves ANN
            # search from the first query.
            self.maintain()

    # -- trained-state install / persistence -------------------------------
    def _append_segment_coded(self, coded: CodedLists, seg,
                              src: int) -> None:
        """Feed one sealed segment into the coded lists: same-gen
        sidecars are adopted verbatim (the encode already happened at
        seal); anything else re-encodes in bounded blocks so a huge
        mmap never materializes at once."""
        gen = coded.codec.gen
        if getattr(seg, "codec_gen", None) == gen \
                and seg.codes is not None and seg.assign is not None:
            coded.append_assigned(
                np.asarray(seg.assign), np.asarray(seg.ids),
                np.asarray(seg.codes), src,
                np.arange(seg.rows, dtype=np.int32))
            return
        block = 65536
        for off in range(0, seg.rows, block):
            hi = min(off + block, seg.rows)
            v = np.asarray(seg.vectors[off:hi], np.float32)
            coded.append_assigned(
                coded.assign(v), np.asarray(seg.ids[off:hi]),
                coded.codec.encode(v), src,
                np.arange(off, hi, dtype=np.int32))

    def _install_coded(self, centroids: np.ndarray,
                       codec: PQCodec) -> None:
        """Build the coded plane over the current sealed segments and
        publish it (pointer swaps under the index lock). Caller holds
        ``_maint_lock`` (or is ``__init__`` — no concurrency yet)."""
        coded = CodedLists(centroids, codec)
        src_meta: list[tuple[str, int]] = []
        for seg in list(self.store.sealed):
            src = coded.add_source(seg.vectors)
            self._append_segment_coded(coded, seg, src)
            src_meta.append((seg.name, 0))
        coder = _StoreCoder(codec, centroids)
        batcher = ScanBatcher(self._scan_fn)
        with self._lock:
            self.store.coder = coder
            self._codec = codec
            self._coded = coded
            self._src_meta = src_meta
            self._batcher = batcher

    def _load_trained(self) -> bool:
        """Reopen the persisted trained state (centroids + codec +
        sealed sidecars) — a restart must serve a trained index with
        ZERO re-clustering. False when anything is missing or stale
        (the caller falls back to retraining)."""
        root = self.store.root
        if root is None or self.pq_m <= 0 or not self.store.sealed:
            return False
        centroids = _load_state(root)
        if centroids is None or centroids.shape[1] != self.dim:
            return False
        codec = PQCodec.load(root)
        if codec is None or codec.dim != self.dim \
                or not codec.trained:
            return False
        self._install_coded(centroids, codec)
        _events.emit("index", action="reopen_trained", step=self.step,
                     rows=self.rows, centroids=int(centroids.shape[0]),
                     pq_m=codec.m, codec_gen=codec.gen)
        if self.metrics is not None:
            self.metrics.op("reopen_trained")
        logger.info("retrieval: reopened TRAINED index (%d rows, %d "
                    "centroids, pq m=%d gen=%d) — no re-clustering",
                    self.rows, centroids.shape[0], codec.m, codec.gen)
        return True

    # -- memory accounting -------------------------------------------------
    def resident_bytes(self) -> int:
        """RAM the search structure holds resident: the coded plane
        (codes + locators) plus the raw insert tail — sealed raw
        vectors live behind mmaps and only page in for re-ranks. The
        pre-PQ structure is charged at raw residency (dim*4 + id)."""
        raw_per = self.dim * 4 + 8
        tail = self.store.mutable.rows \
            + (self.store.pending.rows
               if self.store.pending is not None else 0)
        coded = self._coded
        if coded is not None:
            return coded.memory_bytes() + tail * raw_per
        return self.store.rows * raw_per

    def scan_bytes_per_row(self) -> float:
        return self.resident_bytes() / max(1, self.store.rows)

    # -- writes ------------------------------------------------------------
    def insert(self, ids, vectors, count_metrics: bool = True) -> int:
        """Append rows (searchable immediately); returns rows added.
        ``count_metrics=False`` is the rebuild path's spelling: a
        background re-embed replay must not inflate the client-facing
        insert counters/latency (it has its own
        ``retrieval_rebuilt_rows_total``)."""
        vecs = np.asarray(vectors, np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None]
        if vecs.shape[1] != self.dim:
            raise ValueError(f"expected dim {self.dim}, got "
                             f"{vecs.shape[1]}")
        ids = np.asarray(ids, np.int64)
        t0 = time.monotonic()
        with self._lock:
            self.store.append(ids, vecs)
            if self._ivf is not None:
                self._ivf.add(ids, vecs)
        if self.metrics is not None and count_metrics:
            self.metrics.inserts.inc(int(vecs.shape[0]))
            self.metrics.latency["insert"].observe(
                (time.monotonic() - t0) * 1e3)
        return int(vecs.shape[0])

    # -- reads (all LOCK-FREE — see ``search`` for the argument) -----------
    @property
    def rows(self) -> int:
        return self.store.rows

    @property
    def trained(self) -> bool:
        return self._ivf is not None or self._coded is not None

    def _scan_fn(self, qs: np.ndarray, key) -> tuple[np.ndarray,
                                                     np.ndarray]:
        """The batcher's fused pass: one ``batched_scan`` over the
        coded lists for every coalesced query block sharing ``key``
        (= (k, nprobe))."""
        k, nprobe = key
        stats: dict | None = {} if self.metrics is not None else None
        out = batched_scan(self._coded, qs, k, nprobe,
                           max(self.pq_rerank, 4 * k), stats=stats)
        if stats:
            m = self.metrics
            m.scan_bytes["codes"].inc(stats.get("code_bytes", 0))
            m.scan_bytes["rerank"].inc(stats.get("rerank_bytes", 0))
            m.scan_batches.inc(stats.get("batches", 0))
            m.scan_fused_queries.inc(stats.get("queries", 0))
        return out

    def _search_coded(self, q: np.ndarray, k: int,
                      nprobe: int) -> tuple[np.ndarray, np.ndarray]:
        """Coded-plane search: fused ADC scan over the sealed rows
        (through the batcher) merged with an exact dot over the raw
        insert tail.

        The TAIL IS READ FIRST — the mirror of the seal path's write
        order (freeze → coded append → publish clears pending): a
        reader that misses the rows in pending can only do so after
        the coded append, which its later list scan then sees. The
        tolerated transient is a duplicate sighting, deduped below."""
        tparts = []
        mids, mvecs = self.store.mutable.view()
        if mids.shape[0]:
            tparts.append((mids, mvecs))
        pending = self.store.pending
        if pending is not None and pending.rows:
            tparts.append(pending.view())
        cids, cscores = self._batcher.run(q, (int(k), int(nprobe)))
        if not tparts:
            return cids, cscores
        tid = np.concatenate([np.asarray(i) for i, _ in tparts])
        tvec = np.concatenate([np.asarray(v) for _, v in tparts])
        tsc = q @ tvec.T  # exact: the tail is RAM-resident anyway
        nq = q.shape[0]
        out_ids = np.full((nq, k), -1, np.int64)
        out_scores = np.full((nq, k), -np.inf, np.float32)
        for i in range(nq):
            keep = cids[i] >= 0
            ids_cat = np.concatenate([cids[i][keep], tid])
            sc_cat = np.concatenate([cscores[i][keep], tsc[i]])
            # Dedup (seal-window double sighting): scores are exact on
            # both sides, so either copy of an id is the right one.
            uniq, first = np.unique(ids_cat, return_index=True)
            sc_u = sc_cat[first]
            kk = min(k, uniq.shape[0])
            top = np.argpartition(sc_u, -kk)[-kk:]
            top = top[np.argsort(sc_u[top])[::-1]]
            out_ids[i, :kk] = uniq[top]
            out_scores[i, :kk] = sc_u[top]
        return out_ids, out_scores

    def _ann_search(self, q: np.ndarray, k: int,
                    nprobe: int | None) -> tuple[np.ndarray,
                                                 np.ndarray]:
        """The structure-dispatch core ``search`` and the recall probe
        share (the probe must exercise the REAL ANN path, without the
        client-traffic telemetry)."""
        eff = self.nprobe if nprobe is None else int(nprobe)
        coded = self._coded
        if coded is not None:
            return self._search_coded(q, k, eff)
        ivf = self._ivf
        if ivf is None:
            ids, vecs = self.store.all_rows()
            return brute_force_topk(q, ids, vecs, k)
        return ivf.search(q, k, eff)

    def search(self, queries, k: int = 10,
               nprobe: int | None = None) -> tuple[np.ndarray,
                                                   np.ndarray]:
        """Top-k ``(ids [Q,k], scores [Q,k])``; brute force until
        trained, then the fused coded scan (or IVF-flat when PQ is
        disabled). Missing slots carry id -1; returned scores are
        exact inner products on every path (the PQ approximation only
        selects candidates).

        LOCK-FREE: searches take no lock at all — under concurrent
        insert+query a shared lock convoys with the GIL and measured
        as a ~50 ms search p99 (vs a sub-ms p50). Safety comes from
        the single-writer discipline (``_lock`` serializes all
        mutation) plus write ordering: every append writes row data
        BEFORE bumping the visible count, and buffer growth copies the
        committed prefix before the pointer swap — so any interleaving
        of attribute reads yields a valid prefix of committed rows,
        never torn data. A search may simply miss rows committed after
        it started, which is the semantics a concurrent reader expects
        anyway. (The coded path's batcher holds its own condition
        variable purely to COALESCE concurrent scans — a waiter rides
        a leader's pass instead of contending for memory bandwidth.)"""
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None]
        t0 = time.monotonic()
        out = self._ann_search(q, k, nprobe)
        if self.metrics is not None:
            self.metrics.searches.inc(int(q.shape[0]))
            self.metrics.latency["search"].observe(
                (time.monotonic() - t0) * 1e3)
        return out

    def search_exact(self, queries, k: int = 10) -> tuple[np.ndarray,
                                                          np.ndarray]:
        """Brute-force top-k regardless of training state (the recall
        probe's ground truth). Lock-free like ``search``."""
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None]
        ids, vecs = self.store.all_rows()
        return brute_force_topk(q, ids, vecs, k)

    def recall_probe(self, k: int = 10, sample: int = 32,
                     seed: int = 1) -> float | None:
        """recall@k of ANN search vs brute force on ``sample`` stored
        rows; None below 2k rows (nothing meaningful to probe). Updates
        the gauge."""
        ids, vecs = self.store.all_rows()
        n = vecs.shape[0]
        if n < 2 * k:
            return None
        rng = np.random.RandomState(seed)
        pick = rng.choice(n, size=min(int(sample), n), replace=False)
        q = np.asarray(vecs[pick], np.float32)
        # Bypass ``search``'s metrics: synthetic probe queries must
        # not inflate retrieval_searches_total or the stage=search
        # latency series a dashboard reads as client traffic. (The
        # scan-bytes counters DO tick — they meter bytes genuinely
        # touched, whoever touched them.)
        ann_ids, _ = self._ann_search(q, k, None)
        exact_ids, _ = brute_force_topk(q, ids, vecs, k)
        hit = sum(len(set(a.tolist()) & set(e.tolist()))
                  for a, e in zip(ann_ids, exact_ids))
        recall = hit / float(exact_ids.shape[0] * k)
        if self.metrics is not None:
            self.metrics.recall.set(recall)
        return recall

    # -- maintenance -------------------------------------------------------
    def maintain(self, heavy: bool = True) -> bool:
        """One maintenance pass: train at threshold, seal past
        ``seal_rows``, compact past ``compact_at``. Returns True when
        anything happened (the manager's thread backs off when idle).

        ``heavy=False`` defers the deferrable: compaction (a full
        rewrite of every sealed byte). Training and sealing always run
        — the first gates search quality, the second bounds the
        mutable tail — so the autoscaler's idle gate (ISSUE 17
        satellite) can push the IO-heavy work into quiet windows
        without ever compromising correctness.

        TWO-PHASE under ``_maint_lock``: every copy/IO-heavy step
        (k-means, the freeze's fsyncs, the compaction merge) runs
        OUTSIDE the index lock, which is held only for pointer swaps —
        the cost of background upkeep must never appear as a search
        p99 spike. Searches keep answering throughout: brute force
        while centroids train, the pending tail stays visible while a
        seal's bytes hit disk, old segments serve until the merged one
        swaps in."""
        did = False
        with self._maint_lock:
            if self.retired:
                # Replaced by a rebuild/rollback: no further segment
                # writes — the manager may be deleting our directory.
                return False
            # 1) training cut: k-means AND the full list build run
            #    outside the index lock over a bounded snapshot
            #    (sealed + pending + the mutable tail's first n0
            #    rows — all stable here: only this _maint_lock-
            #    serialized pass seals/compacts, and lock-free reads
            #    of committed prefixes are safe by the view
            #    discipline). Under the lock only the DELTA rows that
            #    arrived mid-training are added before the publish —
            #    a full in-lock build at a large train_rows was
            #    exactly the search-stall this two-phase contract
            #    forbids.
            if not self.trained:
                mut0 = self.store.mutable
                n0 = mut0.rows
                parts = [s.view() if hasattr(s, "view")
                         else (s.ids, s.vectors)
                         for s in list(self.store.sealed)]
                pending = self.store.pending
                if pending is not None and pending.rows:
                    parts.append(pending.view())
                mids0, mvecs0 = mut0.view()
                parts.append((mids0[:n0], mvecs0[:n0]))
                ids1 = np.concatenate([np.asarray(i)
                                       for i, _ in parts])
                vecs1 = np.concatenate([np.asarray(v)
                                        for _, v in parts])
                if ids1.shape[0] >= self.train_rows \
                        and self.pq_m > 0:
                    # The coded cut: IVF centroids + PQ codebooks in
                    # one pass, then the coded lists over every sealed
                    # segment. The raw tail (incl. any rows that land
                    # mid-training) stays exact-scanned until it
                    # seals, so no delta bookkeeping is needed here.
                    k = min(self.n_centroids, max(1, vecs1.shape[0]))
                    centroids = kmeans(vecs1, k, seed=self.seed)
                    stride = max(1,
                                 vecs1.shape[0] // self.pq_train_rows)
                    sample = vecs1[::stride][: self.pq_train_rows]
                    codec = PQCodec(self.dim, m=self.pq_m,
                                    ksub=self.pq_ksub, seed=self.seed)
                    codec.train(sample, opq_iters=self.opq_iters)
                    self._install_coded(centroids, codec)
                    if self.store.root is not None:
                        # Snapshot the trained state (same atomic
                        # idiom as the segments): a restart reopens a
                        # trained index instead of re-clustering.
                        codec.save(self.store.root)
                        _save_state(self.store.root, centroids)
                    did = True
                    _events.emit("index", action="build",
                                 step=self.step,
                                 rows=int(ids1.shape[0]),
                                 centroids=int(k),
                                 nprobe=self.nprobe,
                                 pq_m=codec.m, pq_ksub=codec.ksub,
                                 codec_gen=codec.gen)
                    _events.emit("index", action="pq_train",
                                 step=self.step,
                                 rows=int(sample.shape[0]),
                                 pq_m=codec.m, pq_ksub=codec.ksub,
                                 codec_gen=codec.gen,
                                 opq=self.opq_iters > 0)
                    if self.metrics is not None:
                        self.metrics.op("build")
                        self.metrics.op("pq_train")
                    logger.info("retrieval: trained %d centroids + "
                                "PQ m=%d/ksub=%d over %d rows "
                                "(step %s)", k, codec.m, codec.ksub,
                                ids1.shape[0], self.step)
                elif ids1.shape[0] >= self.train_rows:
                    k = min(self.n_centroids, max(1, vecs1.shape[0]))
                    centroids = kmeans(vecs1, k, seed=self.seed)
                    ivf = IVFIndex(centroids)
                    ivf.add(ids1, vecs1)
                    with self._lock:
                        # Only maintain swaps the mutable tail, and we
                        # ARE maintain — the identity check is a
                        # safety net, not an expected path.
                        if self.store.mutable is mut0:
                            mids, mvecs = mut0.view()
                            if mids.shape[0] > n0:
                                ivf.add(mids[n0:], mvecs[n0:])
                            self._ivf = ivf
                            trained_rows = int(
                                ids1.shape[0]
                                + max(0, mids.shape[0] - n0))
                        else:  # pragma: no cover — retry next pass
                            trained_rows = None
                    if trained_rows is not None:
                        did = True
                        _events.emit("index", action="build",
                                     step=self.step,
                                     rows=trained_rows,
                                     centroids=int(k),
                                     nprobe=self.nprobe)
                        if self.metrics is not None:
                            self.metrics.op("build")
                        logger.info("retrieval: trained %d centroids "
                                    "over %d rows (step %s)", k,
                                    trained_rows, self.step)
            # 2) seal: pointer-take under the lock, freeze (disk or
            #    in-memory trim) outside, publish under the lock. With
            #    the coded plane live the freshly sealed rows enter
            #    the coded lists BEFORE pending clears — a lock-free
            #    reader that misses them in pending finds them in the
            #    lists (the dup-sighting transient ``_search_coded``
            #    dedupes), never in neither.
            frozen = None
            with self._lock:
                if self.store.should_seal():
                    frozen = self.store.take_mutable()
            if frozen is not None and frozen.rows:
                seg = self.store.freeze(frozen)
                coded = self._coded
                if coded is not None:
                    src = coded.add_source(seg.vectors)
                    self._append_segment_coded(coded, seg, src)
                    self._src_meta.append((seg.name, 0))
                with self._lock:
                    self.store.publish(seg)
                did = True
                _events.emit("index", action="seal", step=self.step,
                             segment=seg.name, rows=seg.rows,
                             coded=coded is not None)
                if self.metrics is not None:
                    self.metrics.op("seal")
            # 3) compact (deferrable: a full rewrite of every sealed
            #    byte): merge outside the lock, rebase the coded
            #    sources onto row-aligned slices of the merged mmap
            #    (pointer swaps — not one locator is touched, and the
            #    sidecar concat in ``merge`` means no re-encode
            #    either), swap in, delete the inputs after no reader
            #    can pick them up.
            olds = None
            if heavy:
                with self._lock:
                    if self.store.should_compact():
                        olds = list(self.store.sealed)
            if olds:
                merged = self.store.merge(olds)
                coded = self._coded
                if coded is not None:
                    offsets: dict[str, int] = {}
                    off = 0
                    for s in olds:
                        offsets[s.name] = off
                        off += s.rows
                    for i, (name, start) in enumerate(self._src_meta):
                        if name not in offsets:
                            continue
                        base = offsets[name] + start
                        ln = int(coded.sources[i].shape[0])
                        coded.replace_source(
                            i, merged.vectors[base: base + ln])
                        self._src_meta[i] = (merged.name, base)
                with self._lock:
                    self.store.swap_sealed(olds, merged)
                self.store.delete_segments(olds)
                did = True
                _events.emit("index", action="compact", step=self.step,
                             segment=merged.name, rows=merged.rows)
                if self.metrics is not None:
                    self.metrics.op("compact")
        return did
