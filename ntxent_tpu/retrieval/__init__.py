"""Retrieval tier: a versioned ANN index over served embeddings.

The fleet computes embeddings at high QPS and, until ISSUE 15, threw
every one of them away. This package keeps them searchable: IVF-flat
ANN over memory-mapped append-only segments (``index``/``segments``/
``ivf``), with index VERSIONS keyed to checkpoint steps and driven by
the router's rollout state machine (``versioned`` — promote cuts
searches to the new step's index, rollback restores the prior one, a
shadow-drift breach marks it stale and forces rebuild). The router
surfaces it as ``POST /search`` (serving/router.py).

ISSUE 17 adds the memory-bound scale plane: product quantization
(``pq`` — 8-byte codes + ADC tables + exact re-rank), the fused
batched-gather scan over code lists (``scan`` — probe inversion, one
list pass per batch), and a sharded index plane (``shard`` — IVF lists
partitioned across HTTP shard workers; the router fans /search out and
merges; a dead shard degrades recall, never availability). Durable
state (docstore log + centroid/codebook snapshots) lives in
``versioned``/``index``/``pq`` so a restart reopens trained.

ISSUE 20 makes the shard plane self-healing: rendezvous-hashed list
placement with live rebalancing, checkpoint-step plane versions wired
to the rollout state machine, and a durable per-shard insert journal
(``journal``) whose repair loop redelivers every row a dead shard
missed — degraded briefly, then healed.

JAX-free at import by construction: numpy + stdlib only. The
import-boundary lint (``LintConfig.boundary_roots``) and the runtime
tripwire (tests/test_fleet.py) both enforce it — search must never pay
backend-init latency or hold an accelerator.
"""

from .index import RetrievalMetrics, VectorIndex
from .ivf import IVFIndex, brute_force_topk, kmeans
from .journal import ShardJournal
from .pq import PQCodec
from .scan import CodedLists, ScanBatcher, batched_scan
from .segments import MutableSegment, SealedSegment, SegmentStore
from .shard import (IndexShard, ShardClient, ShardFanout, ShardServer,
                    shard_owner)
from .versioned import IndexManager

__all__ = [
    "CodedLists",
    "IndexManager",
    "IndexShard",
    "IVFIndex",
    "MutableSegment",
    "PQCodec",
    "RetrievalMetrics",
    "ScanBatcher",
    "SealedSegment",
    "SegmentStore",
    "ShardClient",
    "ShardFanout",
    "ShardJournal",
    "ShardServer",
    "VectorIndex",
    "batched_scan",
    "brute_force_topk",
    "kmeans",
    "shard_owner",
]
