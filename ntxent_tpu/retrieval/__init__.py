"""Retrieval tier: a versioned ANN index over served embeddings.

The fleet computes embeddings at high QPS and, until ISSUE 15, threw
every one of them away. This package keeps them searchable: IVF-flat
ANN over memory-mapped append-only segments (``index``/``segments``/
``ivf``), with index VERSIONS keyed to checkpoint steps and driven by
the router's rollout state machine (``versioned`` — promote cuts
searches to the new step's index, rollback restores the prior one, a
shadow-drift breach marks it stale and forces rebuild). The router
surfaces it as ``POST /search`` (serving/router.py).

JAX-free at import by construction: numpy + stdlib only. The
import-boundary lint (``LintConfig.boundary_roots``) and the runtime
tripwire (tests/test_fleet.py) both enforce it — search must never pay
backend-init latency or hold an accelerator.
"""

from .index import RetrievalMetrics, VectorIndex
from .ivf import IVFIndex, brute_force_topk, kmeans
from .segments import MutableSegment, SealedSegment, SegmentStore
from .versioned import IndexManager

__all__ = [
    "IndexManager",
    "IVFIndex",
    "MutableSegment",
    "RetrievalMetrics",
    "SealedSegment",
    "SegmentStore",
    "VectorIndex",
    "brute_force_topk",
    "kmeans",
]
