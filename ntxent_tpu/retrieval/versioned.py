"""Checkpoint-step-versioned index manager: the rollout coupling.

The shadow-drift machinery (ISSUE 10) exists because two checkpoints'
embedding SPACES diverge — which means an ANN index built over one
model's embeddings silently answers wrong under another. So index
versions here are keyed to checkpoint steps, and the router's rollout
state machine drives the version lifecycle (ISSUE 15):

* **adopt** — the first trusted step gets the first (empty) version;
* **promote** — searches CUT OVER atomically to a fresh version keyed
  to the newly trusted step; the prior version is retained (that is
  what a rollback restores) and the new one is rebuilt in the
  background by re-embedding the retained input rows through the now-
  trusted fleet (``set_reembed`` installs the router's forward path);
* **rollback** — the fleet reverted to an older checkpoint: the prior
  step's version is restored ATOMICALLY (same dict-pointer swap as the
  promote cut) with its vectors intact, so post-rollback searches
  answer from the space the workers actually serve again;
* **stale** — a shadow-drift breach is direct evidence the spaces
  moved; the active version is flagged stale (gauge + typed event) and
  a rebuild is forced. Until the rebuild lands, searches still answer
  (an old answer beats a 503) but carry ``stale: true`` so callers can
  tell.

Inputs, not embeddings, are what survive a model change (the cache-
warming lesson from ISSUE 9) — the manager retains up to
``docstore_rows`` inserted INPUT rows keyed by their assigned ids, and
that docstore is the rebuild source. Past the bound the oldest rows
are evicted (counted; a rebuild then covers the retained tail only —
logged, never silent).

The docstore is DURABLE when the manager is rooted (ISSUE 17): rows
append to ``docstore.log`` (flushed per insert, fsync'd on maintenance
ticks and ``stop``), evictions advance a watermark in
``docstore.json``, and the log compacts by the same stage-fsync-rename
idiom as the segments once dead records outgrow live ones. Together
with the per-version codec/centroid snapshots (index.py) a restarted
router reopens a TRAINED index with its rebuild source intact —
zero re-clustering, zero re-embedding.

JAX-free like everything under ``retrieval/``: the lint boundary and
the fleet tripwire both pin it.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
import time
import uuid
from collections import OrderedDict
from pathlib import Path

import numpy as np

from ..obs import events as _events
from ..obs.registry import MetricsRegistry
from .index import RetrievalMetrics, VectorIndex
from .segments import _fsync_path

logger = logging.getLogger(__name__)

__all__ = ["IndexManager"]

_DOC_LOG = "docstore.log"
_DOC_META = "docstore.json"
# One log record: id, ndim, ndim int32 dims, then the f32 payload.
_REC_HEAD = struct.Struct("<qB")


class IndexManager:
    """Versioned retrieval tier: one ``VectorIndex`` per trusted
    checkpoint step, one active at a time.

    ``root`` (optional) gives each version a ``step-<N>/`` segment
    directory; None keeps every version in memory. ``index_kw`` passes
    through to ``VectorIndex`` (train_rows/n_centroids/nprobe/
    seal_rows/compact_at).
    """

    def __init__(self, dim: int | None = None, root=None,
                 registry: MetricsRegistry | None = None,
                 docstore_rows: int = 65536,
                 keep_versions: int = 2,
                 maintain_interval_s: float = 2.0,
                 **index_kw):
        # ``dim=None`` defers to the first inserted embedding's width —
        # the router tier is JAX-free and cannot ask the model; until
        # then versions are registered as placeholders (searches answer
        # empty) and materialize on first insert.
        self.dim = int(dim) if dim is not None else None
        self.root = root
        self.docstore_rows = max(1, int(docstore_rows))
        self.keep_versions = max(1, int(keep_versions))
        self.maintain_interval_s = float(maintain_interval_s)
        self.index_kw = dict(index_kw)
        self.metrics = RetrievalMetrics(registry)
        self._lock = threading.Lock()
        self._versions: OrderedDict[int, VectorIndex] = OrderedDict()
        self._active_step: int | None = None
        self._prior_step: int | None = None
        self._stale_reason: str | None = None
        self._next_id = 0
        # id -> input row (np.float32), insertion-ordered for eviction.
        self._docstore: OrderedDict[int, np.ndarray] = OrderedDict()
        # Durable docstore state (rooted managers only): the open
        # append handle, the eviction watermark (smallest retained
        # id), and the dead-record count that triggers log compaction.
        self._doc_f = None
        self._doc_watermark = 0
        self._doc_dead = 0
        # Compaction only pays off past a floor of dead records — a
        # tiny store must not rewrite its log every few evictions.
        self._doc_compact_floor = 1024
        # Installed by the router: fn(inputs [N, ...]) -> embeddings
        # [N, dim] or None on failure. Called on the rebuild thread.
        self.reembed = None
        # Installed by the fleet plane (ISSUE 17 satellite): a
        # callable -> bool consulted per maintenance tick. False
        # defers the DEFERRABLE work (compaction, docstore log
        # compaction) to an idle window — the autoscaler's idle
        # detector is the intended source. Bounded: after
        # ``heavy_defer_ticks`` consecutive deferrals the work runs
        # anyway (a permanently busy fleet must not grow segments
        # forever).
        self.heavy_gate = None
        self.heavy_defer_ticks = 30
        self._heavy_deferred = 0
        self._rebuild_thread: threading.Thread | None = None
        self._maint_thread: threading.Thread | None = None
        self._stop = threading.Event()
        # Replaced/retired index instances awaiting directory cleanup.
        self._orphans: list = []
        # Row count at the last recall probe: the probe materializes
        # every vector, so an idle index must not pay it per tick.
        self._last_probe_rows = -1
        if self.root is not None:
            self._reopen()
            self._reopen_docstore()

    def _reopen(self) -> None:
        """Adopt prior runs' persisted segments (``--index-dir`` must
        not be write-only): per step, the newest non-empty ``g-*``
        instance directory reopens as that step's version (dim read
        from its segment metadata, ids resumed past the persisted
        maximum so new inserts can never collide); every other
        ``g-*`` dir is a crash/replacement orphan and is deleted —
        without this, restarts leaked every prior instance's segments
        forever. The docstore replays separately
        (``_reopen_docstore``), so a post-restart rebuild covers the
        retained input rows, not just newly inserted ones."""
        import json as _json
        import os
        import shutil

        def _gen_dim(gen_path: str):
            """``("empty", None)`` for a segment-less dir (per-run
            debris — every instance mkdir's its root), ``("ok", dim)``
            when every segment's metadata agrees, ``("unreadable",
            None)`` on any read/parse failure — which must NEVER be
            grounds for deletion (a transient IO error or one corrupt
            meta must not amplify into losing the generation's healthy
            segments; SegmentStore skips bad segments the same way)."""
            try:
                segs = [s for s in os.listdir(gen_path)
                        if s.startswith("seg-")]
            except OSError:
                return "unreadable", None
            if not segs:
                return "empty", None
            try:
                dims = {
                    int(_json.load(open(
                        os.path.join(gen_path, seg, "meta.json")))
                        ["dim"])
                    for seg in segs
                }
            except (OSError, ValueError, KeyError, TypeError):
                return "unreadable", None
            if len(dims) != 1:
                return "unreadable", None
            return "ok", dims.pop()

        root = str(self.root)
        try:
            listing = os.listdir(root)
        except OSError:
            return
        steps: list[tuple[int, str]] = []
        for d in listing:
            if not d.startswith("step-"):
                continue
            try:
                steps.append((int(d.split("-", 1)[1]), d))
            except ValueError:
                continue
        max_id = -1
        adoptions: list[tuple[int, VectorIndex]] = []
        # NEWEST step first: the manager's dim comes from the newest
        # persisted space, so after an embedding-width change across
        # runs the obsolete OLD-dim steps are what gets dropped —
        # oldest-first resolution would pin the stale dim and delete
        # the newest, correct-space data as a "mismatch".
        for step, d in sorted(steps, reverse=True):
            step_path = os.path.join(root, d)
            try:
                gens = sorted((g for g in os.listdir(step_path)
                               if g.startswith("g-")),
                              key=lambda g: os.path.getmtime(
                                  os.path.join(step_path, g)),
                              reverse=True)  # newest first
            except OSError:
                continue
            adopted = False
            for g in gens:
                gen_path = os.path.join(step_path, g)
                verdict, dim = _gen_dim(gen_path)
                if verdict == "unreadable":
                    # Skip, never delete: not adoptable today, but a
                    # single bad meta.json must not destroy the
                    # generation's healthy segments.
                    logger.warning("retrieval: unreadable segment "
                                   "metadata under %s — left on disk, "
                                   "not adopted", gen_path)
                    continue
                if not adopted and verdict == "ok" \
                        and self.dim in (None, dim):
                    self.dim = dim
                    idx = VectorIndex(dim, step=step, root=gen_path,
                                      metrics=self.metrics,
                                      **self.index_kw)
                    if idx.rows:
                        adoptions.append((step, idx))
                        adopted = True
                        for ids_arr, _ in idx.store.blocks():
                            if len(ids_arr):
                                max_id = max(max_id,
                                             int(np.max(ids_arr)))
                        logger.info("retrieval: reopened step-%d "
                                    "index (%d rows) from %s", step,
                                    idx.rows, gen_path)
                        continue
                # Superseded generation, per-run empty debris, or an
                # obsolete-dim space (dim resolved newest-first, so
                # this can never be the newest data): delete, or every
                # restart leaks it.
                shutil.rmtree(gen_path, ignore_errors=True)
        # Register ASCENDING: the OrderedDict's insertion order is what
        # retention evicts from (oldest first) — newest-first
        # registration would make retention destroy the newest version.
        for step, idx in sorted(adoptions, key=lambda si: si[0]):
            self._versions[step] = idx
        self._next_id = max_id + 1

    # -- durable docstore --------------------------------------------------
    def _reopen_docstore(self) -> None:
        """Replay ``docstore.log`` into the in-memory docstore and open
        it for append. Records below the persisted watermark (already
        evicted) are skipped; a truncated tail (crash mid-append) is
        dropped AND truncated off the file — appending past garbage
        would poison every future replay at the same offset. Ids resume
        past the persisted maximum so restarts never re-issue one."""
        root = Path(str(self.root))
        try:
            root.mkdir(parents=True, exist_ok=True)
        except OSError:
            return
        watermark = 0
        try:
            watermark = int(json.loads(
                (root / _DOC_META).read_text()).get("watermark", 0))
        except (OSError, ValueError, TypeError, AttributeError):
            watermark = 0
        log_p = root / _DOC_LOG
        try:
            blob = log_p.read_bytes()
        except OSError:
            blob = b""
        off, n = 0, len(blob)
        replayed = dead = 0
        while off + _REC_HEAD.size <= n:
            rid, ndim = _REC_HEAD.unpack_from(blob, off)
            dims_end = off + _REC_HEAD.size + 4 * ndim
            if ndim == 0 or dims_end > n:
                break
            dims = np.frombuffer(blob, np.int32, ndim,
                                 off + _REC_HEAD.size)
            count = int(np.prod(dims))
            rec_end = dims_end + 4 * count
            if count <= 0 or rec_end > n:
                break
            if rid >= watermark:
                # Ids are monotonic and the log is append-ordered, so
                # plain assignment preserves eviction order.
                self._docstore[rid] = np.frombuffer(
                    blob, np.float32, count, dims_end).reshape(
                        tuple(int(d) for d in dims)).copy()
                replayed += 1
            else:
                dead += 1
            off = rec_end
        if off < n:
            logger.warning("retrieval: docstore.log truncated tail "
                           "dropped (%d byte(s))", n - off)
            try:
                with open(log_p, "r+b") as f:
                    f.truncate(off)
                    f.flush()
                    os.fsync(f.fileno())
            except OSError:
                pass
        while len(self._docstore) > self.docstore_rows:
            self._docstore.popitem(last=False)
            dead += 1
        self._doc_watermark = next(iter(self._docstore)) \
            if self._docstore else watermark
        self._doc_dead = dead
        if self._docstore:
            self._next_id = max(self._next_id,
                                max(self._docstore) + 1)
        try:
            self._doc_f = open(log_p, "ab")
        except OSError:
            self._doc_f = None
        if replayed:
            self.metrics.op("docstore_replay")
            _events.emit("index", action="docstore_replay",
                         rows=replayed, dead=dead)
            logger.info("retrieval: docstore replayed %d row(s) "
                        "(%d dead) from %s", replayed, dead, log_p)

    def _doc_append(self, ids, rows) -> None:
        """Append rows to the log (flushed, not fsync'd — maintenance
        ticks and ``stop`` pay the fsync). Callers hold ``_lock``, so
        appends serialize and stay id-ordered."""
        if self._doc_f is None:
            return
        try:
            buf = bytearray()
            for i, row in zip(ids, rows):
                r = np.ascontiguousarray(row, np.float32)
                buf += _REC_HEAD.pack(int(i), r.ndim)
                buf += np.asarray(r.shape, np.int32).tobytes()
                buf += r.tobytes()
            self._doc_f.write(bytes(buf))
            self._doc_f.flush()
        except (OSError, ValueError):
            logger.exception("retrieval: docstore append failed — "
                             "rows stay in memory only")

    def _doc_sync(self) -> None:
        f = self._doc_f
        if f is None:
            return
        try:
            f.flush()
            os.fsync(f.fileno())
        except (OSError, ValueError):
            pass

    def _write_doc_meta(self, watermark: int) -> None:
        root = Path(str(self.root))
        tmp = root / f".{_DOC_META}.tmp-{uuid.uuid4().hex[:8]}"
        try:
            tmp.write_text(json.dumps({"watermark": int(watermark)}))
            with open(tmp, "rb") as f:
                os.fsync(f.fileno())
            os.replace(tmp, root / _DOC_META)
            _fsync_path(root)
        except OSError:
            logger.exception("retrieval: docstore watermark write "
                             "failed")
            try:
                tmp.unlink()
            except OSError:
                pass

    def _doc_compact(self) -> None:
        """Rewrite the log with only the live rows (stage-fsync-rename,
        same idiom as the segments) and persist the watermark. Holds
        ``_lock`` for the rewrite so no insert can append to the handle
        being swapped out — the hold is bounded by ``docstore_rows``
        worth of sequential writes."""
        if self.root is None:
            return
        root = Path(str(self.root))
        tmp = root / f".{_DOC_LOG}.tmp-{uuid.uuid4().hex[:8]}"
        with self._lock:
            try:
                with open(tmp, "wb") as f:
                    for i, row in self._docstore.items():
                        r = np.ascontiguousarray(row, np.float32)
                        f.write(_REC_HEAD.pack(int(i), r.ndim))
                        f.write(np.asarray(r.shape,
                                           np.int32).tobytes())
                        f.write(r.tobytes())
                    f.flush()
                    os.fsync(f.fileno())
                if self._doc_f is not None:
                    try:
                        self._doc_f.close()
                    except OSError:
                        pass
                os.replace(tmp, root / _DOC_LOG)
                _fsync_path(root)
                self._doc_f = open(root / _DOC_LOG, "ab")
                self._doc_dead = 0
                watermark = next(iter(self._docstore)) \
                    if self._docstore else self._next_id
                self._doc_watermark = watermark
                rows = len(self._docstore)
            except OSError:
                logger.exception("retrieval: docstore compaction "
                                 "failed")
                try:
                    tmp.unlink()
                except OSError:
                    pass
                return
        self._write_doc_meta(watermark)
        self.metrics.op("docstore_compact")
        _events.emit("index", action="docstore_compact", rows=rows)
        logger.info("retrieval: docstore log compacted to %d live "
                    "row(s)", rows)

    # -- version plumbing --------------------------------------------------
    def _index_root(self, step: int):
        """A FRESH directory per index instance (``step-N/g-<nonce>``):
        a rebuild of step N must never reopen the old instance's sealed
        segments — those hold the stale-space vectors the rebuild
        exists to replace, and two instances sharing one directory
        would collide on segment names. The docstore is the rebuild
        source of truth; orphaned instance dirs are deleted by
        ``_drop_index`` once no version points at them."""
        if self.root is None:
            return None
        import os
        import uuid

        return os.path.join(str(self.root), f"step-{int(step)}",
                            f"g-{uuid.uuid4().hex[:8]}")

    def _new_index(self, step: int) -> VectorIndex:
        assert self.dim is not None
        return VectorIndex(self.dim, step=step,
                           root=self._index_root(step),
                           metrics=self.metrics, **self.index_kw)

    @staticmethod
    def _drop_index(idx: VectorIndex | None) -> None:
        """Delete a replaced/retired instance's segment directory.
        In-flight searches on the old instance keep answering — their
        np.memmaps hold the inodes (POSIX unlink semantics). The
        retire-then-barrier handshake closes the seal race: without
        it, a maintenance pass mid-seal on the old instance would
        mkdir+rename the deleted directory BACK into existence, and a
        restart's ``_reopen`` would adopt that resurrected stale-space
        segment as the step's newest generation."""
        if idx is None:
            return
        idx.retired = True
        if idx.store.root is None:
            return
        import shutil

        with idx._maint_lock:
            # Barrier: any in-flight maintain() finishes its writes;
            # retired blocks all future ones.
            pass
        shutil.rmtree(idx.store.root, ignore_errors=True)

    def _ensure_locked(self, step: int) -> VectorIndex | None:
        """Register (and, once ``dim`` is known, materialize) the
        version for ``step``; None while the dim is still unknown.
        Retention-dropped instances land in ``_orphans`` — the caller
        deletes their directories OUTSIDE the lock."""
        idx = self._versions.get(step)
        if idx is None:
            if self.dim is not None:
                idx = self._new_index(step)
            self._versions[step] = idx
            self._versions.move_to_end(step)
            while len(self._versions) > self.keep_versions + 1:
                old_step, old = self._versions.popitem(last=False)
                self._orphans.append(old)
                logger.info("retrieval: dropped index version for "
                            "step %d (retention)", old_step)
        return idx

    def _drain_orphans(self) -> None:
        """Delete retired instances' segment dirs (never under the
        lock — an rmtree must not stall version resolution)."""
        while self._orphans:
            self._drop_index(self._orphans.pop())

    @property
    def active_step(self) -> int | None:
        return self._active_step

    @property
    def stale(self) -> bool:
        return self._stale_reason is not None

    def version(self, step: int) -> VectorIndex | None:
        with self._lock:
            return self._versions.get(step)

    def active(self) -> VectorIndex | None:
        with self._lock:
            if self._active_step is None:
                return None
            return self._versions.get(self._active_step)

    # -- rollout hooks (the router's WorkerPool decisions) -----------------
    def activate(self, step: int) -> None:
        """First trusted adoption: version ``step`` becomes active."""
        step = int(step)
        with self._lock:
            if self._active_step == step:
                return
            self._ensure_locked(step)
            self._prior_step = self._active_step
            self._active_step = step
        self._drain_orphans()
        _events.emit("index", action="activate", step=step)
        self.publish()

    def promote(self, step: int) -> None:
        """Canary promote: cut searches to ``step``'s version (created
        empty if absent) and kick a background rebuild from the
        docstore. The prior version is RETAINED for rollback."""
        step = int(step)
        with self._lock:
            prior = self._active_step
            self._ensure_locked(step)
            self._prior_step = prior
            self._active_step = step
            self._stale_reason = None
        self._drain_orphans()
        self.metrics.op("promote")
        _events.emit("index", action="promote", step=step,
                     prior_step=prior)
        logger.info("retrieval: index cut over to step %d (prior %s "
                    "retained for rollback)", step, prior)
        self.publish()
        self.rebuild_async(reason="promote")

    def rollback_to(self, step: int) -> bool:
        """The fleet reverted: restore ``step``'s retained version
        atomically. Returns False when that version was not retained
        (a fresh empty one is activated instead — still the correct
        space, just cold)."""
        step = int(step)
        with self._lock:
            had = self._versions.get(step) is not None
            self._ensure_locked(step)
            self._prior_step = self._active_step
            self._active_step = step
            self._stale_reason = None
        self._drain_orphans()
        self.metrics.op("rollback")
        _events.emit("index", action="rollback", step=step,
                     retained=had)
        logger.warning("retrieval: index rolled back to step %d "
                       "(%s)", step,
                       "retained version restored" if had
                       else "version not retained — rebuilt cold")
        self.publish()
        if not had:
            self.rebuild_async(reason="rollback_cold")
        return had

    def on_canary_rollback(self, bad_step: int, reason: str) -> None:
        """A canary breached before promotion: its candidate version
        (if one was warmed) is dropped; a DRIFT-reason breach is direct
        evidence the embedding spaces moved, so the live index is
        marked stale and a rebuild is forced."""
        with self._lock:
            dropped = self._versions.pop(int(bad_step), None) \
                if int(bad_step) != self._active_step else None
        if dropped is not None:
            self._drop_index(dropped)
            _events.emit("index", action="drop", step=int(bad_step),
                         reason=reason)
        if reason == "shadow_drift":
            self.mark_stale(f"canary drift breach (step {bad_step})")

    def mark_stale(self, reason: str) -> None:
        """Flag the active index stale and force a rebuild."""
        with self._lock:
            if self._active_step is None:
                return
            self._stale_reason = reason
        self.metrics.op("stale")
        _events.emit("index", action="stale",
                     step=self._active_step, reason=reason)
        logger.warning("retrieval: active index (step %s) marked "
                       "STALE: %s — forcing rebuild",
                       self._active_step, reason)
        self.publish()
        self.rebuild_async(reason="stale")

    # -- data path ---------------------------------------------------------
    def insert(self, inputs, vectors,
               step: int | None = None) -> list[int]:
        """Store input rows + their embeddings under the active
        version; returns assigned ids. ``step`` is the checkpoint step
        that PRODUCED the vectors — a mismatch with the active version
        rejects the insert (empty list): wrong-space vectors must
        never enter the index."""
        x = np.asarray(inputs, np.float32)
        if x.ndim == 1:
            x = x[None]
        vecs = np.asarray(vectors, np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None]
        with self._lock:
            if self.dim is None:
                self.dim = int(vecs.shape[1])
            elif int(vecs.shape[1]) != self.dim:
                # Wrong-width vectors (a worker serving a changed
                # --proj-dim, a foreign payload): rejected BEFORE any
                # state mutates — same graceful empty answer as a
                # wrong-step insert, never a ValueError escaping into
                # the router's handler thread.
                logger.warning("retrieval: insert rejected — dim %d "
                               "!= index dim %d", vecs.shape[1],
                               self.dim)
                return []
            if self._active_step is None and step is not None:
                # Inserts arriving before any rollout decision adopt
                # the producing step — an index must exist to be
                # versioned.
                self._ensure_locked(int(step))
                self._active_step = int(step)
            if self._active_step is None:
                if step is None:
                    # No versioning signal anywhere (stepless smoke
                    # fleets): a single unversioned index under step -1.
                    self._ensure_locked(-1)
                    self._active_step = -1
            elif step is not None and int(step) != self._active_step:
                return []
            idx = self._versions.get(self._active_step)
            if idx is None:
                # The version was registered before the dim was known
                # (activate/promote ahead of the first insert) —
                # materialize it now.
                idx = self._versions[self._active_step] = \
                    self._new_index(self._active_step)
            ids = list(range(self._next_id,
                             self._next_id + x.shape[0]))
            self._next_id += x.shape[0]
            for i, row in zip(ids, x):
                self._docstore[i] = np.array(row, np.float32)
            self._doc_append(ids, x)
            evicted = 0
            while len(self._docstore) > self.docstore_rows:
                self._docstore.popitem(last=False)
                evicted += 1
            if evicted:
                # Evicted rows become dead log records; the watermark
                # (smallest retained id) filters them out of a replay
                # even before the next compaction rewrites the log.
                self._doc_dead += evicted
                self._doc_watermark = next(iter(self._docstore)) \
                    if self._docstore else self._next_id
            # Under the lock: a rebuild's version swap racing this
            # insert would otherwise receive the rows into the
            # about-to-be-orphaned instance — 200 with ids that never
            # answer a search. The hold is the append cost (ms), and
            # searches only touch this lock for version resolution,
            # never for the scan.
            idx.insert(np.asarray(ids, np.int64), vecs)
        self._drain_orphans()
        if evicted:
            self.metrics.docstore_evictions.inc(evicted)
        self.publish()
        return ids

    def search(self, queries, k: int = 10,
               prefer_step: int | None = None) -> dict:
        """Search the version matching ``prefer_step`` (the step that
        embedded the queries) when retained, else the active version —
        query and index must share an embedding space, and during a
        rollout window a laggard worker's embeddings legitimately
        belong to the PRIOR version. Returns ``{ids, scores, step,
        stale, rows}``; ids/scores are lists (JSON-ready)."""
        with self._lock:
            step = self._active_step
            if prefer_step is not None \
                    and self._versions.get(int(prefer_step)) is not None:
                step = int(prefer_step)
            idx = self._versions.get(step) if step is not None else None
            stale = self._stale_reason is not None \
                and step == self._active_step
        if idx is None:
            return {"ids": [], "scores": [], "step": None,
                    "stale": False, "rows": 0}
        ids, scores = idx.search(queries, k)
        return {"ids": ids.tolist(),
                "scores": [[float(s) if np.isfinite(s) else None
                            for s in row] for row in scores],
                "step": step, "stale": stale, "rows": idx.rows}

    def docstore_inputs(self) -> tuple[list[int], np.ndarray | None]:
        """(ids, stacked input rows) currently retained for rebuild."""
        with self._lock:
            if not self._docstore:
                return [], None
            ids = list(self._docstore.keys())
            rows = np.stack([self._docstore[i] for i in ids])
        return ids, rows

    # -- rebuild -----------------------------------------------------------
    def rebuild_async(self, reason: str) -> bool:
        """Re-embed the docstore through ``reembed`` into a FRESH index
        for the active step on a background thread, then swap it in
        atomically. One rebuild at a time; returns False when skipped
        (no reembed fn, nothing stored, or one already running)."""
        if self.reembed is None:
            return False
        with self._lock:
            if not self._docstore or self._active_step is None:
                return False
            if self._rebuild_thread is not None \
                    and self._rebuild_thread.is_alive():
                return False
            self._rebuild_thread = threading.Thread(
                target=self._rebuild, args=(reason,), daemon=True,
                name="retrieval-rebuild")
            self._rebuild_thread.start()
        return True

    def _rebuild(self, reason: str) -> None:
        """One rebuild incarnation. Runs in passes: rows inserted
        while a pass was re-embedding land in the THEN-active instance
        (which the swap replaces) — but they are in the docstore, so
        the next pass replays them; the loop converges the moment a
        pass completes with no concurrent inserts (``_next_id``
        unmoved). Bounded: a pathological sustained-insert storm gets
        a loud warning instead of an unbounded loop."""
        t0 = time.monotonic()
        total_rows = 0
        for attempt in range(4):
            target_step = self._active_step
            with self._lock:
                next_id0 = self._next_id
            ids, rows = self.docstore_inputs()
            if rows is None or target_step is None:
                return
            vecs = None
            try:
                vecs = self.reembed(rows)
            except Exception:  # noqa: BLE001 — a rebuild failure
                # leaves the old (possibly stale) index serving; it
                # must never take down the router thread pool.
                logger.exception("retrieval: rebuild re-embedding "
                                 "failed")
            if vecs is None:
                logger.warning("retrieval: rebuild(%s) aborted — "
                               "re-embed returned nothing (old index "
                               "keeps serving)", reason)
                return
            vecs = np.asarray(vecs, np.float32)
            if vecs.ndim != 2 or int(vecs.shape[1]) != self.dim:
                # A changed embedding width mid-rebuild must abort
                # loudly, not kill the rebuild thread with a
                # ValueError out of fresh.insert.
                logger.warning("retrieval: rebuild(%s) aborted — "
                               "re-embedded width %s != index dim %d",
                               reason, getattr(vecs, "shape", "?"),
                               self.dim)
                return
            fresh = self._new_index(int(target_step))
            fresh.insert(np.asarray(ids, np.int64),
                         np.asarray(vecs, np.float32),
                         count_metrics=False)
            fresh.maintain()
            with self._lock:
                if self._active_step != target_step:
                    # A promote/rollback raced the rebuild: this
                    # result is for a version nobody serves — drop it.
                    logger.warning("retrieval: rebuild(%s) for step "
                                   "%d discarded (active moved to %s)",
                                   reason, target_step,
                                   self._active_step)
                    replaced, settled = fresh, True
                else:
                    replaced = self._versions.get(target_step)
                    self._versions[target_step] = fresh
                    self._stale_reason = None
                    total_rows = len(ids)
                    # Converged only if nothing was inserted while
                    # this pass re-embedded (those rows went to the
                    # instance just replaced).
                    settled = self._next_id == next_id0
            self._drop_index(replaced)
            if replaced is fresh:
                return
            if settled:
                break
        else:
            logger.warning("retrieval: rebuild(%s) still catching up "
                           "after %d passes (sustained inserts) — "
                           "rows inserted in the last pass arrive on "
                           "the next rebuild", reason, attempt + 1)
        self.metrics.op("rebuild")
        self.metrics.rebuilt_rows.inc(total_rows)
        _events.emit("index", action="rebuild",
                     step=int(self._active_step
                              if self._active_step is not None else -1),
                     rows=total_rows, reason=reason,
                     duration_ms=round((time.monotonic() - t0) * 1e3, 3))
        logger.info("retrieval: rebuilt step index from %d stored "
                    "row(s) (%s)", total_rows, reason)
        self.publish()

    def wait_rebuild(self, timeout_s: float = 30.0) -> bool:
        """Block until any in-flight rebuild finishes (tests/smokes)."""
        t = self._rebuild_thread
        if t is None:
            return True
        t.join(timeout_s)
        return not t.is_alive()

    # -- maintenance / publishing -----------------------------------------
    def maintain(self) -> bool:
        # Heavy work (segment compaction, docstore log compaction) is
        # deferrable: when the fleet plane installed ``heavy_gate`` and
        # it reports busy, defer — bounded by ``heavy_defer_ticks``, so
        # a permanently busy fleet still compacts eventually. Seals and
        # training are NOT gated: they bound the exact-scan tail and
        # must track the insert rate.
        heavy = True
        if self.heavy_gate is not None:
            try:
                idle = bool(self.heavy_gate())
            except Exception:  # noqa: BLE001 — a broken gate must not
                # stall maintenance forever.
                idle = True
            if idle:
                self._heavy_deferred = 0
            elif self._heavy_deferred < self.heavy_defer_ticks:
                self._heavy_deferred += 1
                heavy = False
                self.metrics.op("heavy_defer")
            else:
                logger.info("retrieval: heavy maintenance forced "
                            "through after %d deferred tick(s)",
                            self._heavy_deferred)
                self.metrics.op("heavy_forced")
                self._heavy_deferred = 0
        idx = self.active()
        did = idx.maintain(heavy=heavy) if idx is not None else False
        if idx is not None and idx.trained:
            # The probe materializes every stored vector for its
            # brute-force ground truth — neither an idle index nor a
            # steady insert stream may pay that per tick. Probe on the
            # first trained pass, then only when rows moved >= 10 %
            # (or shrank — a rebuild swapped the instance).
            rows = idx.rows
            last = self._last_probe_rows
            if last < 0 or rows < last \
                    or rows - last >= max(1, last // 10):
                idx.recall_probe()
                self._last_probe_rows = rows
        if heavy and self._doc_f is not None:
            self._doc_sync()
            with self._lock:
                live = len(self._docstore)
                dead = self._doc_dead
            if dead > max(live, self._doc_compact_floor):
                self._doc_compact()
        self.publish()
        return did

    def publish(self) -> None:
        """Refresh the active-version gauges."""
        with self._lock:
            step = self._active_step
            idx = self._versions.get(step) if step is not None else None
            n_versions = len(self._versions)
            stale = self._stale_reason is not None
            doc = len(self._docstore)
        m = self.metrics
        m.version.set(step if step is not None else -1)
        m.stale.set(1 if stale else 0)
        m.versions.set(n_versions)
        m.docstore_rows.set(doc)
        if idx is not None:
            m.rows.set(idx.rows)
            m.segments.set(idx.store.segment_count)
            m.index_bytes.set(idx.resident_bytes())
            m.bytes_per_row.set(idx.scan_bytes_per_row())

    def _maint_loop(self) -> None:
        while not self._stop.wait(self.maintain_interval_s):
            try:
                self.maintain()
            except Exception:  # noqa: BLE001 — background upkeep must
                # survive any single bad pass.
                logger.exception("retrieval: maintenance pass failed")

    def start(self) -> "IndexManager":
        if self._maint_thread is not None:
            raise RuntimeError("index manager already started")
        self._stop.clear()
        self._maint_thread = threading.Thread(
            target=self._maint_loop, daemon=True,
            name="retrieval-maintain")
        self._maint_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._maint_thread is not None:
            self._maint_thread.join(self.maintain_interval_s * 4 + 5.0)
            self._maint_thread = None
        # Close out the docstore log: fsync what the last flush left
        # in the page cache and persist the eviction watermark so the
        # next replay skips the dead prefix.
        if self._doc_f is not None:
            self._doc_sync()
            try:
                self._doc_f.close()
            except OSError:
                pass
            self._doc_f = None
            self._write_doc_meta(self._doc_watermark)

    def snapshot(self) -> dict:
        with self._lock:
            versions = {
                str(step): ({"rows": idx.rows, "trained": idx.trained,
                             "segments": idx.store.segment_count,
                             "bytes": int(idx.resident_bytes()),
                             "bytes_per_row":
                                 round(idx.scan_bytes_per_row(), 2),
                             "pq_m": (idx._codec.m
                                      if idx._codec is not None else 0),
                             "from_snapshot":
                                 bool(idx.trained_from_snapshot)}
                            if idx is not None
                            else {"rows": 0, "trained": False,
                                  "segments": 0})
                for step, idx in self._versions.items()
            }
            return {"active_step": self._active_step,
                    "prior_step": self._prior_step,
                    "stale": self._stale_reason,
                    "docstore_rows": len(self._docstore),
                    "docstore_durable": self._doc_f is not None,
                    "docstore_watermark": self._doc_watermark,
                    "next_id": self._next_id,
                    "versions": versions}
