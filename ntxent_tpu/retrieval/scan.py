"""Fused batched-gather scan over product-quantized IVF lists.

The DLRM embedding-bag paper's core observation (PAPERS.md): at scale
the lookup loop is memory-bandwidth-bound, so the win is touching each
byte once for MANY consumers, not computing faster on bytes touched
per-consumer. PR 14's hot loop was the per-query version — every query
re-walked its probed lists with its own small BLAS calls. This module
is the fused fix:

* ``CodedLists`` holds the PQ-coded inverted lists: per row, an int64
  id, ``m`` uint8 codes, and a ``(source, row)`` locator into a table
  of RAW float32 arrays (mmap'd sealed segments, or the live insert
  tail). The scan touches the codes; only re-rank survivors touch raw
  bytes. ``replace_source`` is the REBASE primitive — when a tail
  seals or segments compact, the owner swaps a RAM source for an mmap
  view (same rows, same order) without rewriting a single locator.

* ``batched_scan`` INVERTS the probe map: instead of "for each query,
  for each probed list", it groups queries by list and walks each
  list ONCE — one shared code-gather scoring every query that probed
  it (``m`` byte-gathers produce a ``[Qs, n]`` score block). ADC
  survivors are re-scored exactly from the raw sources, grouped by
  source so an mmap'd segment is gathered once per batch.

* ``ScanBatcher`` coalesces CONCURRENT callers with zero added
  latency: the first thread in becomes the leader and takes every
  compatible queued request; arrivals during a scan queue up and ride
  the next leader. Quiet traffic pays nothing; a burst fuses.

Lock-free reads ride the same count-before-buffers discipline as
``segments.MutableSegment`` — data lands before the visible count
bumps, growth copies the committed prefix before the pointer swap.
Numpy + stdlib only (import-boundary lint + fleet tripwire enforced).
"""

from __future__ import annotations

import threading

import numpy as np

__all__ = ["CodedLists", "ScanBatcher", "batched_scan"]


class _ListBuf:
    """One inverted list: parallel grow-buffers (ids, codes, source
    index, source row) with the lock-free view discipline."""

    __slots__ = ("m", "_ids", "_codes", "_src", "_row", "rows")

    def __init__(self, m: int, chunk_rows: int = 64):
        self.m = int(m)
        self._ids = np.empty((0,), np.int64)
        self._codes = np.empty((0, self.m), np.uint8)
        self._src = np.empty((0,), np.int32)
        self._row = np.empty((0,), np.int32)
        self.rows = 0

    def append(self, ids, codes, src, row) -> None:
        n = int(ids.shape[0])
        need = self.rows + n
        if need > self._ids.shape[0]:
            grow = max(need, int(self._ids.shape[0] * 1.5),
                       self._ids.shape[0] + 64)
            for name, dtype, shape in (("_ids", np.int64, (grow,)),
                                       ("_codes", np.uint8,
                                        (grow, self.m)),
                                       ("_src", np.int32, (grow,)),
                                       ("_row", np.int32, (grow,))):
                nb = np.empty(shape, dtype)
                nb[: self.rows] = getattr(self, name)[: self.rows]
                setattr(self, name, nb)
        self._ids[self.rows: need] = ids
        self._codes[self.rows: need] = codes
        self._src[self.rows: need] = src
        self._row[self.rows: need] = row
        self.rows = need

    def view(self):
        """``(ids, codes, src, row)`` committed-prefix snapshot —
        count read before buffers, same argument as
        ``MutableSegment.view``."""
        n = self.rows
        ids, codes = self._ids, self._codes
        src, row = self._src, self._row
        n = min(n, ids.shape[0], codes.shape[0], src.shape[0],
                row.shape[0])
        return ids[:n], codes[:n], src[:n], row[:n]


class CodedLists:
    """PQ-coded inverted lists + the raw-source table.

    Single-writer (the owning index serializes mutation under its
    lock); readers are lock-free. ``sources`` entries are float32
    ``[rows, dim]`` arrays — RAM for the live tail, mmap views for
    sealed segments; ``replace_source`` swaps one without touching
    locators (the replacement must hold the same rows in the same
    order, which is exactly what seal and compaction guarantee).
    """

    def __init__(self, centroids: np.ndarray, codec):
        self.centroids = np.ascontiguousarray(centroids, np.float32)
        self.codec = codec
        self._lists = [_ListBuf(codec.m)
                       for _ in range(self.centroids.shape[0])]
        self.sources: list[np.ndarray] = []

    @property
    def n_lists(self) -> int:
        return self.centroids.shape[0]

    @property
    def rows(self) -> int:
        return sum(lb.rows for lb in self._lists)

    def memory_bytes(self) -> int:
        """Committed bytes of the compact scan structure (ids + codes
        + locators) — what replaces raw-vector RAM residency."""
        per = 8 + self.codec.m + 4 + 4
        return self.rows * per

    # -- writes (owner-serialized) -------------------------------------------
    def add_source(self, vectors: np.ndarray) -> int:
        self.sources.append(vectors)
        return len(self.sources) - 1

    def replace_source(self, idx: int, vectors: np.ndarray) -> None:
        """Rebase locators onto a new backing array (seal: RAM tail ->
        mmap; compact: old mmap -> a row-aligned slice of the merged
        mmap). Pointer swap only — in-flight scans keep the old array
        alive and stay correct."""
        self.sources[idx] = vectors

    def append_assigned(self, assign: np.ndarray, ids: np.ndarray,
                        codes: np.ndarray, src: int,
                        rows: np.ndarray) -> None:
        """Append pre-assigned, pre-encoded rows: ``assign`` is the
        IVF list per row, ``rows`` the row index inside source
        ``src``. The caller must have made ``src`` cover the rows
        BEFORE appending (readers resolve locators immediately)."""
        ids = np.asarray(ids, np.int64)
        codes = np.asarray(codes, np.uint8)
        rows = np.asarray(rows, np.int32)
        src_arr = np.full(ids.shape[0], int(src), np.int32)
        for c in np.unique(assign):
            mask = assign == c
            self._lists[int(c)].append(ids[mask], codes[mask],
                                       src_arr[mask], rows[mask])

    def list_view(self, c: int) -> tuple[np.ndarray, np.ndarray,
                                         np.ndarray]:
        """``(ids, src, row)`` committed-prefix snapshot of one
        inverted list — the migration read: the raw vectors are
        ``sources[src][row]`` per element."""
        ids, _, src, row = self._lists[int(c)].view()
        return ids, src, row

    def drop_list(self, c: int) -> int:
        """Swap one list for an empty buffer (rebalance hand-off after
        the new owner acks). Pointer swap — in-flight scans keep the
        old buffer alive and stay consistent. Returns rows dropped."""
        old = self._lists[int(c)]
        n = old.rows
        self._lists[int(c)] = _ListBuf(self.codec.m)
        return n

    def assign(self, vectors: np.ndarray) -> np.ndarray:
        """Max-inner-product IVF list per row (same rule as
        ``ivf._nearest`` — unit-norm embeddings, dot == cosine)."""
        return np.argmax(np.asarray(vectors, np.float32)
                         @ self.centroids.T, axis=1)

    def add(self, ids: np.ndarray, vectors: np.ndarray, src: int,
            rows: np.ndarray) -> None:
        """Assign + encode + append in one step (the insert path)."""
        vecs = np.asarray(vectors, np.float32)
        self.append_assigned(self.assign(vecs), ids,
                             self.codec.encode(vecs), src, rows)


def _topk_rows(ids: np.ndarray, scores: np.ndarray, k: int,
               out_ids: np.ndarray, out_scores: np.ndarray) -> None:
    kk = min(k, ids.shape[0])
    if kk == 0:
        return
    top = np.argpartition(scores, -kk)[-kk:]
    top = top[np.argsort(scores[top])[::-1]]
    out_ids[:kk] = ids[top]
    out_scores[:kk] = scores[top]


def batched_scan(coded: CodedLists, queries: np.ndarray, k: int,
                 nprobe: int, rerank: int,
                 stats: dict | None = None) -> tuple[np.ndarray,
                                                     np.ndarray]:
    """Fused ANN top-k over the coded lists for a query BATCH.

    One pass per probed list shared by every query probing it: gather
    the list's codes once, score all those queries against them via
    their ADC tables (m byte-gathers -> a ``[Qs, n]`` block), then
    per query re-rank the ADC top-``rerank`` exactly from the raw
    sources. Returns ``(ids [Q,k], scores [Q,k])`` padded with
    -1/-inf; scores are EXACT inner products for every returned id
    (the PQ approximation only selects candidates).

    Widens like ``IVFIndex.search``: a query whose probed lists hold
    fewer than ``k`` rows re-scans every list, so short lists never
    short the answer. ``stats`` (optional dict) accumulates the
    memory-economy counters: ``code_bytes`` (unique code bytes
    gathered), ``rerank_bytes`` (raw bytes touched), ``rows_scored``
    (query-row pairs), ``list_passes``.
    """
    q = np.asarray(queries, np.float32)
    if q.ndim == 1:
        q = q[None]
    nq = q.shape[0]
    out_ids = np.full((nq, k), -1, np.int64)
    out_scores = np.full((nq, k), -np.inf, np.float32)
    if coded.rows == 0 or nq == 0:
        return out_ids, out_scores
    nprobe = max(1, min(int(nprobe), coded.n_lists))
    rerank = max(int(k), int(rerank))
    cs = q @ coded.centroids.T  # [Q, n_lists]
    if nprobe >= coded.n_lists:
        probe = np.tile(np.arange(coded.n_lists), (nq, 1))
    else:
        probe = np.argpartition(cs, -nprobe, axis=1)[:, -nprobe:]
    tables = coded.codec.adc_tables(q)  # [Q, m, ksub]
    m = coded.codec.m

    # Per-query candidate accumulators: references into shared list
    # views plus the owned ADC score rows — never a per-query copy of
    # ids/locators.
    cand: list[list] = [[] for _ in range(nq)]

    def _scan_lists(list_to_queries: dict[int, list[int]]) -> None:
        for c, qidx in list_to_queries.items():
            ids, codes, src, row = coded._lists[c].view()
            n = ids.shape[0]
            if n == 0:
                continue
            qi = np.asarray(qidx, np.int64)
            # THE fused gather+scan: one walk of this list's codes
            # scores every query that probed it — tables[qi, j] is
            # [Qs, ksub], the code gather broadcasts it to [Qs, n].
            scores = tables[qi, 0][:, codes[:, 0]].astype(
                np.float32, copy=True)
            for j in range(1, m):
                scores += tables[qi, j][:, codes[:, j]]
            for local, query in enumerate(qidx):
                cand[query].append((ids, scores[local], src, row))
            if stats is not None:
                stats["code_bytes"] = stats.get("code_bytes", 0) \
                    + n * m
                stats["rows_scored"] = stats.get("rows_scored", 0) \
                    + n * qi.shape[0]
                stats["list_passes"] = stats.get("list_passes", 0) + 1

    inverted: dict[int, list[int]] = {}
    for i in range(nq):
        for c in probe[i]:
            inverted.setdefault(int(c), []).append(i)
    _scan_lists(inverted)

    # Widen queries whose probed lists came up short (rare: barely
    # populated lists) — rescan the remaining lists for just them.
    if nprobe < coded.n_lists:
        widen: dict[int, list[int]] = {}
        for i in range(nq):
            if sum(t[0].shape[0] for t in cand[i]) < k:
                probed = set(int(c) for c in probe[i])
                for c in range(coded.n_lists):
                    if c not in probed:
                        widen.setdefault(c, []).append(i)
        if widen:
            _scan_lists(widen)

    rerank_bytes = 0
    for i in range(nq):
        parts = cand[i]
        if not parts:
            continue
        ids_cat = np.concatenate([p[0] for p in parts])
        adc_cat = np.concatenate([p[1] for p in parts])
        src_cat = np.concatenate([p[2] for p in parts])
        row_cat = np.concatenate([p[3] for p in parts])
        rr = min(rerank, ids_cat.shape[0])
        sel = np.argpartition(adc_cat, -rr)[-rr:] \
            if rr < ids_cat.shape[0] else np.arange(ids_cat.shape[0])
        # Exact re-rank: gather the survivors' raw rows, grouped by
        # source so each backing array (mmap page run) is touched in
        # one fancy-index gather.
        exact = np.empty(sel.shape[0], np.float32)
        s_sel, r_sel = src_cat[sel], row_cat[sel]
        for s in np.unique(s_sel):
            mask = s_sel == s
            raw = coded.sources[int(s)][r_sel[mask]]
            exact[mask] = np.asarray(raw, np.float32) @ q[i]
            rerank_bytes += int(raw.shape[0]) * int(raw.shape[1]) * 4
        _topk_rows(ids_cat[sel], exact, k, out_ids[i], out_scores[i])
    if stats is not None:
        stats["rerank_bytes"] = stats.get("rerank_bytes", 0) \
            + rerank_bytes
        stats["queries"] = stats.get("queries", 0) + nq
        stats["batches"] = stats.get("batches", 0) + 1
    return out_ids, out_scores


class ScanBatcher:
    """Leader-coalescing request batcher with ZERO idle latency.

    ``run(q, key)`` enqueues a query block; the first free thread
    becomes the leader, takes every queued block with the same
    ``key`` (search params must match to fuse), executes ``fn`` once
    over the stacked rows, and distributes the per-block slices.
    Requests arriving mid-scan queue and ride the next leader — under
    concurrency the fusion is automatic, when quiet a request runs
    immediately and alone. No timers, no added tail latency (a
    fixed coalescing window would tax the quiet path to help the
    busy one; the busy path batches by construction because scans
    serialize)."""

    def __init__(self, fn, max_batch: int = 256):
        # fn(stacked_queries, key) -> (ids [N,k], scores [N,k])
        self._fn = fn
        self.max_batch = max(1, int(max_batch))
        self._cond = threading.Condition()
        self._queue: list[list] = []  # [key, q, box]
        self._busy = False
        self.batches = 0
        self.fused_queries = 0

    def run(self, q: np.ndarray, key) -> tuple[np.ndarray, np.ndarray]:
        q = np.asarray(q, np.float32)
        if q.ndim == 1:
            q = q[None]
        box = {"done": False, "out": None, "err": None}
        entry = [key, q, box]
        with self._cond:
            self._queue.append(entry)
            while not box["done"] and self._busy:
                self._cond.wait()
            if box["done"]:
                if box["err"] is not None:
                    raise box["err"]
                return box["out"]
            # Leader: take every compatible queued request (ours
            # included) up to max_batch rows.
            take, rows = [], 0
            rest = []
            for item in self._queue:
                if item[0] == key and rows < self.max_batch:
                    take.append(item)
                    rows += item[1].shape[0]
                else:
                    rest.append(item)
            self._queue = rest
            self._busy = True
        try:
            stacked = np.concatenate([item[1] for item in take]) \
                if len(take) > 1 else take[0][1]
            out_ids, out_scores = self._fn(stacked, key)
            off = 0
            for item in take:
                n = item[1].shape[0]
                item[2]["out"] = (out_ids[off: off + n],
                                  out_scores[off: off + n])
                item[2]["done"] = True
                off += n
        except BaseException as e:  # noqa: BLE001 — every waiter in
            # the batch must be released with the failure, not hang.
            for item in take:
                if not item[2]["done"]:
                    item[2]["err"] = e
                    item[2]["done"] = True
            raise
        finally:
            with self._cond:
                self._busy = False
                self.batches += 1
                self.fused_queries += len(take)
                self._cond.notify_all()
        return box["out"]
