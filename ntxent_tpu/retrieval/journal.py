"""Durable per-shard insert journal: the write-ahead log that turns
"a dead shard drops rows forever" into "a dead shard delays rows".

Every batch the fan-out routes to a shard is appended HERE first
(flush-per-append), then pushed over HTTP. The journal keeps two
numbers per shard: the total rows ever appended and the acked prefix
the shard has confirmed. ``depth = total - acked`` is the repair debt
— rows that were routed while the owner was dead (or that a restarted
owner lost with its memory). A background repair replays the unacked
tail — or the FULL history, when the shard comes back empty — through
the fan-out's normal insert path, so redelivered rows re-route under
the CURRENT ring and version (a row whose list migrated lands on its
new owner; a row embedded under a rolled-back model version is dropped
at the trust gate, never replayed into the wrong plane).

File discipline is the docstore log's (``versioned.py``): one
append-only file per shard, flush on append, fsync on sync/compact,
torn-tail truncation on reopen, watermark meta via
tmp-fsync-``os.replace``, compaction by stage-fsync-rename. Replay is
idempotent end to end because ``IndexShard.insert`` dedups by id —
a crash between delivery and watermark write redelivers, never
duplicates.

Record format (one record per routed batch)::

    <qii>  version (int64, -1 = unversioned), n_rows, dim
    n_rows * int64   ids
    n_rows * dim * float32  vectors

``root=None`` gives the same API in memory (tests, journal-less
planes). Numpy + stdlib only — rides the retrieval import boundary.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
import uuid
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["ShardJournal"]

_HDR = struct.Struct("<qii")  # version, n_rows, dim
_MAX_ROWS = 10_000_000  # per-record sanity bound for replay
_MAX_DIM = 65_536


def _fsync_path(path) -> None:
    """Best-effort fsync of a file or directory (durability of the
    rename, not just the bytes)."""
    try:
        fd = os.open(str(path), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class _Log:
    """One shard's journal: file handle + counters. All mutation under
    the owning journal's lock."""

    __slots__ = ("path", "meta_path", "fh", "total_batches",
                 "total_rows", "acked_batches", "acked_rows", "mem",
                 "pending")

    def __init__(self, path: Path | None, meta_path: Path | None):
        self.path = path
        self.meta_path = meta_path
        self.fh = None
        self.total_batches = 0
        self.total_rows = 0
        self.acked_batches = 0
        self.acked_rows = 0
        # Delivered batches above the watermark (ordinal -> rows):
        # the watermark only advances over a CONTIGUOUS delivered
        # prefix, so a failed batch holds it (and the depth) until
        # repair redelivers the range.
        self.pending: dict[int, int] = {}
        # In-memory mode: list of (version, ids, vecs) batches.
        self.mem: list | None = [] if path is None else None

    def advance(self) -> None:
        while self.acked_batches in self.pending:
            self.acked_rows += self.pending.pop(self.acked_batches)
            self.acked_batches += 1


class ShardJournal:
    """Append-only per-shard insert WAL with an acked watermark.

    ``append`` before the HTTP push, ``ack`` on delivery, ``replay``
    to redeliver (tail or full history), ``compact`` to fold a long
    delivered history down to one live batch per shard.
    """

    def __init__(self, root: str | Path | None = None,
                 compact_rows: int = 100_000):
        self.root = Path(root) if root is not None else None
        self.compact_rows = max(1, int(compact_rows))
        self._lock = threading.Lock()
        self._logs: dict[int, _Log] = {}
        if self.root is not None:
            self.root.mkdir(parents=True, exist_ok=True)
            # Purge staged compactions that never renamed.
            for debris in self.root.glob(".tmp-*"):
                try:
                    debris.unlink()
                except OSError:
                    pass
            for p in sorted(self.root.glob("shard-*.log")):
                try:
                    sid = int(p.stem.split("-", 1)[1])
                except (IndexError, ValueError):
                    continue
                self._reopen(sid)

    # -- per-shard log plumbing ----------------------------------------------
    def _log(self, sid: int) -> _Log:
        log = self._logs.get(sid)
        if log is None:
            if self.root is None:
                log = _Log(None, None)
            else:
                log = _Log(self.root / f"shard-{sid}.log",
                           self.root / f"shard-{sid}.meta.json")
                log.fh = open(log.path, "ab")
            self._logs[sid] = log
        return log

    def _reopen(self, sid: int) -> None:
        """Replay an existing file: count intact records, truncate the
        torn tail (a kill mid-append leaves a partial record — the
        prefix is the truth), clamp the watermark to what survived."""
        log = _Log(self.root / f"shard-{sid}.log",
                   self.root / f"shard-{sid}.meta.json")
        acked_b = acked_r = 0
        try:
            meta = json.loads(log.meta_path.read_text())
            acked_b = int(meta.get("acked_batches", 0))
            acked_r = int(meta.get("acked_rows", 0))
        except (OSError, ValueError):
            pass
        with open(log.path, "r+b") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            off = batches = rows = 0
            while off + _HDR.size <= size:
                f.seek(off)
                _, n, d = _HDR.unpack(f.read(_HDR.size))
                end = off + _HDR.size + n * 8 + n * d * 4
                if (n <= 0 or n > _MAX_ROWS or d <= 0 or d > _MAX_DIM
                        or end > size):
                    break
                batches += 1
                rows += n
                off = end
            if off < size:
                logger.warning(
                    "shard journal %s: torn tail truncated at byte %d "
                    "(%d bytes dropped)", log.path, off, size - off)
                f.truncate(off)
                f.flush()
                os.fsync(f.fileno())
        log.total_batches, log.total_rows = batches, rows
        # A watermark ahead of the surviving records is impossible
        # (acks follow appends); behind is fine — replay redelivers
        # and the shard dedups by id.
        log.acked_batches = min(acked_b, batches)
        log.acked_rows = min(acked_r, rows)
        if log.acked_batches < acked_b:
            log.acked_rows = 0
            log.acked_batches = 0
        log.fh = open(log.path, "ab")
        self._logs[sid] = log

    def _write_meta(self, log: _Log) -> None:
        if log.meta_path is None:
            return
        tmp = log.meta_path.with_suffix(f".tmp-{uuid.uuid4().hex[:8]}")
        payload = json.dumps({"acked_batches": log.acked_batches,
                              "acked_rows": log.acked_rows})
        try:
            with open(tmp, "w") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, log.meta_path)
            _fsync_path(self.root)
        except OSError as e:
            logger.warning("shard journal meta write failed: %s", e)
            try:
                tmp.unlink()
            except OSError:
                pass

    # -- the WAL surface -----------------------------------------------------
    def append(self, sid: int, ids, vecs,
               version: int | None) -> int | None:
        """Journal one routed batch BEFORE the push. Returns the batch
        ordinal (the ``ack`` handle), or None when the disk write
        failed — the caller counts those rows as truly dropped."""
        ids = np.ascontiguousarray(ids, np.int64)
        vecs = np.ascontiguousarray(vecs, np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None]
        n, d = vecs.shape
        ver = -1 if version is None else int(version)
        with self._lock:
            log = self._log(sid)
            if n == 0:
                return log.total_batches
            if log.mem is not None:
                log.mem.append((ver, ids.copy(), vecs.copy()))
            else:
                try:
                    buf = bytearray(_HDR.pack(ver, n, d))
                    buf += ids.tobytes()
                    buf += vecs.tobytes()
                    log.fh.write(buf)
                    log.fh.flush()
                except OSError as e:
                    logger.error("shard journal append failed for "
                                 "shard %d: %s", sid, e)
                    return None
            ordinal = log.total_batches
            log.total_batches += 1
            log.total_rows += n
            return ordinal

    def ack(self, sid: int, ordinal: int, rows: int) -> None:
        """Confirm delivery of one appended batch. The watermark
        advances over the contiguous delivered prefix — a failed
        earlier batch holds it (and the depth) until repair redelivers
        the range (shard-side id dedup makes redelivery free)."""
        with self._lock:
            log = self._logs.get(sid)
            if log is None or ordinal < log.acked_batches:
                return
            log.pending[int(ordinal)] = int(rows)
            log.advance()

    def set_acked(self, sid: int, batches: int, rows: int) -> None:
        """Move the watermark to a replay snapshot boundary (every
        record below it was redelivered or version-dropped)."""
        with self._lock:
            log = self._logs.get(sid)
            if log is None:
                return
            if int(batches) > log.acked_batches:
                log.acked_batches = min(int(batches),
                                        log.total_batches)
                log.acked_rows = max(log.acked_rows,
                                     min(int(rows), log.total_rows))
            for done in [b for b in log.pending
                         if b < log.acked_batches]:
                del log.pending[done]
            log.advance()
            self._write_meta(log)

    def depth(self, sid: int) -> int:
        with self._lock:
            log = self._logs.get(sid)
            return 0 if log is None else log.total_rows - log.acked_rows

    def depths(self) -> dict:
        with self._lock:
            return {sid: log.total_rows - log.acked_rows
                    for sid, log in self._logs.items()}

    def totals(self, sid: int) -> tuple[int, int]:
        """(total_batches, total_rows) — the replay snapshot bound."""
        with self._lock:
            log = self._logs.get(sid)
            return (0, 0) if log is None else (log.total_batches,
                                               log.total_rows)

    def shards(self) -> list[int]:
        with self._lock:
            return sorted(self._logs)

    def replay(self, sid: int, from_start: bool = False,
               upto_batches: int | None = None):
        """Yield ``(version, ids, vecs)`` batches from the watermark
        (or from record 0 for a restarted-empty shard) up to a
        snapshot bound. Reads a private handle — appends during replay
        land past the bound and are untouched."""
        with self._lock:
            log = self._logs.get(sid)
            if log is None:
                return
            start = 0 if from_start else log.acked_batches
            stop = (log.total_batches if upto_batches is None
                    else min(int(upto_batches), log.total_batches))
            mem = None if log.mem is None else list(log.mem)
            path = log.path
        if mem is not None:
            for ver, ids, vecs in mem[start:stop]:
                yield (None if ver == -1 else ver), ids, vecs
            return
        with open(path, "rb") as f:
            for i in range(stop):
                head = f.read(_HDR.size)
                if len(head) < _HDR.size:
                    return
                ver, n, d = _HDR.unpack(head)
                body = f.read(n * 8 + n * d * 4)
                if len(body) < n * 8 + n * d * 4:
                    return
                if i < start:
                    continue
                ids = np.frombuffer(body[: n * 8], np.int64).copy()
                vecs = np.frombuffer(body[n * 8:], np.float32).reshape(
                    n, d).copy()
                yield (None if ver == -1 else ver), ids, vecs

    def maybe_compact(self, sid: int, live_version: int | None) -> bool:
        """When the delivered history has grown past ``compact_rows``,
        fold it: keep the LAST record per id at the live version (the
        row a full replay would leave standing), rewrite by
        stage-fsync-rename, watermark = everything. Only runs with a
        clean watermark (depth 0) — compacting an undelivered tail
        would launder the debt."""
        with self._lock:
            log = self._logs.get(sid)
            if (log is None or log.total_rows - log.acked_rows != 0
                    or log.total_rows <= self.compact_rows):
                return False
        live_ids: dict[int, np.ndarray] = {}
        dim = 0
        for ver, ids, vecs in self.replay(sid, from_start=True):
            if live_version is not None and ver != live_version:
                continue
            dim = vecs.shape[1]
            for j, rid in enumerate(ids.tolist()):
                live_ids[rid] = vecs[j]
        with self._lock:
            log = self._logs.get(sid)
            if log is None or log.total_rows != log.acked_rows:
                return False  # raced an append; next maintenance
            if live_ids:
                ids = np.fromiter(live_ids, np.int64,
                                  count=len(live_ids))
                vecs = np.stack([live_ids[i] for i in ids.tolist()]
                                ).astype(np.float32)
            else:
                ids = np.empty((0,), np.int64)
                vecs = np.empty((0, max(1, dim)), np.float32)
            n = int(ids.shape[0])
            if log.mem is not None:
                log.mem = ([] if n == 0
                           else [(-1 if live_version is None
                                  else int(live_version), ids, vecs)])
            else:
                tmp = self.root / f".tmp-{uuid.uuid4().hex[:8]}"
                try:
                    with open(tmp, "wb") as f:
                        if n:
                            ver = (-1 if live_version is None
                                   else int(live_version))
                            f.write(_HDR.pack(ver, n, vecs.shape[1]))
                            f.write(ids.tobytes())
                            f.write(vecs.tobytes())
                        f.flush()
                        os.fsync(f.fileno())
                    log.fh.close()
                    os.rename(tmp, log.path)
                    _fsync_path(self.root)
                    log.fh = open(log.path, "ab")
                except OSError as e:
                    logger.warning("shard journal compact failed: %s",
                                   e)
                    try:
                        tmp.unlink()
                    except OSError:
                        pass
                    log.fh = open(log.path, "ab")
                    return False
            log.total_batches = log.acked_batches = 1 if n else 0
            log.total_rows = log.acked_rows = n
            log.pending.clear()
            self._write_meta(log)
        logger.info("shard journal %d compacted to %d live row(s)",
                    sid, n)
        return True

    def sync(self) -> None:
        """fsync every log + persist watermarks (maintenance cadence —
        appends only flush)."""
        with self._lock:
            for log in self._logs.values():
                if log.fh is not None:
                    try:
                        log.fh.flush()
                        os.fsync(log.fh.fileno())
                    except OSError:
                        pass
                self._write_meta(log)

    def close(self) -> None:
        self.sync()
        with self._lock:
            for log in self._logs.values():
                if log.fh is not None:
                    try:
                        log.fh.close()
                    except OSError:
                        pass
                    log.fh = None
