"""Sharded index plane: IVF lists partitioned across worker processes.

At millions of rows a single process's scan is bounded by one memory
bus. The shard plane splits the CODED structure, not the query: each
IVF list has one owning shard (rendezvous hashing — see
``shard_owner``), every shard keeps the full centroid table, and a
query probes the same global top-``nprobe`` lists on EVERY shard —
shard ``s`` contributes exactly the probed lists it holds, so the
union across shards equals the unsharded probe set row-for-row.
Scores are exact re-ranked inner products (scan.py), hence directly
comparable, and the router-side merge is a per-query top-k that
dedups by id (a list mid-migration briefly lives on two shards; the
duplicate carries the identical exact score). Three consequences:

* recall is IDENTICAL to the unsharded index when every shard answers
  (same candidate rows, same exact scores);
* a dead shard subtracts only the rows of the lists it holds — the
  merge runs over whoever answered, the response carries
  ``shards: {ok, total, degraded}``, and availability never depends
  on any single shard. Degraded recall, never a 503;
* changing ``n_shards`` N→N±1 moves only ~1/N of the lists
  (``ShardFanout.rebalance`` streams each moving list row-by-row
  under a two-phase cutover — no re-clustering, ever).

The plane is VERSIONED: every shard carries the checkpoint-step-keyed
generation of the index it serves (the ``retrieval/versioned.py``
contract), echoes it on every response, and retains ONE prior
generation so a rollout rollback restores the previous plane without
a rebuild. The fan-out stamps inserts with the plane version and
rejects search responses from a shard on the wrong generation —
mixed-model neighbors across shards are impossible by construction.

Dropped rows are REPAIRED, not counted: every routed batch lands in a
durable per-shard journal (``journal.py``) before the push; a dead or
version-drifted shard's debt drains back through the normal insert
path when it returns (``repair_tick``), and a shard that comes back
EMPTY (restart) is resurrected from its full journal history.

Training stays CENTRAL: the coordinator (``ShardFanout``) buffers the
first ``train_rows`` inserts, fits IVF centroids + the PQ codec once,
pushes both to every shard (``POST /shard/init``), then flushes the
buffered rows to their owners. Until that point searches brute-force
the coordinator's buffer — cold behavior matches ``VectorIndex``.

Wire format: vectors ride as base64 float32 blobs (``_pack``), ~3x
denser than JSON float lists and loss-free. Everything here is numpy
+ stdlib (http.server / urllib) — the retrieval import boundary and
the fleet tripwire both pin that no jax sneaks in. ``main()`` is the
subprocess entry (``python -m ntxent_tpu.retrieval.shard``) so shard
workers ride the PR 8 supervision path: port-file handshake,
``/readyz`` probe, SIGTERM-clean shutdown.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import signal
import socket
import sys
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .ivf import brute_force_topk, kmeans
from .journal import ShardJournal
from .pq import PQCodec
from .scan import CodedLists, batched_scan

logger = logging.getLogger(__name__)

__all__ = ["IndexShard", "ShardClient", "ShardFanout", "ShardServer",
           "main", "shard_owner"]

_MAX_BODY = 64 * 1024 * 1024  # b64 f32 rows are bulky; cap, don't trust


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — the avalanche mixing both rendezvous
    keys ride. Pure uint64 numpy, wraps mod 2^64 by construction."""
    x = np.asarray(x, np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def shard_owner(lists: np.ndarray, n_shards: int) -> np.ndarray:
    """IVF list -> owning shard via rendezvous (HRW) hashing.

    Owner = argmax over shards of ``mix(mix(list) ^ mix(shard))``:
    deterministic, derivable anywhere from ``(list, n_shards)`` with
    no ring state to replicate, and — the property the old ``c % N``
    placement lacked — growing or shrinking the plane by one shard
    remaps only ~1/N of the lists (each list's argmax survives unless
    the new shard wins it), so a rebalance streams a fraction of the
    rows instead of rebuilding the plane.
    """
    arr = np.asarray(lists, np.int64)
    n = int(n_shards)
    if n <= 1:
        return np.zeros(np.shape(arr), np.int64)
    with np.errstate(over="ignore"):
        lk = _mix64(arr.astype(np.uint64)
                    + np.uint64(0x9E3779B97F4A7C15))
        sk = _mix64((np.arange(1, n + 1, dtype=np.uint64))
                    * np.uint64(0xD1B54A32D192ED03))
        w = _mix64(lk[..., None] ^ sk)
    return np.argmax(w, axis=-1).astype(np.int64)


def _pack(arr: np.ndarray) -> dict:
    a = np.ascontiguousarray(arr, np.float32)
    return {"shape": list(a.shape),
            "f32": base64.b64encode(a.tobytes()).decode("ascii")}


def _unpack(obj: dict) -> np.ndarray:
    shape = tuple(int(s) for s in obj["shape"])
    raw = base64.b64decode(obj["f32"])
    return np.frombuffer(raw, np.float32).reshape(shape).copy()


class _Gen:
    """One plane generation on one shard: the coded lists, the raw
    re-rank buffer backing them, the version stamp, and the id-dedup
    set that makes journal replay idempotent."""

    __slots__ = ("step", "coded", "raw", "raw_rows", "seen")

    def __init__(self, dim: int, step: int | None = None):
        self.step = step
        self.coded: CodedLists | None = None
        self.raw = np.empty((0, dim), np.float32)
        self.raw_rows = 0
        self.seen: set[int] = set()


class IndexShard:
    """One worker's slice of the plane: the coded lists it owns plus a
    raw grow-buffer source for exact re-rank — now two-generational.

    Single-writer per shard (the HTTP handler serializes under
    ``_lock``); searches ride the lock-free coded-list views. ``cut``
    retains the current generation and opens a fresh empty one at the
    new step (same trained structure — versions share centroids);
    ``rollback`` swaps the retained generation back. Rows for lists
    this shard does NOT own under the current ring are rejected
    loudly; rows whose id the generation already holds are skipped
    silently (replay idempotency).
    """

    def __init__(self, dim: int, shard_id: int = 0, n_shards: int = 1):
        self.dim = int(dim)
        self.shard_id = int(shard_id)
        self.n_shards = max(1, int(n_shards))
        self._lock = threading.Lock()
        self._gen = _Gen(self.dim)
        self._retained: _Gen | None = None
        self.nprobe = 8
        self.misrouted = 0
        self.duplicates = 0

    @property
    def trained(self) -> bool:
        return self._gen.coded is not None

    @property
    def rows(self) -> int:
        coded = self._gen.coded
        return coded.rows if coded is not None else 0

    @property
    def version(self) -> int | None:
        return self._gen.step

    def init_plane(self, centroids: np.ndarray, codec: PQCodec,
                   shard_id: int, n_shards: int, nprobe: int = 8,
                   step: int | None = None) -> None:
        """Install the centrally trained structure. Re-init replaces
        the current generation wholesale (a retrain invalidates old
        codes); in-flight searches keep the old arrays alive and stay
        consistent. The retained generation is dropped too — a
        re-init is a new plane, not a cut."""
        with self._lock:
            self.shard_id = int(shard_id)
            self.n_shards = max(1, int(n_shards))
            self.nprobe = max(1, int(nprobe))
            gen = _Gen(self.dim,
                       None if step is None else int(step))
            coded = CodedLists(centroids, codec)
            coded.add_source(gen.raw)  # source 0: the raw grow buffer
            gen.coded = coded
            self._gen = gen
            self._retained = None

    def set_ring(self, n_shards: int,
                 shard_id: int | None = None) -> None:
        """Adopt a new ring size (rebalance phase 1). Ownership checks
        switch immediately; lists this shard no longer owns keep
        serving reads until the new owner acks them (``drop_list``)."""
        with self._lock:
            self.n_shards = max(1, int(n_shards))
            if shard_id is not None:
                self.shard_id = int(shard_id)

    def cut(self, step: int) -> int | None:
        """Open a fresh empty generation at ``step``, retaining the
        current one for rollback. Same centroids/codec — a version cut
        changes which MODEL's vectors the plane holds, not the trained
        scan structure. No-op when already at ``step``."""
        step = int(step)
        with self._lock:
            if self._gen.step == step:
                return self._gen.step
            retained = self._gen
            gen = _Gen(self.dim, step)
            if retained.coded is not None:
                coded = CodedLists(retained.coded.centroids,
                                   retained.coded.codec)
                coded.add_source(gen.raw)
                gen.coded = coded
            self._retained = retained
            self._gen = gen
            return gen.step

    def rollback(self, step: int) -> bool:
        """Restore the retained generation when it carries ``step``
        (True). A shard restarted since the cut has nothing to restore
        — it reports False and the fan-out resurrects it from the
        journal instead."""
        step = int(step)
        with self._lock:
            if self._gen.step == step:
                return True
            if (self._retained is not None
                    and self._retained.step == step):
                self._gen, self._retained = self._retained, self._gen
                return True
            # Cold at the target version: keep the trained structure
            # (if any) but start empty — journal replay refills.
            retained = self._gen
            gen = _Gen(self.dim, step)
            if retained.coded is not None:
                coded = CodedLists(retained.coded.centroids,
                                   retained.coded.codec)
                coded.add_source(gen.raw)
                gen.coded = coded
            self._retained = retained
            self._gen = gen
            return False

    def insert(self, ids: np.ndarray, vectors: np.ndarray) -> int:
        """Index owned rows; returns how many were accepted (dedup
        skips don't count — they are already served)."""
        vecs = np.asarray(vectors, np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None]
        ids = np.asarray(ids, np.int64)
        with self._lock:
            gen = self._gen
            coded = gen.coded
            if coded is None:
                raise RuntimeError("shard not initialized")
            assign = coded.assign(vecs)
            owned = shard_owner(assign, self.n_shards) == self.shard_id
            if not bool(np.all(owned)):
                self.misrouted += int((~owned).sum())
                logger.warning("shard %d: %d misrouted row(s) rejected",
                               self.shard_id, int((~owned).sum()))
                vecs, ids = vecs[owned], ids[owned]
                assign = assign[owned]
            if ids.shape[0]:
                fresh = np.fromiter((int(i) not in gen.seen
                                     for i in ids), bool,
                                    count=ids.shape[0])
                ndup = int((~fresh).sum())
                if ndup:
                    self.duplicates += ndup
                    vecs, ids = vecs[fresh], ids[fresh]
                    assign = assign[fresh]
            n = vecs.shape[0]
            if not n:
                return 0
            need = gen.raw_rows + n
            if need > gen.raw.shape[0]:
                grow = max(need, int(gen.raw.shape[0] * 1.5),
                           gen.raw.shape[0] + 1024)
                nb = np.empty((grow, self.dim), np.float32)
                nb[: gen.raw_rows] = gen.raw[: gen.raw_rows]
                gen.raw = nb
                # Locators live in the coded lists; rebase them onto
                # the grown array BEFORE the new rows publish.
                coded.replace_source(0, gen.raw)
            start = gen.raw_rows
            gen.raw[start: need] = vecs
            gen.raw_rows = need
            coded.append_assigned(
                assign, ids, coded.codec.encode(vecs), 0,
                np.arange(start, need, dtype=np.int32))
            gen.seen.update(int(i) for i in ids)
            return n

    def search(self, queries: np.ndarray, k: int,
               nprobe: int | None = None) -> tuple[np.ndarray,
                                                   np.ndarray]:
        coded = self._gen.coded
        if coded is None or coded.rows == 0:
            q = np.asarray(queries, np.float32)
            nq = q.shape[0] if q.ndim > 1 else 1
            return (np.full((nq, k), -1, np.int64),
                    np.full((nq, k), -np.inf, np.float32))
        return batched_scan(coded, queries, int(k),
                            int(nprobe or self.nprobe),
                            rerank=max(512, 4 * int(k)))

    def extract_list(self, c: int) -> tuple[np.ndarray, np.ndarray]:
        """``(ids, vectors)`` snapshot of one inverted list — the
        migration read (the list keeps serving until ``drop_list``)."""
        with self._lock:
            coded = self._gen.coded
            if coded is None:
                return (np.empty((0,), np.int64),
                        np.empty((0, self.dim), np.float32))
            ids, _, row = coded.list_view(int(c))
            return ids.copy(), self._gen.raw[row.astype(np.int64)].copy()

    def drop_list(self, c: int) -> int:
        """Release one list after the new owner acked it. The raw
        buffer keeps the bytes (compaction is a coordinator-side
        concern); the ids leave the dedup set so a migrate-back can
        re-insert them."""
        with self._lock:
            coded = self._gen.coded
            if coded is None:
                return 0
            ids, _, _ = coded.list_view(int(c))
            self._gen.seen.difference_update(int(i) for i in ids)
            return coded.drop_list(int(c))


class ShardServer:
    """Stdlib HTTP front end over one ``IndexShard``.

    ``POST /shard/init`` installs centroids+codec (+ ring + version),
    ``/shard/insert`` indexes owned rows (version-gated),
    ``/shard/search`` answers ``{ids, scores, version}``;
    ``/shard/cut``, ``/shard/rollback``, ``/shard/ring``,
    ``/shard/extract``, ``/shard/drop_list`` drive the lifecycle; GET
    ``/healthz`` reports liveness+rows+version and ``/readyz`` is the
    supervision probe (the ``ServingFleet`` port-file protocol). One
    process per shard in production (``main()``); tests run several
    in-process."""

    def __init__(self, dim: int, host: str = "127.0.0.1",
                 port: int = 0):
        self.shard = IndexShard(dim)
        self.host, self.port = host, int(port)
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ShardServer":
        shard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802 — stdlib name
                pass

            def _reply(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_GET(self):  # noqa: N802
                s = shard.shard
                if self.path == "/healthz":
                    self._reply(200, {"ok": True, "rows": s.rows,
                                      "trained": s.trained,
                                      "shard": s.shard_id,
                                      "version": s.version,
                                      "misrouted": s.misrouted,
                                      "duplicates": s.duplicates})
                elif self.path == "/readyz":
                    # Supervision probe: ready as soon as the socket
                    # answers — an untrained shard is JOINABLE (the
                    # fan-out inits it), which is what ready means.
                    self._reply(200, {"ok": True, "shard": s.shard_id,
                                      "version": s.version,
                                      "checkpoint_step": s.version})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    if n > _MAX_BODY:
                        self._reply(413, {"error": "body too large"})
                        return
                    req = json.loads(self.rfile.read(n) or b"{}")
                    s = shard.shard
                    if self.path == "/shard/init":
                        step = req.get("step")
                        s.init_plane(
                            _unpack(req["centroids"]),
                            PQCodec.from_wire(req["codec"]),
                            int(req["shard_id"]),
                            int(req["n_shards"]),
                            int(req.get("nprobe", 8)),
                            None if step is None else int(step))
                        self._reply(200, {"ok": True,
                                          "version": s.version})
                    elif self.path == "/shard/insert":
                        want = req.get("version")
                        if want != s.version:
                            # Wrong plane generation: refusing keeps a
                            # lagging shard from serving another
                            # model's vectors; the fan-out journals
                            # the rows and resyncs us.
                            self._reply(200, {"stored": 0,
                                              "version_mismatch": True,
                                              "version": s.version})
                            return
                        before = s.misrouted
                        took = s.insert(
                            np.asarray(req["ids"], np.int64),
                            _unpack(req["vectors"]))
                        # `rejected` lets the fan-out tell a silent
                        # ring disagreement (rows dropped, must NOT
                        # ack) from a dedup skip (rows already served,
                        # safe to ack).
                        self._reply(200, {"stored": took,
                                          "rejected": int(s.misrouted
                                                          - before),
                                          "rows": s.rows,
                                          "version": s.version})
                    elif self.path == "/shard/search":
                        ids, scores = s.search(
                            _unpack(req["queries"]),
                            int(req.get("k", 10)),
                            req.get("nprobe"))
                        self._reply(200, {
                            "ids": ids.tolist(),
                            "scores": [[float(v) if np.isfinite(v)
                                        else None for v in row]
                                       for row in scores],
                            "version": s.version})
                    elif self.path == "/shard/cut":
                        ver = s.cut(int(req["step"]))
                        self._reply(200, {"ok": True, "version": ver})
                    elif self.path == "/shard/rollback":
                        restored = s.rollback(int(req["step"]))
                        self._reply(200, {"ok": True,
                                          "restored": restored,
                                          "version": s.version,
                                          "rows": s.rows})
                    elif self.path == "/shard/ring":
                        s.set_ring(int(req["n_shards"]),
                                   req.get("shard_id"))
                        self._reply(200, {"ok": True})
                    elif self.path == "/shard/extract":
                        ids, vecs = s.extract_list(int(req["list"]))
                        self._reply(200, {"ids": ids.tolist(),
                                          "vectors": _pack(vecs),
                                          "rows": int(ids.shape[0])})
                    elif self.path == "/shard/drop_list":
                        dropped = s.drop_list(int(req["list"]))
                        self._reply(200, {"dropped": dropped})
                    else:
                        self._reply(404, {"error": "not found"})
                except Exception as e:  # noqa: BLE001 — a bad payload
                    # must answer 400, never drop the connection.
                    self._reply(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"shard-{self.port}")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None


def _is_timeout(e: Exception) -> bool:
    if isinstance(e, TimeoutError):  # socket.timeout is an alias
        return True
    if isinstance(e, urllib.error.URLError):
        reason = getattr(e, "reason", None)
        if isinstance(reason, (TimeoutError, socket.timeout)):
            return True
        return "timed out" in str(reason).lower()
    return False


class ShardClient:
    """One shard endpoint with failure memory — now failure-MODE
    aware. A connect-refused shard (process gone) cools down for the
    full ``cooldown_s``; a TIMED-OUT shard (alive but paused — GC, a
    SIGSTOP lag fault) gets ``timeout_cooldown_s`` plus ONE free retry
    on the next call, so a transient stall doesn't bench a healthy
    shard for the long window. After the free retry also fails, the
    short cooldown holds until expiry."""

    def __init__(self, url: str, timeout_s: float = 5.0,
                 cooldown_s: float = 2.0,
                 timeout_cooldown_s: float = 0.25):
        self.url = url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.cooldown_s = float(cooldown_s)
        self.timeout_cooldown_s = float(timeout_cooldown_s)
        self._dead_until = 0.0
        self._retry_pass = False
        self.failures = 0
        self.timeouts = 0

    @property
    def available(self) -> bool:
        return self._retry_pass or time.monotonic() >= self._dead_until

    def call(self, path: str, payload: dict | None = None,
             timeout_s: float | None = None,
             force: bool = False) -> dict | None:
        """POST (or GET when ``payload`` is None); None on any
        transport/HTTP failure — the caller degrades, never raises.
        ``force`` skips the cooldown gate (the repair loop's probe —
        cooldowns protect the query hot path, not a 1 Hz healer)."""
        if not force and not self.available:
            return None
        retrying = (self._retry_pass
                    and time.monotonic() < self._dead_until)
        self._retry_pass = False
        try:
            if payload is None:
                req = urllib.request.Request(self.url + path)
            else:
                req = urllib.request.Request(
                    self.url + path,
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(
                    req, timeout=timeout_s or self.timeout_s) as resp:
                out = json.loads(resp.read())
            self._dead_until = 0.0
            return out
        except (urllib.error.URLError, OSError, ValueError) as e:
            self.failures += 1
            if _is_timeout(e):
                self.timeouts += 1
                self._dead_until = (time.monotonic()
                                    + self.timeout_cooldown_s)
                # One free retry — unless THIS call was it.
                self._retry_pass = not retrying
                logger.warning("shard %s timed out — short cooldown "
                               "%.2fs%s", self.url,
                               self.timeout_cooldown_s,
                               "" if retrying
                               else " (one retry allowed)")
            else:
                self._dead_until = time.monotonic() + self.cooldown_s
                self._retry_pass = False
                logger.warning("shard %s unreachable (%s) — cooling "
                               "down %.1fs", self.url, e,
                               self.cooldown_s)
            return None


class ShardFanout:
    """Coordinator: central training, owner-routed WAL-backed inserts,
    merged fan-out searches, plane-wide version lifecycle, journal
    repair, and live rebalancing.

    ``registry`` (optional MetricsRegistry) exports the plane's
    health: alive/total gauges, per-shard ``retrieval_shard_up``
    gauges (the anomaly detector's shard-death signal), degraded and
    version-mismatch counters, and the journal's depth/journaled/
    repaired set — the difference between "recall quietly sagged" and
    a page."""

    def __init__(self, urls, dim: int | None = None,
                 train_rows: int = 4096, n_centroids: int = 64,
                 nprobe: int = 8, pq_m: int = 8,
                 registry=None, seed: int = 0,
                 timeout_s: float = 5.0,
                 cooldown_s: float = 2.0,
                 timeout_cooldown_s: float = 0.25,
                 journal_dir=None, compact_rows: int = 100_000):
        self._client_opts = {"timeout_s": float(timeout_s),
                             "cooldown_s": float(cooldown_s),
                             "timeout_cooldown_s":
                                 float(timeout_cooldown_s)}
        self.clients = [ShardClient(u, **self._client_opts)
                        for u in urls]
        self.dim = int(dim) if dim is not None else None
        self.train_rows = max(1, int(train_rows))
        self.n_centroids = max(1, int(n_centroids))
        self.nprobe = max(1, int(nprobe))
        self.pq_m = max(1, int(pq_m))
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, len(self.clients)),
            thread_name_prefix="shard-fanout")
        self.centroids: np.ndarray | None = None
        self.codec: PQCodec | None = None
        # Plane version: checkpoint step of the generation every shard
        # serves. None until the rollout machinery adopts a step.
        self.version: int | None = None
        self._prior_version: int | None = None
        self.journal = ShardJournal(journal_dir,
                                    compact_rows=compact_rows)
        # Rows acked at the CURRENT version per shard — the restart
        # detector (healthz rows < acked means the shard lost state).
        self._acked: dict[int, int] = {}
        # Shards flagged for a full re-init + journal resurrection.
        self._resync: set[int] = set()
        # Pre-training buffer: (ids, rows) pairs, brute-forced by
        # searches until the plane trains.
        self._buf_ids: list[np.ndarray] = []
        self._buf_rows: list[np.ndarray] = []
        self._buf_n = 0
        self.inserted = 0
        self.dropped = 0          # journal write failed: truly lost
        self.journaled = 0        # rows parked for repair
        self.repaired = 0         # rows redelivered by repair
        self.stale_dropped = 0    # journal rows version-gated away
        self.degraded_searches = 0
        self.version_mismatches = 0
        # Standalone id allocator (no IndexManager in front): plane-
        # local monotonic ids. NOT durable — a bare shard plane is a
        # cache of the fleet's embeddings, not a system of record.
        self._next_id = 0
        self._registry = registry
        self._m = None
        self._up: dict[int, object] = {}
        if registry is not None:
            self._m = {
                "alive": registry.gauge(
                    "retrieval_shards_alive",
                    "shard endpoints answering"),
                "total": registry.gauge(
                    "retrieval_shards_total",
                    "shard endpoints configured"),
                "degraded": registry.counter(
                    "retrieval_shard_degraded_searches_total",
                    "searches answered with >=1 shard missing"),
                "dropped": registry.counter(
                    "retrieval_shard_dropped_rows_total",
                    "insert rows lost (journal write failed)"),
                "journaled": registry.counter(
                    "retrieval_shard_journaled_rows_total",
                    "insert rows parked in the repair journal"),
                "repaired": registry.counter(
                    "retrieval_shard_repaired_rows_total",
                    "journal rows redelivered by repair"),
                "jdepth": registry.gauge(
                    "retrieval_shard_journal_depth",
                    "journal rows awaiting redelivery"),
                "vmismatch": registry.counter(
                    "retrieval_shard_version_mismatch_total",
                    "shard responses rejected on plane version"),
            }
            self._m["total"].set(len(self.clients))
        self._repair_thread: threading.Thread | None = None
        self._repair_stop = threading.Event()

    @property
    def trained(self) -> bool:
        return self.centroids is not None

    def _set_up(self, sid: int, value: float) -> None:
        if self._registry is None:
            return
        g = self._up.get(sid)
        if g is None:
            g = self._up[sid] = self._registry.gauge(
                "retrieval_shard_up",
                "1 when the shard answers, 0 when dark",
                labels={"shard": str(sid)})
        g.set(value)

    def _gauge_depth(self) -> None:
        if self._m:
            self._m["jdepth"].set(
                float(sum(self.journal.depths().values())))

    # -- training ------------------------------------------------------------
    def _init_wire_locked(self) -> dict:
        return {"centroids": _pack(self.centroids),
                "codec": self.codec.to_wire(),
                "n_shards": len(self.clients),
                "nprobe": self.nprobe,
                "step": self.version}

    def _train_and_flush_locked(self) -> None:
        rows = np.concatenate(self._buf_rows)
        ids = np.concatenate(self._buf_ids)
        self.centroids = kmeans(rows, self.n_centroids, seed=self.seed)
        self.codec = PQCodec(self.dim, m=self.pq_m,
                             seed=self.seed).train(rows)
        wire = self._init_wire_locked()
        inited = []
        for sid, cl in enumerate(self.clients):
            got = cl.call("/shard/init", dict(wire, shard_id=sid))
            if got is not None and got.get("ok"):
                inited.append(sid)
                self._acked[sid] = 0
            else:
                self._resync.add(sid)
        logger.info("shard plane trained: %d centroids, pq m=%d, "
                    "%d/%d shard(s) initialized",
                    self.centroids.shape[0], self.codec.m,
                    len(inited), len(self.clients))
        self._buf_ids, self._buf_rows, self._buf_n = [], [], 0
        self._route_locked(ids, rows)

    def _route_locked(self, ids: np.ndarray, vecs: np.ndarray) -> None:
        """Owner-routed insert push: rows grouped per shard, journaled
        FIRST (write-ahead), then one ``/shard/insert`` each
        (parallel). A dead or version-drifted owner's rows stay in the
        journal as repair debt — visible in ``_journal_depth``, never
        lost. Rows are only DROPPED when the journal write itself
        fails (disk error) — that counter should read zero."""
        assign = np.argmax(vecs @ self.centroids.T, axis=1)
        owner = shard_owner(assign, len(self.clients))
        futs = []
        for sid in np.unique(owner):
            mask = owner == sid
            bids, bvecs = ids[mask], vecs[mask]
            n = int(mask.sum())
            ordinal = self.journal.append(int(sid), bids, bvecs,
                                          self.version)
            cl = self.clients[int(sid)]
            payload = {"ids": bids.tolist(), "vectors": _pack(bvecs),
                       "version": self.version}
            futs.append((int(sid), n, ordinal, self._pool.submit(
                cl.call, "/shard/insert", payload)))
        for sid, n, ordinal, fut in futs:
            got = fut.result()
            delivered = (got is not None
                         and not got.get("version_mismatch")
                         and not int(got.get("rejected", 0)))
            if delivered:
                if ordinal is not None:
                    self.journal.ack(sid, ordinal, n)
                # Advance the ledger by the shard's STORED count, not
                # the delivered batch size: a duplicate redelivery
                # (client timeout on a push the server completed, then
                # a tail drain) stores 0 — counting it as n inflates
                # `_acked` past the shard's real rows until the repair
                # loop reads `rows < acked` as a phantom restart and
                # wipes a healthy shard.
                self._acked[sid] = (self._acked.get(sid, 0)
                                    + int(got.get("stored", n)))
                self.inserted += int(got.get("stored", 0))
                continue
            if got is not None:
                # Alive but on the wrong generation (version mismatch)
                # or the wrong ring (rows rejected as misrouted):
                # resync re-installs both before the journal debt is
                # redelivered. Never ack a partially-rejected batch.
                self._resync.add(sid)
            if ordinal is not None:
                self.journaled += n
                if self._m:
                    self._m["journaled"].inc(n)
            else:
                self.dropped += n
                if self._m:
                    self._m["dropped"].inc(n)
        self._gauge_depth()

    # -- data path -----------------------------------------------------------
    def insert(self, ids, vectors) -> int:
        vecs = np.asarray(vectors, np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None]
        ids = np.asarray(ids, np.int64)
        with self._lock:
            if self.dim is None:
                self.dim = int(vecs.shape[1])
            elif int(vecs.shape[1]) != self.dim:
                logger.warning("shard fanout: insert rejected — dim %d "
                               "!= plane dim %d", vecs.shape[1],
                               self.dim)
                return 0
            if self.centroids is None:
                self._buf_ids.append(ids)
                self._buf_rows.append(vecs)
                self._buf_n += vecs.shape[0]
                if self._buf_n >= self.train_rows:
                    self._train_and_flush_locked()
                return int(vecs.shape[0])
            self._route_locked(ids, vecs)
        return int(vecs.shape[0])

    def insert_auto(self, vectors) -> list[int]:
        """Insert with plane-allocated ids (routers without a local
        ``IndexManager``); returns the assigned ids."""
        vecs = np.asarray(vectors, np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None]
        with self._lock:
            ids = list(range(self._next_id,
                             self._next_id + vecs.shape[0]))
            self._next_id += vecs.shape[0]
        got = self.insert(np.asarray(ids, np.int64), vecs)
        return ids if got else []

    def search(self, queries, k: int = 10) -> dict:
        """Fan out + merge. ``{ids, scores, shards: {ok, total,
        degraded}, rows, version}`` — ids/scores numpy ``[Q, k]``
        padded with -1/-inf like every scan in this package. A shard
        answering on the WRONG plane version is rejected (counted
        degraded) — merged results can never mix model generations."""
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None]
        nq = q.shape[0]
        with self._lock:
            trained = self.centroids is not None
            version = self.version
            if not trained and self._buf_n:
                ids_cat = np.concatenate(self._buf_ids)
                rows_cat = np.concatenate(self._buf_rows)
            else:
                ids_cat = rows_cat = None
        total = len(self.clients)
        if not trained:
            if rows_cat is None:
                return {"ids": np.full((nq, k), -1, np.int64),
                        "scores": np.full((nq, k), -np.inf,
                                          np.float32),
                        "shards": {"ok": total, "total": total,
                                   "degraded": False},
                        "rows": 0, "version": version}
            ids_out, scores_out = brute_force_topk(
                q, ids_cat, rows_cat, k)
            return {"ids": ids_out, "scores": scores_out,
                    "shards": {"ok": total, "total": total,
                               "degraded": False},
                    "rows": int(rows_cat.shape[0]),
                    "version": version}
        payload = {"queries": _pack(q), "k": int(k),
                   "nprobe": self.nprobe}
        futs = [self._pool.submit(cl.call, "/shard/search", payload)
                for cl in self.clients]
        per_shard = [f.result() for f in futs]
        ok = 0
        # Per-query candidate merge, deduped by id keeping the max
        # score: a list mid-migration answers from BOTH owners with
        # identical exact scores, so the window is invisible.
        cand: list[dict] = [{} for _ in range(nq)]
        for sid, r in enumerate(per_shard):
            self._set_up(sid, 0.0 if r is None else 1.0)
            if r is None:
                continue
            if r.get("version") != version:
                self.version_mismatches += 1
                self._resync.add(sid)
                if self._m:
                    self._m["vmismatch"].inc()
                logger.warning(
                    "shard %d answered version %r != plane %r — "
                    "response rejected", sid, r.get("version"),
                    version)
                continue
            ok += 1
            for i, (row_ids, row_scores) in enumerate(
                    zip(r["ids"], r["scores"])):
                ci = cand[i]
                for rid, rs in zip(row_ids, row_scores):
                    if rid >= 0 and rs is not None:
                        prev = ci.get(rid)
                        if prev is None or rs > prev:
                            ci[rid] = rs
        degraded = ok < total
        out_ids = np.full((nq, k), -1, np.int64)
        out_scores = np.full((nq, k), -np.inf, np.float32)
        for i in range(nq):
            if not cand[i]:
                continue
            ids_arr = np.fromiter(cand[i], np.int64,
                                  count=len(cand[i]))
            sc_arr = np.fromiter(cand[i].values(), np.float32,
                                 count=len(cand[i]))
            kk = min(k, ids_arr.shape[0])
            top = np.argpartition(sc_arr, -kk)[-kk:]
            top = top[np.argsort(sc_arr[top])[::-1]]
            out_ids[i, :kk] = ids_arr[top]
            out_scores[i, :kk] = sc_arr[top]
        if degraded:
            self.degraded_searches += 1
            if self._m:
                self._m["degraded"].inc()
        if self._m:
            self._m["alive"].set(ok)
        return {"ids": out_ids, "scores": out_scores,
                "shards": {"ok": ok, "total": total,
                           "degraded": degraded},
                "rows": self.inserted, "version": version}

    # -- plane version lifecycle (rollout state machine) ---------------------
    def _cut_all(self, step: int, op: str) -> None:
        with self._lock:
            if self.version == step:
                return
            self._prior_version = self.version
            self.version = int(step)
            if self.centroids is None:
                return  # untrained: the stamp rides future inits
            clients = list(enumerate(self.clients))
        for sid, cl in clients:
            got = cl.call("/shard/cut", {"step": int(step)})
            if got is not None and got.get("ok"):
                self._acked[sid] = 0
            else:
                self._resync.add(sid)
        logger.info("shard plane %s: every shard cut to step %d "
                    "(%d flagged for resync)", op, step,
                    len(self._resync))

    def activate(self, step: int | None) -> None:
        """First trusted adoption: stamp the plane (and cut any
        pre-version rows — they were embedded by an untrusted or
        unknown model)."""
        if step is None:
            return
        self._cut_all(int(step), op="activate")

    def promote(self, step: int) -> None:
        """Rollout promote: cut EVERY shard to the new generation.
        The prior generation stays retained shard-side for
        rollback."""
        self._cut_all(int(step), op="promote")

    def rollback_to(self, step: int | None) -> bool:
        """Restore the prior generation fleet-wide. Shards that
        retained it swap back instantly; a shard restarted since the
        cut reports cold and is resurrected from its journal history
        by the repair loop. True when every shard restored warm."""
        if step is None:
            return False
        step = int(step)
        with self._lock:
            self.version = step
            clients = list(enumerate(self.clients))
            trained = self.centroids is not None
        if not trained:
            return True
        warm = True
        for sid, cl in clients:
            got = cl.call("/shard/rollback", {"step": step})
            if got is None:
                self._resync.add(sid)
                warm = False
                continue
            self._acked[sid] = int(got.get("rows", 0))
            if not got.get("restored"):
                self._resync.add(sid)
                warm = False
        logger.warning("shard plane rollback to step %d: %s", step,
                       "warm on all shards" if warm
                       else f"{len(self._resync)} shard(s) need "
                            "journal resurrection")
        return warm

    def on_canary_rollback(self, bad_step: int, reason: str = "",
                           ) -> None:
        """Canary verdicts normally precede promote, so the plane was
        never cut to the bad step — only act if it WAS (first
        adoption landed on a lemon)."""
        with self._lock:
            hit = self.version == bad_step
            prior = self._prior_version
        if hit and prior is not None:
            logger.warning("shard plane: canary rollback of step %d "
                           "(%s) — restoring %d", bad_step, reason,
                           prior)
            self.rollback_to(prior)

    # -- repair --------------------------------------------------------------
    def _drain(self, sid: int, from_start: bool) -> tuple[int, int]:
        """Redeliver one shard's journal through the NORMAL insert
        path — rows re-route under the current ring (a migrated list's
        rows land on their new owner) and re-journal at their
        destination, so a failure mid-drain just leaves fresh debt.
        Rows from another plane version are version-gated away (the
        trust gate: a rolled-back model's vectors must not enter the
        current plane)."""
        batches, rows = self.journal.totals(sid)
        repaired = stale = 0
        for ver, ids, vecs in self.journal.replay(
                sid, from_start=from_start, upto_batches=batches):
            if ver != self.version:
                stale += int(ids.shape[0])
                continue
            self.insert(ids, vecs)
            repaired += int(ids.shape[0])
        self.journal.set_acked(sid, batches, rows)
        if repaired:
            self.repaired += repaired
            if self._m:
                self._m["repaired"].inc(repaired)
        if stale:
            self.stale_dropped += stale
        return repaired, stale

    def _resync_shard(self, sid: int, cl: ShardClient) -> bool:
        """Full recovery: re-init the shard's plane structure (ring,
        version, centroids, codec), then resurrect its rows from the
        complete journal history."""
        with self._lock:
            if self.centroids is None:
                return False
            wire = dict(self._init_wire_locked(), shard_id=sid)
        got = cl.call("/shard/init", wire)
        if got is None or not got.get("ok"):
            return False
        self._acked[sid] = 0
        self._resync.discard(sid)
        repaired, stale = self._drain(sid, from_start=True)
        logger.info("shard %d resynced: %d row(s) resurrected, %d "
                    "stale row(s) version-gated", sid, repaired, stale)
        return True

    def repair_tick(self) -> dict:
        """One pass of the self-healing loop (the background thread's
        body; tests call it directly): probe every shard, refresh the
        per-shard ``up`` gauges, resync/resurrect returned shards,
        drain journal debt, compact delivered history."""
        with self._lock:
            clients = list(enumerate(self.clients))
            trained = self.centroids is not None
            version = self.version
        out = {"repaired": 0, "stale": 0, "resynced": []}
        for sid, cl in clients:
            # Snapshot the ledger BEFORE the probe: `_acked` only
            # grows under live traffic, so comparing the probe's row
            # count against a LATER ledger read flags a healthy shard
            # as restarted whenever an insert lands between the two.
            acked = self._acked.get(sid, 0)
            got = cl.call("/healthz", force=True)
            self._set_up(sid, 0.0 if got is None else 1.0)
            if got is None or not trained:
                continue
            needs_resync = (sid in self._resync
                            or not got.get("trained")
                            or got.get("version") != version
                            or int(got.get("rows", 0)) < acked)
            if needs_resync:
                if self._resync_shard(sid, cl):
                    out["resynced"].append(sid)
            elif self.journal.depth(sid) > 0:
                repaired, stale = self._drain(sid, from_start=False)
                out["repaired"] += repaired
                out["stale"] += stale
            self.journal.maybe_compact(sid, version)
        self._gauge_depth()
        return out

    def start(self, interval_s: float = 1.0) -> "ShardFanout":
        """Run ``repair_tick`` on a background thread (the production
        wiring; the CLI starts it next to the fleet loop)."""
        if self._repair_thread is not None:
            return self
        self._repair_stop.clear()

        def _loop():
            while not self._repair_stop.wait(interval_s):
                try:
                    self.repair_tick()
                except Exception:  # noqa: BLE001 — repair must not die
                    logger.exception("shard repair tick failed")

        self._repair_thread = threading.Thread(
            target=_loop, daemon=True, name="shard-repair")
        self._repair_thread.start()
        return self

    def stop(self) -> None:
        self._repair_stop.set()
        if self._repair_thread is not None:
            self._repair_thread.join(5.0)
            self._repair_thread = None

    # -- live rebalancing ----------------------------------------------------
    def rebalance(self, urls) -> dict:
        """Resize the plane to ``urls`` under traffic.

        Two-phase per-list cutover: (0) init genuinely new shards with
        the trained structure at the current version; (1) broadcast
        the new ring and swap the fan-out's client list — inserts now
        route under the new ring, reads fan to the union; (2) stream
        each list whose rendezvous owner changed: extract from the old
        owner (which keeps serving it), journal + insert to the new
        owner, and only on ack ``drop_list`` on the old owner. The
        merge's id-dedup makes the both-owners window row-identical to
        unsharded. Kept shards must keep their position in ``urls``
        (rendezvous identity is the index).
        """
        urls = [u.rstrip("/") for u in urls]
        stats = {"n_old": 0, "n_new": len(urls), "lists_moved": 0,
                 "rows_moved": 0, "rows_total": 0, "lists_skipped": 0}
        with self._lock:
            old_clients = list(self.clients)
            stats["n_old"] = len(old_clients)
            by_url = {c.url: c for c in old_clients}
            new_clients = [by_url.get(u)
                           or ShardClient(u, **self._client_opts)
                           for u in urls]
            trained = self.centroids is not None
            if not trained:
                self.clients = new_clients
                if self._m:
                    self._m["total"].set(len(new_clients))
                return stats
            wire = {"centroids": _pack(self.centroids),
                    "codec": self.codec.to_wire(),
                    "n_shards": len(urls),
                    "nprobe": self.nprobe,
                    "step": self.version}
            n_lists = int(self.centroids.shape[0])
        old_n, new_n = len(old_clients), len(new_clients)
        # Phase 0: bring genuinely new shards onto the plane.
        for sid, cl in enumerate(new_clients):
            if cl.url not in by_url:
                got = cl.call("/shard/init", dict(wire, shard_id=sid))
                if got is None or not got.get("ok"):
                    self._resync.add(sid)
                self._acked[sid] = 0
        lists = np.arange(n_lists)
        old_owner = shard_owner(lists, old_n)
        new_owner = shard_owner(lists, new_n)
        rows_before = 0
        for cl in old_clients:
            got = cl.call("/healthz")
            if got is not None:
                rows_before += int(got.get("rows", 0))
        stats["rows_total"] = rows_before
        # Phase 1: new ring everywhere, then swap the client list —
        # from here inserts route under the new ring and searches fan
        # to the union; old owners keep serving their moving lists.
        for sid, cl in enumerate(new_clients):
            got = cl.call("/shard/ring", {"n_shards": new_n,
                                          "shard_id": sid})
            if got is None:
                self._resync.add(sid)
        with self._lock:
            self.clients = new_clients
            if self._m:
                self._m["total"].set(new_n)
        # Phase 2: stream each moving list old-owner -> new-owner.
        moving = [int(c) for c in lists
                  if old_owner[c] < old_n
                  and (old_owner[c] >= new_n
                       or int(old_owner[c]) != int(new_owner[c]))]
        for c in moving:
            src_sid = int(old_owner[c])
            dst_sid = int(new_owner[c])
            src, dst = old_clients[src_sid], new_clients[dst_sid]
            got = src.call("/shard/extract", {"list": c})
            if got is None:
                # Old owner dark: its rows are journal debt already —
                # repair will land them on the NEW owner.
                stats["lists_skipped"] += 1
                continue
            n = int(got.get("rows", 0))
            if n == 0:
                src.call("/shard/drop_list", {"list": c})
                stats["lists_moved"] += 1
                continue
            ids = np.asarray(got["ids"], np.int64)
            vecs = _unpack(got["vectors"])
            ordinal = self.journal.append(dst_sid, ids, vecs,
                                          self.version)
            ack = dst.call("/shard/insert",
                           {"ids": ids.tolist(),
                            "vectors": _pack(vecs),
                            "version": self.version})
            if (ack is not None and not ack.get("version_mismatch")
                    and not int(ack.get("rejected", 0))):
                if ordinal is not None:
                    self.journal.ack(dst_sid, ordinal, n)
                self._acked[dst_sid] = (self._acked.get(dst_sid, 0)
                                        + int(ack.get("stored", n)))
                src.call("/shard/drop_list", {"list": c})
                stats["lists_moved"] += 1
                stats["rows_moved"] += n
            else:
                # New owner unavailable: rows are journaled (repair
                # finishes the move); old owner keeps serving reads.
                stats["lists_skipped"] += 1
                if ordinal is not None:
                    self.journaled += n
                    if self._m:
                        self._m["journaled"].inc(n)
        self._gauge_depth()
        logger.info("shard plane rebalanced %d -> %d: %d/%d list(s) "
                    "moved, %d row(s) streamed (%d total), %d "
                    "deferred to repair", old_n, new_n,
                    stats["lists_moved"], len(moving),
                    stats["rows_moved"], rows_before,
                    stats["lists_skipped"])
        return stats

    def snapshot(self) -> dict:
        health = []
        for cl in self.clients:
            got = cl.call("/healthz")
            health.append({"url": cl.url,
                           "alive": got is not None,
                           **({k: got[k] for k in
                               ("rows", "trained", "shard", "version")
                               if k in got}
                              if got else {})})
        return {"trained": self.trained,
                "n_shards": len(self.clients),
                "version": self.version,
                "inserted": self.inserted,
                "dropped": self.dropped,
                "journaled": self.journaled,
                "repaired": self.repaired,
                "journal_depth": sum(self.journal.depths().values()),
                "degraded_searches": self.degraded_searches,
                "version_mismatches": self.version_mismatches,
                "buffered": self._buf_n,
                "shards": health}

    def close(self) -> None:
        self.stop()
        self.journal.close()
        self._pool.shutdown(wait=False)


def main(argv=None) -> int:
    """Shard worker subprocess entry: serve one ``IndexShard`` until
    SIGTERM/SIGINT. Publishes the bound port via ``--port-file``
    (atomic tmp+rename) — the ``ServingFleet`` handshake — and
    answers its ``/readyz`` probes. JAX-free by construction."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="ntxent-shard",
        description="one retrieval shard worker (supervised)")
    parser.add_argument("--dim", type=int, required=True,
                        help="embedding dimension of the plane")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0,
                        help="bind port (0 = ephemeral)")
    parser.add_argument("--port-file", default=None,
                        help="publish the bound port here (the "
                             "supervisor handshake)")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s shard %(message)s")
    server = ShardServer(args.dim, host=args.host,
                         port=args.port).start()
    if args.port_file:
        tmp = f"{args.port_file}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(server.port))
        os.replace(tmp, args.port_file)
    stop = threading.Event()

    def _handle(signum, frame):  # noqa: ARG001
        stop.set()

    signal.signal(signal.SIGTERM, _handle)
    signal.signal(signal.SIGINT, _handle)
    logger.info("shard worker up on %s (pid %d)", server.url,
                os.getpid())
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
