"""Sharded index plane: IVF lists partitioned across worker processes.

At millions of rows a single process's scan is bounded by one memory
bus. The shard plane splits the COATED structure, not the query: IVF
list ``c`` lives on shard ``c % n_shards``, every shard keeps the full
centroid table, and a query probes the same global top-``nprobe``
lists on EVERY shard — shard ``s`` contributes exactly the probed
lists it owns, so the union across shards equals the unsharded probe
set row-for-row. Scores are exact re-ranked inner products (scan.py),
hence directly comparable, and the router-side merge is a plain
per-query top-k. Two consequences fall out for free:

* recall is IDENTICAL to the unsharded index when every shard answers
  (same candidate rows, same exact scores);
* a dead shard subtracts only the rows of the lists it owns — the
  merge runs over whoever answered, the response carries
  ``shards: {ok, total, degraded}``, and availability never depends
  on any single shard. Degraded recall, never a 503.

Training stays CENTRAL: the coordinator (``ShardFanout``) buffers the
first ``train_rows`` inserts, fits IVF centroids + the PQ codec once,
pushes both to every shard (``POST /shard/init``), then flushes the
buffered rows to their owners. Until that point searches brute-force
the coordinator's buffer — cold behavior matches ``VectorIndex``.
Shards are UNVERSIONED (one plane, no per-step cutover) — wiring the
rollout state machine through the fan-out is a ROADMAP follow-up.

Wire format: vectors ride as base64 float32 blobs (``_pack``), ~3x
denser than JSON float lists and loss-free. Everything here is numpy
+ stdlib (http.server / urllib) — the retrieval import boundary and
the fleet tripwire both pin that no jax sneaks in.
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .ivf import brute_force_topk, kmeans
from .pq import PQCodec
from .scan import CodedLists, batched_scan

logger = logging.getLogger(__name__)

__all__ = ["IndexShard", "ShardClient", "ShardFanout", "ShardServer",
           "shard_owner"]

_MAX_BODY = 64 * 1024 * 1024  # b64 f32 rows are bulky; cap, don't trust


def shard_owner(lists: np.ndarray, n_shards: int) -> np.ndarray:
    """IVF list -> owning shard. Static modulo placement: no lookup
    table to replicate, and a list's owner is derivable anywhere."""
    return np.asarray(lists) % int(n_shards)


def _pack(arr: np.ndarray) -> dict:
    a = np.ascontiguousarray(arr, np.float32)
    return {"shape": list(a.shape),
            "f32": base64.b64encode(a.tobytes()).decode("ascii")}


def _unpack(obj: dict) -> np.ndarray:
    shape = tuple(int(s) for s in obj["shape"])
    raw = base64.b64decode(obj["f32"])
    return np.frombuffer(raw, np.float32).reshape(shape).copy()


class IndexShard:
    """One worker's slice of the plane: the coded lists it owns plus a
    raw grow-buffer source for exact re-rank.

    Single-writer per shard (the HTTP handler serializes under
    ``_lock``); searches ride the lock-free coded-list views. Rows for
    lists this shard does NOT own are rejected loudly — a misrouted
    insert means the coordinator's plan and this shard disagree, and
    silently indexing it would double rows under another shard.
    """

    def __init__(self, dim: int, shard_id: int = 0, n_shards: int = 1):
        self.dim = int(dim)
        self.shard_id = int(shard_id)
        self.n_shards = max(1, int(n_shards))
        self._lock = threading.Lock()
        self._coded: CodedLists | None = None
        # Raw rows backing the coded locators: grown copy-on-publish
        # (committed prefix copied before the pointer swap, same
        # discipline as scan._ListBuf).
        self._raw = np.empty((0, self.dim), np.float32)
        self._raw_rows = 0
        self.nprobe = 8
        self.misrouted = 0

    @property
    def trained(self) -> bool:
        return self._coded is not None

    @property
    def rows(self) -> int:
        coded = self._coded
        return coded.rows if coded is not None else 0

    def init_plane(self, centroids: np.ndarray, codec: PQCodec,
                   shard_id: int, n_shards: int,
                   nprobe: int = 8) -> None:
        """Install the centrally trained structure. Re-init replaces
        the coded lists wholesale (a retrain invalidates old codes);
        in-flight searches keep the old arrays alive and stay
        consistent."""
        with self._lock:
            self.shard_id = int(shard_id)
            self.n_shards = max(1, int(n_shards))
            self.nprobe = max(1, int(nprobe))
            coded = CodedLists(centroids, codec)
            # Fresh lists drop any previous generation's rows (the
            # coordinator re-flushes on retrain — ROADMAP follow-up);
            # source 0 is this shard's raw grow buffer.
            self._raw_rows = 0
            self._raw = np.empty((0, self.dim), np.float32)
            coded.add_source(self._raw)
            self._coded = coded

    def insert(self, ids: np.ndarray, vectors: np.ndarray) -> int:
        """Index owned rows; returns how many were accepted."""
        vecs = np.asarray(vectors, np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None]
        ids = np.asarray(ids, np.int64)
        with self._lock:
            coded = self._coded
            if coded is None:
                raise RuntimeError("shard not initialized")
            assign = coded.assign(vecs)
            owned = shard_owner(assign, self.n_shards) == self.shard_id
            if not bool(np.all(owned)):
                self.misrouted += int((~owned).sum())
                logger.warning("shard %d: %d misrouted row(s) rejected",
                               self.shard_id, int((~owned).sum()))
                vecs, ids = vecs[owned], ids[owned]
                assign = assign[owned]
            n = vecs.shape[0]
            if not n:
                return 0
            need = self._raw_rows + n
            if need > self._raw.shape[0]:
                grow = max(need, int(self._raw.shape[0] * 1.5),
                           self._raw.shape[0] + 1024)
                nb = np.empty((grow, self.dim), np.float32)
                nb[: self._raw_rows] = self._raw[: self._raw_rows]
                self._raw = nb
                # Locators live in the coded lists; rebase them onto
                # the grown array BEFORE the new rows publish.
                coded.replace_source(0, self._raw)
            start = self._raw_rows
            self._raw[start: need] = vecs
            self._raw_rows = need
            coded.append_assigned(
                assign, ids, coded.codec.encode(vecs), 0,
                np.arange(start, need, dtype=np.int32))
            return n

    def search(self, queries: np.ndarray, k: int,
               nprobe: int | None = None) -> tuple[np.ndarray,
                                                   np.ndarray]:
        coded = self._coded
        if coded is None or coded.rows == 0:
            q = np.asarray(queries, np.float32)
            nq = q.shape[0] if q.ndim > 1 else 1
            return (np.full((nq, k), -1, np.int64),
                    np.full((nq, k), -np.inf, np.float32))
        return batched_scan(coded, queries, int(k),
                            int(nprobe or self.nprobe),
                            rerank=max(512, 4 * int(k)))


class ShardServer:
    """Stdlib HTTP front end over one ``IndexShard``.

    ``POST /shard/init`` installs centroids+codec, ``POST
    /shard/insert`` indexes owned rows, ``POST /shard/search`` answers
    ``{ids, scores}``, ``GET /healthz`` reports liveness+rows. One
    process per shard in production; tests run several in-process."""

    def __init__(self, dim: int, host: str = "127.0.0.1",
                 port: int = 0):
        self.shard = IndexShard(dim)
        self.host, self.port = host, int(port)
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ShardServer":
        shard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802 — stdlib name
                pass

            def _reply(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    s = shard.shard
                    self._reply(200, {"ok": True, "rows": s.rows,
                                      "trained": s.trained,
                                      "shard": s.shard_id,
                                      "misrouted": s.misrouted})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    if n > _MAX_BODY:
                        self._reply(413, {"error": "body too large"})
                        return
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if self.path == "/shard/init":
                        shard.shard.init_plane(
                            _unpack(req["centroids"]),
                            PQCodec.from_wire(req["codec"]),
                            int(req["shard_id"]),
                            int(req["n_shards"]),
                            int(req.get("nprobe", 8)))
                        self._reply(200, {"ok": True})
                    elif self.path == "/shard/insert":
                        took = shard.shard.insert(
                            np.asarray(req["ids"], np.int64),
                            _unpack(req["vectors"]))
                        self._reply(200, {"stored": took})
                    elif self.path == "/shard/search":
                        ids, scores = shard.shard.search(
                            _unpack(req["queries"]),
                            int(req.get("k", 10)),
                            req.get("nprobe"))
                        self._reply(200, {
                            "ids": ids.tolist(),
                            "scores": [[float(s) if np.isfinite(s)
                                        else None for s in row]
                                       for row in scores]})
                    else:
                        self._reply(404, {"error": "not found"})
                except Exception as e:  # noqa: BLE001 — a bad payload
                    # must answer 400, never drop the connection.
                    self._reply(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"shard-{self.port}")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None


class ShardClient:
    """One shard endpoint with failure memory: a refused/timed-out
    call marks the shard dead for ``cooldown_s`` so a fan-out isn't
    taxed a connect timeout per query per dead shard; after the
    cooldown the next call retries it (a restarted shard rejoins by
    answering)."""

    def __init__(self, url: str, timeout_s: float = 5.0,
                 cooldown_s: float = 2.0):
        self.url = url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.cooldown_s = float(cooldown_s)
        self._dead_until = 0.0
        self.failures = 0

    @property
    def available(self) -> bool:
        return time.monotonic() >= self._dead_until

    def call(self, path: str, payload: dict | None = None,
             timeout_s: float | None = None) -> dict | None:
        """POST (or GET when ``payload`` is None); None on any
        transport/HTTP failure — the caller degrades, never raises."""
        if not self.available:
            return None
        try:
            if payload is None:
                req = urllib.request.Request(self.url + path)
            else:
                req = urllib.request.Request(
                    self.url + path,
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(
                    req, timeout=timeout_s or self.timeout_s) as resp:
                out = json.loads(resp.read())
            self._dead_until = 0.0
            return out
        except (urllib.error.URLError, OSError, ValueError) as e:
            self.failures += 1
            self._dead_until = time.monotonic() + self.cooldown_s
            logger.warning("shard %s unreachable (%s) — cooling down "
                           "%.1fs", self.url, e, self.cooldown_s)
            return None


class ShardFanout:
    """Coordinator: central training, owner-routed inserts, merged
    fan-out searches.

    ``registry`` (optional MetricsRegistry) exports the plane's
    health: per-shard row gauges, degraded-search and dropped-insert
    counters — the difference between "recall quietly sagged" and a
    page."""

    def __init__(self, urls, dim: int | None = None,
                 train_rows: int = 4096, n_centroids: int = 64,
                 nprobe: int = 8, pq_m: int = 8,
                 registry=None, seed: int = 0,
                 timeout_s: float = 5.0):
        self.clients = [ShardClient(u, timeout_s=timeout_s)
                        for u in urls]
        self.dim = int(dim) if dim is not None else None
        self.train_rows = max(1, int(train_rows))
        self.n_centroids = max(1, int(n_centroids))
        self.nprobe = max(1, int(nprobe))
        self.pq_m = max(1, int(pq_m))
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, len(self.clients)),
            thread_name_prefix="shard-fanout")
        self.centroids: np.ndarray | None = None
        self.codec: PQCodec | None = None
        # Pre-training buffer: (ids, rows) pairs, brute-forced by
        # searches until the plane trains.
        self._buf_ids: list[np.ndarray] = []
        self._buf_rows: list[np.ndarray] = []
        self._buf_n = 0
        self.inserted = 0
        self.dropped = 0
        self.degraded_searches = 0
        # Standalone id allocator (no IndexManager in front): plane-
        # local monotonic ids. NOT durable — a bare shard plane is a
        # cache of the fleet's embeddings, not a system of record.
        self._next_id = 0
        self._m = None
        if registry is not None:
            self._m = {
                "alive": registry.gauge(
                    "retrieval_shards_alive",
                    "shard endpoints answering"),
                "total": registry.gauge(
                    "retrieval_shards_total",
                    "shard endpoints configured"),
                "degraded": registry.counter(
                    "retrieval_shard_degraded_searches_total",
                    "searches answered with >=1 shard missing"),
                "dropped": registry.counter(
                    "retrieval_shard_dropped_rows_total",
                    "insert rows lost to dead shards"),
            }
            self._m["total"].set(len(self.clients))

    @property
    def trained(self) -> bool:
        return self.centroids is not None

    # -- training ------------------------------------------------------------
    def _train_and_flush_locked(self) -> None:
        rows = np.concatenate(self._buf_rows)
        ids = np.concatenate(self._buf_ids)
        self.centroids = kmeans(rows, self.n_centroids, seed=self.seed)
        self.codec = PQCodec(self.dim, m=self.pq_m,
                             seed=self.seed).train(rows)
        wire = {"centroids": _pack(self.centroids),
                "codec": self.codec.to_wire(),
                "n_shards": len(self.clients),
                "nprobe": self.nprobe}
        inited = []
        for sid, cl in enumerate(self.clients):
            got = cl.call("/shard/init", dict(wire, shard_id=sid))
            if got is not None and got.get("ok"):
                inited.append(sid)
        logger.info("shard plane trained: %d centroids, pq m=%d, "
                    "%d/%d shard(s) initialized",
                    self.centroids.shape[0], self.codec.m,
                    len(inited), len(self.clients))
        self._buf_ids, self._buf_rows, self._buf_n = [], [], 0
        self._route_locked(ids, rows)

    def _route_locked(self, ids: np.ndarray, vecs: np.ndarray) -> None:
        """Owner-routed insert push: rows grouped per shard, one
        ``/shard/insert`` each (parallel). A dead owner's rows are
        DROPPED and counted — the plane stays available and the loss
        is visible, the recall contract (degraded, never down) over
        durability for rows in flight."""
        assign = np.argmax(vecs @ self.centroids.T, axis=1)
        owner = shard_owner(assign, len(self.clients))
        futs = []
        for sid in np.unique(owner):
            mask = owner == sid
            cl = self.clients[int(sid)]
            payload = {"ids": ids[mask].tolist(),
                       "vectors": _pack(vecs[mask])}
            futs.append((int(mask.sum()), self._pool.submit(
                cl.call, "/shard/insert", payload)))
        for n, fut in futs:
            got = fut.result()
            if got is None:
                self.dropped += n
                if self._m:
                    self._m["dropped"].inc(n)
            else:
                self.inserted += int(got.get("stored", 0))

    # -- data path -----------------------------------------------------------
    def insert(self, ids, vectors) -> int:
        vecs = np.asarray(vectors, np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None]
        ids = np.asarray(ids, np.int64)
        with self._lock:
            if self.dim is None:
                self.dim = int(vecs.shape[1])
            elif int(vecs.shape[1]) != self.dim:
                logger.warning("shard fanout: insert rejected — dim %d "
                               "!= plane dim %d", vecs.shape[1],
                               self.dim)
                return 0
            if self.centroids is None:
                self._buf_ids.append(ids)
                self._buf_rows.append(vecs)
                self._buf_n += vecs.shape[0]
                if self._buf_n >= self.train_rows:
                    self._train_and_flush_locked()
                return int(vecs.shape[0])
            self._route_locked(ids, vecs)
        return int(vecs.shape[0])

    def insert_auto(self, vectors) -> list[int]:
        """Insert with plane-allocated ids (routers without a local
        ``IndexManager``); returns the assigned ids."""
        vecs = np.asarray(vectors, np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None]
        with self._lock:
            ids = list(range(self._next_id,
                             self._next_id + vecs.shape[0]))
            self._next_id += vecs.shape[0]
        got = self.insert(np.asarray(ids, np.int64), vecs)
        return ids if got else []

    def search(self, queries, k: int = 10) -> dict:
        """Fan out + merge. ``{ids, scores, shards: {ok, total,
        degraded}, rows}`` — ids/scores numpy ``[Q, k]`` padded with
        -1/-inf like every scan in this package."""
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None]
        nq = q.shape[0]
        with self._lock:
            trained = self.centroids is not None
            if not trained and self._buf_n:
                ids_cat = np.concatenate(self._buf_ids)
                rows_cat = np.concatenate(self._buf_rows)
            else:
                ids_cat = rows_cat = None
        total = len(self.clients)
        if not trained:
            if rows_cat is None:
                return {"ids": np.full((nq, k), -1, np.int64),
                        "scores": np.full((nq, k), -np.inf,
                                          np.float32),
                        "shards": {"ok": total, "total": total,
                                   "degraded": False},
                        "rows": 0}
            ids_out, scores_out = brute_force_topk(
                q, ids_cat, rows_cat, k)
            return {"ids": ids_out, "scores": scores_out,
                    "shards": {"ok": total, "total": total,
                               "degraded": False},
                    "rows": int(rows_cat.shape[0])}
        payload = {"queries": _pack(q), "k": int(k),
                   "nprobe": self.nprobe}
        futs = [self._pool.submit(cl.call, "/shard/search", payload)
                for cl in self.clients]
        per_shard = [f.result() for f in futs]
        ok = sum(1 for r in per_shard if r is not None)
        degraded = ok < total
        out_ids = np.full((nq, k), -1, np.int64)
        out_scores = np.full((nq, k), -np.inf, np.float32)
        cand_ids: list[list] = [[] for _ in range(nq)]
        cand_scores: list[list] = [[] for _ in range(nq)]
        for r in per_shard:
            if r is None:
                continue
            for i, (row_ids, row_scores) in enumerate(
                    zip(r["ids"], r["scores"])):
                for rid, rs in zip(row_ids, row_scores):
                    if rid >= 0 and rs is not None:
                        cand_ids[i].append(rid)
                        cand_scores[i].append(rs)
        for i in range(nq):
            if not cand_ids[i]:
                continue
            ids_arr = np.asarray(cand_ids[i], np.int64)
            sc_arr = np.asarray(cand_scores[i], np.float32)
            kk = min(k, ids_arr.shape[0])
            top = np.argpartition(sc_arr, -kk)[-kk:]
            top = top[np.argsort(sc_arr[top])[::-1]]
            out_ids[i, :kk] = ids_arr[top]
            out_scores[i, :kk] = sc_arr[top]
        if degraded:
            self.degraded_searches += 1
            if self._m:
                self._m["degraded"].inc()
        if self._m:
            self._m["alive"].set(ok)
        return {"ids": out_ids, "scores": out_scores,
                "shards": {"ok": ok, "total": total,
                           "degraded": degraded},
                "rows": self.inserted}

    def snapshot(self) -> dict:
        health = []
        for cl in self.clients:
            got = cl.call("/healthz")
            health.append({"url": cl.url,
                           "alive": got is not None,
                           **({k: got[k] for k in
                               ("rows", "trained", "shard")}
                              if got else {})})
        return {"trained": self.trained,
                "n_shards": len(self.clients),
                "inserted": self.inserted,
                "dropped": self.dropped,
                "degraded_searches": self.degraded_searches,
                "buffered": self._buf_n,
                "shards": health}

    def close(self) -> None:
        self._pool.shutdown(wait=False)
