"""Append-only vector segments: a mutable tail, sealed memory-maps.

The retrieval tier's durable substrate (ISSUE 15). Inserts land in a
plain in-memory ``MutableSegment``; once it crosses ``seal_rows`` the
maintenance pass SEALS it — the rows are staged under a ``.tmp-*``
directory, every file and the directory fsync'd, then the directory
``rename``d into place and the parent fsync'd. That is the checkpoint
tier's stage-fsync-rename idiom (training/checkpoint.py): a SIGKILL at
any instant leaves either no segment or a complete one, never a torn
file, and leftover staging debris is purged at open. Sealed segments
are read back as ``np.memmap`` views, so a large index costs the page
cache, not the heap, and reopening a store is O(metadata).

Compaction keeps the segment count bounded: when sealed segments
exceed ``compact_at``, one pass merges them all into a single new
segment (same atomic staging), publishes it, then deletes the inputs —
a reader that opened the old segments keeps its mmaps alive (POSIX
unlink semantics), a crash mid-compaction leaves the originals
untouched.

Everything here is numpy + stdlib. The import-boundary lint and the
fleet tripwire test both pin that this module can never reach jax.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import uuid
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["MutableSegment", "SealedSegment", "SegmentStore"]

_META = "meta.json"
_VECS = "vectors.f32"
_IDS = "ids.i64"
_CODES = "codes.u8"
_ASSIGN = "assign.i32"


def _fsync_path(path: Path) -> None:
    """fsync a file or directory (directory fsync persists the entry);
    same tolerance contract as the checkpoint tier's copy."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class MutableSegment:
    """The in-memory insert tail: grows by chunks, never reallocates
    per row. Single-writer (the index holds its own lock)."""

    def __init__(self, dim: int, chunk_rows: int = 1024):
        self.dim = int(dim)
        self.chunk_rows = max(1, int(chunk_rows))
        self._vecs = np.empty((0, self.dim), np.float32)
        self._ids = np.empty((0,), np.int64)
        self.rows = 0

    def append(self, ids: np.ndarray, vecs: np.ndarray) -> None:
        n = int(vecs.shape[0])
        need = self.rows + n
        if need > self._vecs.shape[0]:
            # Geometric growth: the copy-everything reallocation must
            # amortize to O(1)/row — with linear growth a large
            # unsealed tail paid a full-array copy every chunk_rows
            # inserts, and that copy runs under the index lock where
            # it read as a concurrent-search p99 spike.
            grow = max(need, int(self._vecs.shape[0] * 1.5),
                       self._vecs.shape[0] + self.chunk_rows)
            nv = np.empty((grow, self.dim), np.float32)
            nv[: self.rows] = self._vecs[: self.rows]
            self._vecs = nv
            ni = np.empty((grow,), np.int64)
            ni[: self.rows] = self._ids[: self.rows]
            self._ids = ni
        self._vecs[self.rows: need] = vecs
        self._ids[self.rows: need] = ids
        self.rows = need

    def view(self) -> tuple[np.ndarray, np.ndarray]:
        """Consistent ``(ids, vectors)`` snapshot for LOCK-FREE
        readers: the committed count is read before the buffers (data
        is written before the count bumps; growth copies the prefix
        before the swap), so the slice can never expose uninitialized
        rows or mismatched lengths."""
        n = self.rows
        ids, vecs = self._ids, self._vecs
        n = min(n, ids.shape[0], vecs.shape[0])
        return ids[:n], vecs[:n]

    @property
    def vectors(self) -> np.ndarray:
        return self.view()[1]

    @property
    def ids(self) -> np.ndarray:
        return self.view()[0]


class SealedSegment:
    """One on-disk segment: raw little-endian f32 rows + int64 ids,
    described by ``meta.json``, mapped read-only."""

    def __init__(self, path: Path):
        self.path = Path(path)
        meta = json.loads((self.path / _META).read_text())
        self.rows = int(meta["rows"])
        self.dim = int(meta["dim"])
        self.vectors = np.memmap(self.path / _VECS, dtype=np.float32,
                                 mode="r", shape=(self.rows, self.dim))
        self.ids = np.memmap(self.path / _IDS, dtype=np.int64,
                             mode="r", shape=(self.rows,))
        # PQ sidecars (encode-on-seal, ISSUE 17): compact codes + IVF
        # assignments stamped with the codec generation that produced
        # them. Optional — pre-PQ segments stay readable, and a
        # missing/mismatched sidecar means "recompute", never "fail".
        self.codec_gen = meta.get("codec_gen")
        self.codes = self.assign = None
        if self.codec_gen is not None:
            m = int(meta.get("pq_m", 0))
            try:
                if m > 0:
                    self.codes = np.memmap(
                        self.path / _CODES, dtype=np.uint8, mode="r",
                        shape=(self.rows, m))
                self.assign = np.memmap(
                    self.path / _ASSIGN, dtype=np.int32, mode="r",
                    shape=(self.rows,))
            except (OSError, ValueError):
                self.codes = self.assign = None
                self.codec_gen = None

    @property
    def name(self) -> str:
        return self.path.name


class FrozenSegment:
    """An in-memory sealed segment (``root=None`` stores): same read
    surface as ``SealedSegment``, no durability. Freezing still
    matters without a disk — it bounds the mutable tail, so the
    geometric-growth copy can never grow past ``seal_rows`` (an
    unbounded tail's reallocation measured as a multi-10-ms search
    stall under the index lock)."""

    def __init__(self, name: str, ids: np.ndarray, vecs: np.ndarray,
                 codes: np.ndarray | None = None,
                 assign: np.ndarray | None = None,
                 codec_gen: int | None = None):
        self.name = name
        self.ids = np.ascontiguousarray(ids, np.int64)
        self.vectors = np.ascontiguousarray(vecs, np.float32)
        self.rows = int(self.vectors.shape[0])
        self.dim = int(self.vectors.shape[1])
        self.codes = codes
        self.assign = assign
        self.codec_gen = codec_gen


def _write_segment(parent: Path, name: str, ids: np.ndarray,
                   vecs: np.ndarray,
                   codes: np.ndarray | None = None,
                   assign: np.ndarray | None = None,
                   codec_gen: int | None = None) -> Path:
    """Stage + fsync + rename one complete segment directory.
    ``codes``/``assign`` (with their ``codec_gen`` stamp) ride the
    same atomic commit — a segment either carries a complete PQ
    sidecar or none."""
    tmp = parent / f".tmp-{name}-{uuid.uuid4().hex[:8]}"
    tmp.mkdir(parents=True)
    vecs = np.ascontiguousarray(vecs, np.float32)
    ids = np.ascontiguousarray(ids, np.int64)
    blobs = [(_VECS, vecs), (_IDS, ids)]
    meta = {"rows": int(vecs.shape[0]), "dim": int(vecs.shape[1])}
    if assign is not None and codec_gen is not None:
        blobs.append((_ASSIGN,
                      np.ascontiguousarray(assign, np.int32)))
        meta["codec_gen"] = int(codec_gen)
        meta["pq_m"] = 0
        if codes is not None:
            codes = np.ascontiguousarray(codes, np.uint8)
            blobs.append((_CODES, codes))
            meta["pq_m"] = int(codes.shape[1])
    for fname, arr in blobs:
        with open(tmp / fname, "wb") as f:
            f.write(arr.tobytes())
            f.flush()
            os.fsync(f.fileno())
    with open(tmp / _META, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    _fsync_path(tmp)
    final = parent / name
    os.rename(tmp, final)
    _fsync_path(parent)
    return final


class SegmentStore:
    """Mutable tail + sealed mmaps under one directory (or fully
    in-memory with ``root=None`` — tests and ephemeral indexes).

    Not itself thread-safe: the owning ``VectorIndex`` serializes
    mutation; readers go through ``blocks()`` snapshots.
    """

    def __init__(self, dim: int, root: str | os.PathLike | None = None,
                 seal_rows: int = 4096, compact_at: int = 4):
        self.dim = int(dim)
        self.seal_rows = max(1, int(seal_rows))
        self.compact_at = max(2, int(compact_at))
        self.root = Path(root) if root is not None else None
        self.mutable = MutableSegment(self.dim)
        self.sealed: list = []
        # Optional PQ coder (set by the owning index once trained):
        # an object with encode(vecs)->uint8 codes, assign(vecs)->
        # int32 IVF lists, and a ``gen`` stamp. When present, freeze
        # and merge write the sidecars — encode-on-seal is what makes
        # the trained state rebuildable without touching raw floats.
        self.coder = None
        # A taken-but-not-yet-published tail (mid-freeze): still part
        # of every read view — a brute-force search during the freeze
        # window must not miss its rows.
        self.pending: MutableSegment | None = None
        self._seq = 0
        if self.root is not None:
            self.root.mkdir(parents=True, exist_ok=True)
            for debris in self.root.glob(".tmp-*"):
                # A crash mid-seal/compact left staging: incomplete by
                # definition (the rename IS the commit) — purge.
                shutil.rmtree(debris, ignore_errors=True)
            for seg in sorted(self.root.glob("seg-*")):
                try:
                    self.sealed.append(SealedSegment(seg))
                except (OSError, ValueError, KeyError) as e:
                    logger.warning("retrieval: unreadable segment %s "
                                   "(%s) — skipped", seg, e)
            if self.sealed:
                self._seq = 1 + max(int(s.name.split("-")[1])
                                    for s in self.sealed)

    # -- writes ------------------------------------------------------------
    def append(self, ids: np.ndarray, vecs: np.ndarray) -> None:
        self.mutable.append(ids, vecs)

    def should_seal(self) -> bool:
        return self.mutable.rows >= self.seal_rows

    # The two-phase maintenance primitives (VectorIndex.maintain): the
    # POINTER operations (take/publish/swap) run under the index lock
    # in microseconds, the COPY/IO operations (freeze/merge) run
    # outside it — a seal's fsync or a compaction's merge must never
    # stall a concurrent search.
    def take_mutable(self) -> MutableSegment:
        """Swap the mutable tail for a fresh one (pointer-only); the
        taken tail stays visible via ``pending`` until published.

        Write ORDER matters for the lock-free readers: ``pending`` is
        set BEFORE the mutable swap (and ``publish`` appends to
        ``sealed`` before clearing ``pending``), while ``blocks()``
        reads mutable → pending → sealed. Any interleaving then shows
        the taken rows in at least one place — the tolerated transient
        is a DUPLICATE sighting (both pending and its published copy),
        never a loss."""
        taken = self.mutable
        self.pending = taken
        self.mutable = MutableSegment(self.dim)
        return taken

    def _code(self, vecs: np.ndarray):
        """``(codes, assign, gen)`` for rows about to seal — or
        ``(None, None, None)`` without a trained coder."""
        coder = self.coder
        if coder is None or vecs.shape[0] == 0:
            return None, None, None
        return coder.encode(vecs), coder.assign(vecs), coder.gen

    def freeze(self, mutable: MutableSegment):
        """Materialize a taken tail as a sealed segment (disk when
        rooted, in-memory otherwise). Copy/IO only — no store state
        is touched; ``publish`` it afterwards."""
        name = f"seg-{self._seq:06d}"
        self._seq += 1
        ids, vecs = mutable.view()
        codes, assign, gen = self._code(vecs)
        if self.root is None:
            return FrozenSegment(name, ids, vecs, codes=codes,
                                 assign=assign, codec_gen=gen)
        path = _write_segment(self.root, name, ids, vecs,
                              codes=codes, assign=assign,
                              codec_gen=gen)
        return SealedSegment(path)

    def publish(self, segment) -> None:
        self.sealed.append(segment)
        self.pending = None

    def seal(self):
        """Single-threaded convenience: take + freeze + publish."""
        if self.mutable.rows == 0:
            return None
        seg = self.freeze(self.take_mutable())
        self.publish(seg)
        return seg

    def should_compact(self) -> bool:
        return len(self.sealed) > self.compact_at

    def merge(self, segments: list):
        """Merge sealed segments into one new segment (copy/IO only;
        ``swap_sealed`` it in afterwards). Input sidecars of the
        current codec generation are CONCATENATED, never recomputed —
        a compaction is an IO pass, not an encode pass; any stale or
        missing sidecar re-encodes that segment only."""
        ids = np.concatenate([np.asarray(s.ids) for s in segments])
        vecs = np.concatenate([np.asarray(s.vectors)
                               for s in segments])
        codes = assign = gen = None
        coder = self.coder
        if coder is not None and vecs.shape[0]:
            gen = coder.gen
            code_parts, assign_parts = [], []
            for s in segments:
                if getattr(s, "codec_gen", None) == gen \
                        and s.assign is not None:
                    assign_parts.append(np.asarray(s.assign))
                    code_parts.append(
                        np.asarray(s.codes) if s.codes is not None
                        else coder.encode(np.asarray(s.vectors)))
                else:
                    sv = np.asarray(s.vectors)
                    code_parts.append(coder.encode(sv))
                    assign_parts.append(coder.assign(sv))
            codes = np.concatenate(code_parts)
            assign = np.concatenate(assign_parts)
        name = f"seg-{self._seq:06d}"
        self._seq += 1
        if self.root is None:
            return FrozenSegment(name, ids, vecs, codes=codes,
                                 assign=assign, codec_gen=gen)
        return SealedSegment(_write_segment(
            self.root, name, ids, vecs, codes=codes, assign=assign,
            codec_gen=gen))

    def swap_sealed(self, olds: list, merged) -> None:
        """Replace ``olds`` (a prefix snapshot of ``sealed``) with
        ``merged`` (pointer-only; the caller deletes old dirs after)."""
        assert self.sealed[: len(olds)] == olds
        self.sealed = [merged] + self.sealed[len(olds):]

    @staticmethod
    def delete_segments(segments: list) -> None:
        for s in segments:
            path = getattr(s, "path", None)
            if path is not None:
                shutil.rmtree(path, ignore_errors=True)

    def compact(self):
        """Single-threaded convenience: merge every sealed segment and
        delete the inputs. Returns the merged segment."""
        if len(self.sealed) < 2:
            return None
        olds = list(self.sealed)
        merged = self.merge(olds)
        self.swap_sealed(olds, merged)
        self.delete_segments(olds)
        return merged

    # -- reads -------------------------------------------------------------
    @property
    def rows(self) -> int:
        pending = self.pending.rows if self.pending is not None else 0
        return self.mutable.rows + pending \
            + sum(s.rows for s in self.sealed)

    @property
    def segment_count(self) -> int:
        """Sealed segments + pending + the mutable tail (non-empty)."""
        return len(self.sealed) \
            + (1 if self.pending is not None else 0) \
            + (1 if self.mutable.rows else 0)

    def blocks(self):
        """Yield ``(ids, vectors)`` per segment.

        READ order (mutable → pending → sealed) is the mirror of the
        seal path's write order (see ``take_mutable``): a lock-free
        reader racing a seal may see the taken rows twice (pending +
        published), never zero times. Duplicates are a nanosecond-
        window transient on the pre-training brute-force path only;
        loss would be silent wrong answers."""
        mutable = self.mutable
        pending = self.pending
        sealed = list(self.sealed)
        for s in sealed:
            yield s.ids, s.vectors
        if pending is not None and pending.rows:
            yield pending.view()
        if mutable.rows:
            yield mutable.view()

    def all_rows(self) -> tuple[np.ndarray, np.ndarray]:
        """Materialize ``(ids, vectors)`` across every segment."""
        parts = list(self.blocks())
        if not parts:
            return (np.empty((0,), np.int64),
                    np.empty((0, self.dim), np.float32))
        return (np.concatenate([np.asarray(i) for i, _ in parts]),
                np.concatenate([np.asarray(v) for _, v in parts]))
