"""``ntxent-train``: command-line SimCLR pretraining driver.

The runtime config/flag surface for the framework (SURVEY.md §5.6: the
reference's only knobs were build-time CMake options,
/root/reference/CMakeLists.txt:9-16, plus per-call kwargs — it shipped no
way to actually launch the training its name promised). One command covers
the BASELINE.json config ladder: synthetic smoke runs, CIFAR-10 single
chip, ImageNet-layout folders on a data-parallel mesh, multi-host via
explicit coordinator flags (the mpirun role).

Everything here composes public API: datasets.TwoViewPipeline ->
create_mesh/global_batch -> make_train_step/make_sharded_train_step ->
fit under a PreemptionGuard (SIGTERM => checkpoint => clean exit => exact
resume on relaunch).
"""

from __future__ import annotations

import argparse
import functools
import logging
import os
import sys

logger = logging.getLogger("ntxent_tpu.cli")


MODEL_CHOICES = ["resnet18", "resnet34", "resnet50", "resnet50x2",
                 "resnet101", "resnet152", "vit_t16", "vit_s16",
                 "vit_b16", "vit_l16", "tiny"]


def _add_common_args(p: argparse.ArgumentParser) -> None:
    """Data/model/platform flags shared by ntxent-train and ntxent-eval
    (one source of truth: a model added here is launchable AND evaluable)."""
    d = p.add_argument_group("data")
    d.add_argument("--dataset", default="synthetic",
                   choices=["synthetic", "cifar10", "imagefolder", "npy"],
                   help="npy: memmap'd .npy (N, H, W, C) row store "
                        "(--data-dir points at the file; training only)")
    d.add_argument("--data-dir", default=None,
                   help="CIFAR-10 pickle dir / ImageNet-layout root / "
                        ".npy row store")
    d.add_argument("--image-size", type=int, default=None,
                   help="default: 32 (synthetic/cifar10), 224 "
                        "(imagefolder), or the npy store's row shape")
    d.add_argument("--loader", default="python",
                   choices=["python", "native"],
                   help="batch-gather engine: python = threaded "
                        "StreamingLoader; native = C++ worker pool over "
                        "the mmap'd store (npy dataset only)")

    m = p.add_argument_group("model")
    m.add_argument("--model", default="resnet50", choices=MODEL_CHOICES)
    m.add_argument("--stem", default="conv",
                   choices=["conv", "space_to_depth"],
                   help="ResNet ImageNet stem: space_to_depth runs the "
                        "7x7/s2 conv as an MXU-dense 4x4/s1 conv on "
                        "space-to-depth input (weight-compatible)")
    m.add_argument("--vit-attention", default="xla",
                   choices=["xla", "flash"],
                   help="ViT tower attention: 'flash' swaps the XLA "
                        "dot-product attention for the fused blockwise "
                        "Pallas kernel (weight-compatible; "
                        "models/vit.py:EncoderBlock)")
    m.add_argument("--proj-hidden-dim", type=int, default=2048)
    m.add_argument("--proj-dim", type=int, default=128)
    m.add_argument("--moe-experts", type=int, default=0,
                   help="ViT towers only (simclr encoder / clip image "
                        "tower): switch-MoE MLP with this many experts in "
                        "every other block (parallel/moe.py); 0 = dense")
    m.add_argument("--moe-aux-weight", type=float, default=0.01,
                   help="weight of the MoE load-balance aux loss when "
                        "--moe-experts > 0 (Switch Transformer default)")

    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None, metavar="cpu|tpu",
                   help="force a JAX platform before backend init")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ntxent-train",
        description="TPU-native SimCLR pretraining (fused NT-Xent loss)")
    _add_common_args(p)
    p.add_argument("--synthetic-samples", type=int, default=512)

    t = p.add_argument_group("training")
    t.add_argument("--objective", default="simclr",
                   choices=["simclr", "clip"],
                   help="simclr: two-view NT-Xent on --model. clip: "
                        "symmetric InfoNCE over a dual encoder (--model is "
                        "the image tower); --data-dir may point to an .npz "
                        "with 'images' and 'tokens' arrays, else synthetic "
                        "pairs")
    t.add_argument("--clip-parallel", default="dp", choices=["dp", "tp"],
                   help="clip multi-device strategy: dp = shard_map data "
                        "parallelism with the fused partial InfoNCE (the "
                        "production TPU path); tp = compiler-partitioned "
                        "(data, model) mesh for towers that need sharding "
                        "(set --model-par > 1 or nothing is model-sharded)")
    t.add_argument("--model-par", type=int, default=2,
                   help="tp runs (--parallel tp / --clip-parallel tp): "
                        "model-axis size of the (data, model) mesh; "
                        "device count must divide by it")
    t.add_argument("--tp-loss-axes", default="data",
                   choices=["data", "both"],
                   help="tp runs: mesh axes the fused loss shards over — "
                        "'data' (default; loss compute replicated across "
                        "'model') or 'both' (loss rows spread over every "
                        "device, one embedding reshard into the "
                        "shard_map; pays off at large per-step batch)")
    t.add_argument("--parallel", default="dp", choices=["dp", "tp"],
                   help="simclr multi-device strategy: dp = shard_map "
                        "data-parallel with the fused loss (default); "
                        "tp = compiler-partitioned (data, model) mesh "
                        "(Megatron sharding for ViT encoders; the fused "
                        "--dp-loss bodies run over 'data' inside the "
                        "GSPMD program) — composes with --fsdp into "
                        "Megatron + ZeRO-3")
    t.add_argument("--vocab-size", type=int, default=49408,
                   help="clip: text-tower vocabulary")
    t.add_argument("--token-len", type=int, default=None,
                   help="clip: tokenized caption length (derived from "
                        "--data-dir tokens when given; 77 for synthetic)")
    t.add_argument("--batch", type=int, default=256,
                   help="GLOBAL batch (split across devices and processes)")
    t.add_argument("--steps", type=int, default=1000)
    t.add_argument("--temperature", type=float, default=0.1)
    t.add_argument("--base-lr", type=float, default=0.3)
    t.add_argument("--weight-decay", type=float, default=1e-6)
    t.add_argument("--warmup-steps", type=int, default=100)
    t.add_argument("--accum-steps", type=int, default=1)
    t.add_argument("--fsdp", action="store_true",
                   help="fully-sharded data parallelism (ZeRO-3 via "
                        "GSPMD): shard params + optimizer moments over "
                        "the data axis instead of replicating them — "
                        "HBM capacity for ICI bandwidth; with "
                        "--dcn-slices > 1, hybrid ZeRO (params confined "
                        "to the intra-slice ICI axis, replicated across "
                        "slices) (parallel/fsdp.py)")
    t.add_argument("--dp-loss", default="strip",
                   choices=["strip", "pair", "chunked"],
                   help="data-parallel NT-Xent decomposition: 'strip' "
                        "(local rows x global cols per device), 'pair' "
                        "(balanced shard-pair schedule — each global "
                        "similarity tile computed once across the mesh), "
                        "or 'chunked' (ISSUE 19: chunked ring-overlap — "
                        "the embedding all-gather becomes ring-step "
                        "ppermute chunks whose transfers overlap the "
                        "similarity folds, same total wire bytes); "
                        "honored by the shard_map DP step and the "
                        "fused-loss FSDP and TP steps")
    t.add_argument("--ring-chunks", type=int, default=None, metavar="C",
                   help="per-hop chunk count for --dp-loss chunked "
                        "(default: the ops.autotune cached/heuristic "
                        "choice for the batch, dim and mesh; ignored "
                        "with a warning for other --dp-loss values)")
    t.add_argument("--measure-overlap", action="store_true",
                   help="before training, A/B the chunked vs monolithic "
                        "loss schedule on this backend and publish the "
                        "measured overlap window through the step "
                        "timeline (train_step_comms_overlap_ms / _frac "
                        "+ one comms_overlap event); an accelerator "
                        "effect — near zero on CPU, where the census "
                        "byte parity is the meaningful claim")
    t.add_argument("--collective-dtype", default="float32",
                   choices=["float32", "bf16", "int8"],
                   help="wire precision for the distributed step's "
                        "hand-written collectives (ISSUE 12): bf16 "
                        "halves the bytes; int8 quantizes embedding "
                        "gathers (straight-through gradients) and "
                        "gradient reductions (with error feedback — "
                        "the compression residual carries into the "
                        "next step, so the noise cannot bias SGD) for "
                        "a ~4x wire cut. Data-parallel multi-device "
                        "runs only (tp/fsdp collectives live in GSPMD)")
    t.add_argument("--remat", action="store_true",
                   help="rematerialize the encoder forward in the backward "
                        "pass (fits bigger batches in HBM at ~1 extra "
                        "forward of FLOPs)")
    t.add_argument("--ckpt-dir", default=None)
    t.add_argument("--ckpt-every", type=int, default=500)
    t.add_argument("--async-ckpt", action="store_true",
                   help="asynchronous checkpointing: snapshot to host and "
                        "hand serialization/fsync to a bounded background "
                        "writer (the loop blocks only when a save is "
                        "already in flight); SIGTERM/preemption still "
                        "force a synchronous emergency save")
    t.add_argument("--ckpt-keep-last", type=int, default=3,
                   metavar="K",
                   help="retention: keep the newest K checkpoint steps "
                        "(0 keeps everything); the newest VALID step is "
                        "never garbage-collected")
    t.add_argument("--ckpt-keep-every", type=int, default=None,
                   metavar="N",
                   help="retention: additionally keep every step "
                        "divisible by N as a long-horizon anchor")
    t.add_argument("--restore-step", type=int, default=None, metavar="N",
                   help="resume from this exact historical checkpoint "
                        "step instead of the newest valid one (same "
                        "mirror-fallback semantics as normal restore; a "
                        "step present in no replica fails loudly). "
                        "Rewind semantics: checkpoint steps NEWER than N "
                        "are deleted (both replicas, logged) so the "
                        "replayed lineage owns the timeline — its saves "
                        "land, and a crash mid-replay resumes the replay, "
                        "not the abandoned future. A supervised run "
                        "applies this to its FIRST attempt only")
    t.add_argument("--ckpt-save-ef", action="store_true",
                   help="persist the quantized-collective error-feedback "
                        "residual in checkpoints (P-stacked f32 copy of "
                        "every param — P x the param payload per save). "
                        "Off by default: restore falls back to a zero "
                        "residual, which a topology change forces anyway")
    t.add_argument("--ckpt-mirror", default=None, metavar="DIR",
                   help="replicate every checkpoint to DIR (atomic copy "
                        "after each save); restore falls back to the "
                        "mirror when the primary copy is corrupt or "
                        "missing")
    t.add_argument("--log-every", type=int, default=50)
    def _positive_float(text: str) -> float:
        try:
            value = float(text)
        except ValueError:
            raise argparse.ArgumentTypeError(f"not a number: {text!r}")
        if value <= 0:
            raise argparse.ArgumentTypeError(
                f"must be a positive number of seconds, got {text!r}")
        return value

    t.add_argument("--stall-timeout", type=_positive_float, default=None,
                   metavar="SECONDS",
                   help="failure detection: if no train step completes for "
                        "this long, dump all thread stacks (where is it "
                        "stuck) and log the stall; with --max-restarts the "
                        "supervisor escalates (stop at a step boundary, "
                        "checkpoint, restart in-process), otherwise the "
                        "run is left alive for external supervision")

    perf = p.add_argument_group("async input pipeline "
                                "(ntxent_tpu/training/data.py)")
    perf.add_argument("--prefetch", type=int, default=0, metavar="DEPTH",
                      help="device-side prefetch: keep DEPTH batches "
                           "transferring to the device (committed to the "
                           "run's mesh sharding) under the running step "
                           "instead of placing each batch on the critical "
                           "path; 2-3 is plenty (double/triple buffering), "
                           "0 = off")
    perf.add_argument("--lag-metrics", action="store_true",
                      help="lag-1 metrics drain: read step N-1's loss/"
                           "grad_norm/step_ok while step N runs, so "
                           "--nan-policy guards and telemetry "
                           "(--metrics-port/--log-jsonl) stop syncing "
                           "host and device every step; divergence "
                           "handling runs exactly one step late (never "
                           "missed — the jit-side guard already kept the "
                           "bad update out of the params)")

    r = p.add_argument_group("resilience (self-healing runs; "
                             "ntxent_tpu/resilience/)")
    r.add_argument("--max-restarts", type=int, default=0,
                   help="supervise the run (resilience.Supervisor): on a "
                        "crash, divergence rollback, SIGTERM, or stall, "
                        "restart in-process from the newest VALID "
                        "checkpoint (--ckpt-dir) up to N times with "
                        "exponential backoff; 0 = single attempt")
    r.add_argument("--nan-policy", default="off",
                   choices=["off", "skip", "backoff", "rollback"],
                   help="in-step divergence guard: 'skip' drops non-finite "
                        "updates (params/opt-state untouched, step still "
                        "advances); 'backoff' also halves the gradient "
                        "scale on repeated skips; 'rollback' also aborts "
                        "to the last valid checkpoint once the skip budget "
                        "is spent (pair with --max-restarts); 'off' = "
                        "unguarded fast path (no per-step host sync)")
    r.add_argument("--no-ckpt-verify", action="store_true",
                   help="skip per-save checkpoint CRC manifests (saves "
                        "stay fully async; restore can no longer detect "
                        "torn/corrupt checkpoints and fall back to a "
                        "valid one)")
    r.add_argument("--chaos", default=None, metavar="SPEC",
                   help="deterministic fault injection "
                        "(resilience.FaultPlan), comma list of "
                        "kind@ordinal: nan@K poisons the K-th batch, "
                        "sigterm@K / kill@K (SIGKILL, no cleanup) / "
                        "crash@K fire at the K-th batch, fetch@N raises "
                        "a transient error on the N-th source read, "
                        "diskfull@N raises ENOSPC on the N-th checkpoint "
                        "write, truncate@A corrupts the newest "
                        "checkpoint after attempt A; implies supervision "
                        "(uses --max-restarts attempts)")

    o = p.add_argument_group("observability (ntxent_tpu/obs/: metrics "
                             "registry, JSONL event log, profiler)")
    o.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                   help="serve the metrics registry over HTTP on this "
                        "port (/metrics: Prometheus text, ?format=json "
                        "for JSON; /healthz); 0 picks a free port "
                        "(logged at startup)")
    o.add_argument("--log-jsonl", default=None, metavar="PATH",
                   help="append typed JSONL events (step timeline, "
                        "retries, divergence, restarts, checkpoints, "
                        "compiles, traces) to this file")
    o.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="arm on-demand jax.profiler capture into DIR: a "
                        "step slower than --slow-step-factor x the "
                        "rolling median (or touching DIR/TRIGGER, or "
                        "SIGUSR2) captures the next --trace-steps steps")
    o.add_argument("--trace-steps", type=int, default=5,
                   help="steps per profiler capture window")
    o.add_argument("--slow-step-factor", type=float, default=3.0,
                   help="slow-step trigger threshold (x rolling median "
                        "device time; warmup/compile steps never fire it)")

    dist = p.add_argument_group("distributed (multi-host rendezvous; "
                                "single-host multi-chip needs no flags)")
    dist.add_argument("--dcn-slices", type=int, default=1,
                      help="multi-slice pods: split the data axis across "
                           "this many slices (DCN) with the per-slice "
                           "chips innermost on ICI "
                           "(parallel.create_hybrid_mesh); 1 = single "
                           "slice / flat mesh")
    dist.add_argument("--coordinator", default=None,
                      help="host:port of process 0 (mpirun role; "
                           "auto-detected on Cloud TPU)")
    dist.add_argument("--num-processes", type=int, default=None)
    dist.add_argument("--process-id", type=int, default=None)
    return p


def _npy_store_shape(args) -> tuple:
    """Validate --dataset npy flags and return the store's array shape
    (single source of truth for both the pipeline and image-size logic)."""
    import numpy as np

    if args.data_dir is None:
        raise SystemExit("--dataset npy requires --data-dir")
    return np.load(args.data_dir, mmap_mode="r").shape


def _make_encoder(name: str, image_size: int, moe_experts: int = 0,
                  stem: str = "conv", vit_attention: str = "xla",
                  axis_name: str | None = None):
    """``axis_name``: mesh data axis for cross-replica BatchNorm
    statistics in ResNet encoders (the dp shard_map branch passes
    "data"). Global-batch BN is both the SimCLR recipe and what makes
    the sharded loss DEVICE-COUNT INVARIANT — the property elastic
    shrink/grow restores are audited against (a per-shard-local BN
    normalizes over batch/P rows, so the same global batch would produce
    a different loss on a different mesh size). ViT encoders use
    LayerNorm (per-row) and ignore it."""
    from ntxent_tpu import models

    if moe_experts > 0 and not name.startswith("vit"):
        raise SystemExit("--moe-experts requires a ViT model")
    if stem != "conv" and not name.startswith("resnet"):
        raise SystemExit(f"--stem {stem} applies to ResNet encoders only "
                         f"(got --model {name}); it would be silently "
                         "ignored")
    if vit_attention != "xla" and not name.startswith("vit"):
        raise SystemExit(f"--vit-attention {vit_attention} applies to ViT "
                         f"encoders only (got --model {name}); it would "
                         "be silently ignored")
    if name == "tiny":
        return functools.partial(models.ResNet, stage_sizes=(1,),
                                 small_images=True, axis_name=axis_name)
    table = {
        "resnet18": models.ResNet18, "resnet34": models.ResNet34,
        "resnet50": models.ResNet50, "resnet50x2": models.ResNet50x2,
        "resnet101": models.ResNet101, "resnet152": models.ResNet152,
        "vit_t16": models.ViT_Ti16, "vit_s16": models.ViT_S16,
        "vit_b16": models.ViT_B16, "vit_l16": models.ViT_L16,
    }
    enc = table[name]
    if name.startswith("resnet") and image_size <= 64:
        if stem != "conv":
            raise SystemExit(
                f"--stem {stem} applies to the ImageNet stem only; "
                f"--image-size {image_size} selects the small-images "
                "(3x3/s1) stem, which would silently ignore it")
        enc = functools.partial(enc, small_images=True)
    elif name.startswith("resnet") and stem != "conv":
        # MXU-dense ImageNet stem (weight-compatible with the plain one;
        # models/resnet.py:SpaceToDepthStem).
        enc = functools.partial(enc, stem=stem)
    if name.startswith("resnet") and axis_name is not None:
        enc = functools.partial(enc, axis_name=axis_name)
    if moe_experts > 0:
        enc = functools.partial(enc, moe_experts=moe_experts)
    if vit_attention != "xla":
        # Weight-compatible fused-kernel attention (models/vit.py:
        # EncoderBlock.attention_impl).
        enc = functools.partial(enc, attention_impl=vit_attention)
    return enc


def _data_mesh(args, fsdp: bool = False):
    """The data mesh for DP/FSDP runs: flat, or hybrid DCN x ICI when
    --dcn-slices > 1 (slice-aware device order on multi-slice pods).

    DP keeps the hybrid layout as ONE combined 'data' axis (its only
    collectives are the once-per-step bulky all-gather/psum, which may
    span DCN). FSDP instead gets distinct ('dcn', 'data') axes so
    parameter shards can ride the intra-slice ICI axis alone — the
    per-layer weight all-gathers GSPMD inserts at use are frequent and
    latency-sensitive, exactly the traffic create_hybrid_mesh's layout
    rule says must not cross DCN (ADVICE r3 #1; hybrid ZeRO in
    parallel/fsdp.py)."""
    from ntxent_tpu.parallel import create_hybrid_mesh, create_mesh

    n = getattr(args, "dcn_slices", 1)
    if n and n > 1:
        import jax as _jax

        if _jax.device_count() % n:
            raise SystemExit(f"--dcn-slices {n} must divide the "
                             f"{_jax.device_count()} devices")
        per_slice = _jax.device_count() // n
        if fsdp:
            return create_hybrid_mesh((1, per_slice), (n, 1),
                                      axis_names=("dcn", "data"))
        return create_hybrid_mesh((per_slice,), (n,),
                                  axis_names=("data",))
    return create_mesh(axis_names=("data",))


def _log_hybrid_zero(mesh):
    """One line naming the hybrid-ZeRO layout when the FSDP mesh has a
    DCN axis (shared by the SimCLR and CLIP --fsdp branches)."""
    if len(mesh.axis_names) > 1:
        logger.info("hybrid ZeRO: params sharded over ICI axis 'data' "
                    "(size %d), replicated across %d slices",
                    mesh.shape["data"], mesh.shape["dcn"])


def _make_injector(args):
    """FaultInjector from --chaos, or None (parse errors fail loudly
    before any backend work)."""
    if not getattr(args, "chaos", None):
        return None
    from ntxent_tpu.resilience import FaultInjector, FaultPlan

    try:
        plan = FaultPlan.parse(args.chaos, seed=args.seed)
    except ValueError as e:
        raise SystemExit(f"--chaos: {e}")
    logger.warning("chaos mode: %s", plan)
    return FaultInjector(plan)


class _ObsContext:
    """What --metrics-port/--log-jsonl/--trace-dir wired up (inert when
    none was given); closed by _run_fit's epilogue."""

    def __init__(self):
        self.event_log = None
        self.server = None
        self.profiler = None
        self.timeline = None

    def close(self) -> None:
        if self.timeline is not None:
            self.timeline.close()  # ends any in-flight profiler capture
        if self.server is not None:
            self.server.close()
        if self.event_log is not None:
            from ntxent_tpu import obs

            obs.install(None)
            self.event_log.close()


def _setup_observability(args) -> _ObsContext:
    """Telemetry wiring from the observability flag group.

    Any one flag turns the layer on: an EventLog is installed process-
    wide (so resilience/checkpoint instrumentation publishes even when
    only --metrics-port was given — their counters need the registry,
    their events need a log) and a StepTimeline is handed to the train
    loop. With no flag at all, training keeps the zero-per-step-sync
    fast path: no timeline, no block_until_ready per step.
    """
    ctx = _ObsContext()
    metrics_port = getattr(args, "metrics_port", None)
    log_jsonl = getattr(args, "log_jsonl", None)
    trace_dir = getattr(args, "trace_dir", None)
    if metrics_port is None and not log_jsonl and not trace_dir:
        return ctx
    from ntxent_tpu import obs

    ctx.event_log = obs.EventLog(log_jsonl)  # path None: in-memory tail
    obs.install(ctx.event_log)
    logger.info("telemetry: run_id=%s%s", ctx.event_log.run_id,
                f" events -> {log_jsonl}" if log_jsonl else "")
    if metrics_port is not None:
        ctx.server = obs.MetricsServer(port=metrics_port).start()
    if trace_dir:
        ctx.profiler = obs.ProfilerTrigger(
            trace_dir, slow_factor=args.slow_step_factor,
            capture_steps=args.trace_steps)
        ctx.profiler.install_sigusr2()
        logger.info("profiler armed: traces -> %s (touch %s or SIGUSR2 "
                    "for a manual capture)", trace_dir,
                    ctx.profiler.trigger_file)
    ctx.timeline = obs.StepTimeline(profiler=ctx.profiler)
    return ctx


def _make_step_guard(nan_policy: str):
    """resilience.DivergenceGuard for --nan-policy (None for 'off')."""
    if nan_policy == "off":
        return None
    from ntxent_tpu.resilience import DivergenceGuard

    if nan_policy == "skip":
        return DivergenceGuard(backoff_after=None, rollback_after=None)
    if nan_policy == "backoff":
        return DivergenceGuard(rollback_after=None)
    return DivergenceGuard()  # rollback: every tier armed


def _make_pipeline(args, per_process_batch: int, sharding=None, mesh=None,
                   injector=None):
    import numpy as np

    import jax

    from ntxent_tpu.resilience import RetryPolicy
    from ntxent_tpu.training.datasets import (
        ArraySource,
        Cifar10Source,
        GlobalTwoViewPipeline,
        ImageFolderSource,
        StreamingLoader,
        TwoViewPipeline,
    )

    size = args.image_size
    if args.dataset == "cifar10":
        if args.data_dir is None:
            raise SystemExit("--dataset cifar10 requires --data-dir")
        source = Cifar10Source(args.data_dir)
    elif args.dataset == "imagefolder":
        if args.data_dir is None:
            raise SystemExit("--dataset imagefolder requires --data-dir")
        source = ImageFolderSource(args.data_dir, image_size=size)
    elif args.dataset == "npy":
        # --data-dir presence/readability already validated by main()'s
        # _npy_store_shape call (which also pinned image_size).
        source = ArraySource(np.load(args.data_dir, mmap_mode="r"))
    else:
        rng = np.random.RandomState(args.seed)
        source = ArraySource(rng.rand(
            args.synthetic_samples, size, size, 3).astype(np.float32))
    # Multi-process: each process streams ITS slice of every global batch
    # (seeded identically, offset by process_id — the per-rank DataLoader).
    # Fetches retry transient IO errors (resilience/retry.py); --chaos
    # fetch@N faults inject against exactly this path.
    fetch_retry = RetryPolicy(max_attempts=3, base_delay_s=0.1,
                              max_delay_s=5.0, seed=args.seed)
    if args.loader == "native":
        if injector is not None and injector.plan.fetch_calls:
            logger.warning("--chaos fetch@N ignored: the native engine "
                           "reads the mmap'd file directly (no per-item "
                           "__getitem__ to inject into)")
        from ntxent_tpu.training.native_loader import NativeStreamingLoader

        try:
            loader = NativeStreamingLoader(
                source, per_process_batch, seed=args.seed,
                shard_index=jax.process_index(),
                shard_count=jax.process_count(),
                retry_policy=fetch_retry)
        except (TypeError, ValueError, OSError, RuntimeError) as e:
            # not-a-memmap source AND native-build failures (no compiler,
            # cmake error) both land here: one clean exit, no traceback.
            raise SystemExit(f"--loader native: {e}")
    else:
        if injector is not None:
            source = injector.wrap_source(source)
        loader = StreamingLoader(source, per_process_batch, seed=args.seed,
                                 shard_index=jax.process_index(),
                                 shard_count=jax.process_count(),
                                 retry_policy=fetch_retry)
    key = jax.random.PRNGKey(args.seed + 1)
    if mesh is not None and jax.process_count() > 1:
        # Global assembly before augmentation: only raw bytes cross the
        # host boundary, views are born sharded (one replicated program —
        # same key everywhere; per-row randomness is global-position-based).
        return GlobalTwoViewPipeline(loader, key=key, mesh=mesh)
    return TwoViewPipeline(loader, key=key, sharding=sharding)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    # Rendezvous BEFORE any backend touch (explicit flags or cloud
    # auto-detect; a plain single-process run is a logged no-op).
    from ntxent_tpu.parallel.mesh import (
        create_mesh, init_distributed, process_info)

    init_distributed(coordinator_address=args.coordinator,
                     num_processes=args.num_processes,
                     process_id=args.process_id)
    info = process_info()
    logger.info("topology: %s", info)

    if args.batch % info["global_device_count"]:
        raise SystemExit(
            f"--batch {args.batch} must divide across "
            f"{info['global_device_count']} devices")
    per_process_batch = args.batch // info["process_count"]

    injector = _make_injector(args)

    if args.objective == "clip":
        # image_size stays None here: the clip branch derives it from the
        # paired data, and a conflicting EXPLICIT flag must fail loudly.
        if args.dp_loss != "strip":
            logger.warning("--dp-loss %s ignored: the CLIP objective uses "
                           "the InfoNCE loss family (see --clip-parallel)",
                           args.dp_loss)
        if args.loader != "python":
            logger.warning("--loader %s ignored: the CLIP objective uses "
                           "PairedArrayLoader", args.loader)
        if args.parallel != "dp":
            logger.warning("--parallel %s ignored: the CLIP objective "
                           "uses --clip-parallel for its strategy",
                           args.parallel)
        if args.tp_loss_axes != "data" and args.clip_parallel != "tp":
            logger.warning("--tp-loss-axes %s ignored: only "
                           "--clip-parallel tp runs shard the loss over "
                           "the model axis", args.tp_loss_axes)
        return _train_clip(args, info, per_process_batch,
                           injector=injector)
    if args.dataset == "npy":
        # No resize path exists for the raw row store: the model MUST be
        # built at the store's native resolution.
        store_size = int(_npy_store_shape(args)[1])
        if args.image_size is not None and args.image_size != store_size:
            raise SystemExit(
                f"--image-size {args.image_size} disagrees with the npy "
                f"store's row shape ({store_size}); omit the flag or "
                f"re-export the store")
        args.image_size = store_size
    elif args.image_size is None:
        args.image_size = 224 if args.dataset == "imagefolder" else 32

    from ntxent_tpu.models import SimCLRModel
    from ntxent_tpu.training import (
        TrainerConfig,
        create_train_state,
        make_train_step,
    )
    from ntxent_tpu.training.trainer import make_sharded_train_step

    # Cross-replica BatchNorm on the plain data-parallel branch: the
    # model forward runs inside shard_map there, so BN can psum its
    # batch statistics over 'data' — global-batch normalization (the
    # SimCLR recipe) AND device-count-invariant math, which is what lets
    # the elastic audit hold a shrunken/regrown run's loss curve against
    # a fixed-mesh reference. The TP/FSDP branches run the forward under
    # GSPMD (no named axis in scope) and keep local stats.
    dp_bn_axis = "data" if (info["global_device_count"] > 1
                            and args.parallel == "dp"
                            and not args.fsdp) else None
    encoder = _make_encoder(args.model, args.image_size,
                            moe_experts=args.moe_experts,
                            stem=args.stem,
                            vit_attention=args.vit_attention,
                            axis_name=dp_bn_axis)
    model = SimCLRModel(encoder=encoder,
                        proj_hidden_dim=args.proj_hidden_dim,
                        proj_dim=args.proj_dim,
                        axis_name=dp_bn_axis)
    moe_aux = args.moe_aux_weight if args.moe_experts > 0 else 0.0
    cfg = TrainerConfig(
        batch_size=args.batch, temperature=args.temperature,
        base_lr=args.base_lr, weight_decay=args.weight_decay,
        warmup_steps=args.warmup_steps, total_steps=args.steps,
        accum_steps=args.accum_steps)

    def base_state():
        return create_train_state(
            model, jax.random.PRNGKey(args.seed),
            (1, args.image_size, args.image_size, 3), cfg)

    state = base_state()
    # Per-branch state placement, captured so a supervised restart can
    # rebuild a FRESH template (a crashed attempt's donated buffers must
    # not be reused as a restore template; resilience/supervisor.py).
    prepare_state = lambda s: s  # noqa: E731
    # Elastic rebuild seam, set by the data-parallel branch only (the
    # one whose world is rebuildable over a device subset in-process).
    elastic_builder = None
    # Overlap A/B capture (--measure-overlap), set by the data-parallel
    # branch only — the one whose loss owns the chunked ring schedule.
    overlap_probe = None
    nan_policy = args.nan_policy
    guard_steps = nan_policy != "off"

    n_dev = info["global_device_count"]
    if args.tp_loss_axes != "data" and not (n_dev > 1
                                            and args.parallel == "tp"):
        # Same silent-drop hole the step factories guard against for
        # loss_axes + oracle: an A/B that forgot --parallel tp would
        # compare two identical configs without noticing.
        logger.warning("--tp-loss-axes %s ignored: only --parallel tp "
                       "runs shard the loss over the model axis",
                       args.tp_loss_axes)
    if n_dev > 1 and args.parallel == "tp":
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ntxent_tpu.parallel import (
            make_tp_simclr_train_step,
            shard_train_state,
            shard_train_state_tp_fsdp,
            tp_fsdp_spec_fn,
        )

        if getattr(args, "dcn_slices", 1) > 1:
            raise SystemExit("--dcn-slices > 1 does not compose with "
                             "--parallel tp yet (the TP mesh has no "
                             "'dcn' axis); use --parallel dp")
        if args.moe_experts > 0:
            raise SystemExit("--parallel tp does not collect the MoE "
                             "aux loss (make_tp_simclr_train_step); use "
                             "--parallel dp for MoE encoders")
        if n_dev % args.model_par:
            raise SystemExit(f"--model-par {args.model_par} must divide "
                             f"{n_dev} devices")
        if not args.model.startswith("vit"):
            logger.warning("--parallel tp shards transformer weights "
                           "only; --model %s keeps everything replicated "
                           "over the model axis", args.model)
        mesh = create_mesh(shape=(n_dev // args.model_par,
                                  args.model_par),
                           axis_names=("data", "model"))
        has_bs = bool(jax.tree_util.tree_leaves(state.batch_stats))
        if guard_steps:
            logger.warning("--nan-policy %s ignored: the GSPMD TP step "
                           "carries no in-step divergence guard yet; use "
                           "--parallel dp for guarded runs", nan_policy)
            nan_policy, guard_steps = "off", False
        if args.collective_dtype != "float32":
            logger.warning("--collective-dtype %s ignored: the TP step's "
                           "collectives are GSPMD compiler-inserted, not "
                           "the quantizable mesh shims; use --parallel dp",
                           args.collective_dtype)
        if args.fsdp:
            prepare_state = lambda s: shard_train_state_tp_fsdp(s, mesh)  # noqa: E731,E501
            spec_fn = tp_fsdp_spec_fn(mesh)
            logger.info("SimCLR GSPMD Megatron + ZeRO-3 on the (%d, %d) "
                        "(data, model) mesh",
                        n_dev // args.model_par, args.model_par)
        else:
            prepare_state = lambda s: shard_train_state(s, mesh)  # noqa: E731,E501
            spec_fn = None
            logger.info("SimCLR GSPMD (%d, %d) (data, model) mesh",
                        n_dev // args.model_par, args.model_par)
        state = prepare_state(state)
        # --dp-loss strip/pair is honored under TP too (round 5: the TP
        # step embeds the fused shard_map bodies over 'data', or over
        # both mesh axes with --tp-loss-axes both).
        loss_axes = (("data", "model") if args.tp_loss_axes == "both"
                     else None)
        step = make_tp_simclr_train_step(mesh, cfg.temperature,
                                         has_batch_stats=has_bs,
                                         remat=args.remat,
                                         loss_impl=args.dp_loss,
                                         loss_axes=loss_axes,
                                         param_spec_fn=spec_fn)
        batch_sharding = NamedSharding(mesh, P("data"))
        data = _make_pipeline(args, per_process_batch,
                              sharding=batch_sharding,
                              mesh=mesh, injector=injector)
    elif n_dev > 1 and args.fsdp:
        from ntxent_tpu.parallel import (
            make_fsdp_train_step,
            shard_train_state_fsdp,
        )
        from ntxent_tpu.parallel.mesh import data_sharding

        mesh = _data_mesh(args, fsdp=True)
        has_bs = bool(jax.tree_util.tree_leaves(state.batch_stats))
        if guard_steps:
            logger.warning("--nan-policy %s ignored: the FSDP step "
                           "carries no in-step divergence guard yet; "
                           "drop --fsdp for guarded runs", nan_policy)
            nan_policy, guard_steps = "off", False
        if args.collective_dtype != "float32":
            logger.warning("--collective-dtype %s ignored: the FSDP "
                           "step's parameter/gradient collectives are "
                           "GSPMD compiler-inserted, not the quantizable "
                           "mesh shims; drop --fsdp",
                           args.collective_dtype)
        # The fused shard_map NT-Xent runs INSIDE the GSPMD step, so
        # --dp-loss strip/pair is honored under FSDP (round 4; the
        # pre-round-4 oracle loss remains as loss_impl="oracle").
        step = make_fsdp_train_step(mesh, cfg.temperature,
                                    remat=args.remat,
                                    has_batch_stats=has_bs,
                                    loss_impl=args.dp_loss,
                                    moe_aux_weight=moe_aux)
        prepare_state = lambda s: shard_train_state_fsdp(s, mesh)  # noqa: E731,E501
        state = prepare_state(state)
        batch_sharding = data_sharding(mesh, tuple(mesh.axis_names))
        data = _make_pipeline(args, per_process_batch,
                              sharding=batch_sharding,
                              mesh=mesh, injector=injector)
        _log_hybrid_zero(mesh)
        logger.info("FSDP (ZeRO-3, %s loss) over %d devices "
                    "(%d process(es))",
                    args.dp_loss, n_dev, info["process_count"])
    elif n_dev > 1:
        from ntxent_tpu.parallel.mesh import data_sharding, replicate_state
        from ntxent_tpu.training import init_error_feedback

        mesh = _data_mesh(args)
        ring_chunks = args.ring_chunks if args.dp_loss == "chunked" else None
        if args.ring_chunks is not None and args.dp_loss != "chunked":
            logger.warning("--ring-chunks %d ignored: --dp-loss %s has no "
                           "ring chunks (use --dp-loss chunked)",
                           args.ring_chunks, args.dp_loss)
        step = make_sharded_train_step(mesh, cfg.temperature,
                                       remat=args.remat,
                                       loss_impl=args.dp_loss,
                                       moe_aux_weight=moe_aux,
                                       guard=guard_steps,
                                       collective_dtype=args.collective_dtype,
                                       ring_chunks=ring_chunks)
        if args.measure_overlap:
            from ntxent_tpu.training.trainer import measure_comms_overlap

            _mesh_probe, _nl = mesh, args.batch // n_dev

            def overlap_probe(tl):
                return measure_comms_overlap(
                    _mesh_probe, _nl, args.proj_dim,
                    temperature=cfg.temperature,
                    ring_chunks=ring_chunks, timeline=tl)
        if args.collective_dtype != "float32":
            logger.info("quantized collectives: %s wire payloads%s",
                        args.collective_dtype,
                        " + gradient error feedback"
                        if args.collective_dtype == "int8" else "")
        # Commit params/opt-state replicated on the mesh BEFORE fit's
        # checkpoint restore: a fresh template restores committed to one
        # device and the sharded step then rejects the device mismatch.
        # int8 runs also carry the error-feedback residual in the state
        # (zero-initialized; per-device slice via the stacked leading
        # axis), so checkpoints persist it.
        if args.collective_dtype == "int8":
            prepare_state = lambda s: init_error_feedback(  # noqa: E731
                replicate_state(s, mesh), mesh)
        else:
            prepare_state = lambda s: replicate_state(s, mesh)  # noqa: E731,E501
        state = prepare_state(state)
        # Batches arrive already sharded over the mesh: single-process via
        # sharded device_put + sharded augmentation, multi-process via
        # GlobalTwoViewPipeline's uint8 global assembly.
        batch_sharding = data_sharding(mesh)
        data = _make_pipeline(args, per_process_batch,
                              sharding=batch_sharding, mesh=mesh,
                              injector=injector)
        logger.info("data-parallel over %d devices (%d process(es))",
                    n_dev, info["process_count"])

        if info["process_count"] == 1 and getattr(args, "dcn_slices", 1) <= 1:
            # Elastic seam (shrink@K/grow@K): rebuild the whole dp world
            # over a device subset. Single-process flat meshes only — a
            # multi-process pool changes membership at the process level
            # (relaunch; crashsim drives that boundary), and hybrid
            # DCN meshes shrink by slices, not by arbitrary halving.
            def topology_builder(n_active):
                devices = jax.devices()[:n_active]
                mesh_n = create_mesh(devices=devices,
                                     axis_names=("data",))
                step_n = make_sharded_train_step(
                    mesh_n, cfg.temperature, remat=args.remat,
                    loss_impl=args.dp_loss, moe_aux_weight=moe_aux,
                    guard=guard_steps,
                    collective_dtype=args.collective_dtype)
                sharding_n = data_sharding(mesh_n)
                data_n = _make_pipeline(args, per_process_batch,
                                        sharding=sharding_n, mesh=mesh_n,
                                        injector=injector)
                if args.collective_dtype == "int8":
                    # The residual re-stacks to the NEW device count;
                    # restore resets a mismatched saved residual to
                    # zeros (checkpoint._from_bytes_tolerant).
                    factory_n = lambda: init_error_feedback(  # noqa: E731
                        replicate_state(base_state(), mesh_n), mesh_n)
                else:
                    factory_n = lambda: replicate_state(  # noqa: E731
                        base_state(), mesh_n)
                return data_n, step_n, factory_n, sharding_n

            elastic_builder = topology_builder
    else:
        if args.fsdp:
            logger.warning("--fsdp ignored: single-device run has nothing "
                           "to shard over")
        if args.parallel != "dp":
            logger.warning("--parallel %s ignored: single-device run has "
                           "no model axis", args.parallel)
        if args.dp_loss != "strip":
            logger.warning("--dp-loss %s ignored: single-device run has "
                           "no shard-pair schedule", args.dp_loss)
        if args.collective_dtype != "float32":
            logger.warning("--collective-dtype %s ignored: single-device "
                           "run issues no collectives",
                           args.collective_dtype)
        step = make_train_step(cfg.temperature, remat=args.remat,
                               moe_aux_weight=moe_aux, guard=guard_steps)
        batch_sharding = None
        data = _make_pipeline(args, per_process_batch, injector=injector)
        logger.info("single-device run")

    if args.measure_overlap and overlap_probe is None:
        logger.warning("--measure-overlap ignored: the overlap A/B "
                       "measures the data-parallel shard_map loss "
                       "schedule (multi-device --parallel dp, no --fsdp)")
    return _run_fit(data, state, step, args,
                    state_factory=lambda: prepare_state(base_state()),
                    step_guard=_make_step_guard(nan_policy),
                    injector=injector, sharding=batch_sharding,
                    topology_builder=elastic_builder,
                    overlap_probe=overlap_probe)


def _log_final(history) -> None:
    if history:
        last = history[-1]
        logger.info("final: step %d loss %.4f (%.2f steps/s%s)",
                    last["step"], last["loss"], last["steps_per_sec"],
                    f", MFU {last['mfu']:.1%}" if "mfu" in last else "")


def _run_fit(data, state, step, args, state_factory=None, step_guard=None,
             injector=None, sharding=None, topology_builder=None,
             overlap_probe=None) -> int:
    """Shared training epilogue for both objectives.

    Unsupervised (default): one preemption-guarded ``fit`` — SIGTERM means
    checkpoint-and-exit for an external relauncher. With --max-restarts or
    --chaos: ``resilience.Supervisor`` runs attempts of the same ``fit``
    and restarts in-process from the newest valid checkpoint on any
    detected fault (crash, divergence rollback, SIGTERM, stall).

    ``sharding`` is the run's batch ``NamedSharding`` (None on a single
    device): with --prefetch it binds the DevicePrefetcher to the mesh so
    batches arrive as committed global arrays (training/data.py).

    ``topology_builder(n_active) -> (data, step, state_factory,
    sharding)`` is the elastic seam (data-parallel branch only): when a
    supervised attempt dies with a ``TopologyChange`` (chaos
    ``shrink@K``/``grow@K``, or a resource manager surfacing a pool
    change), the supervisor's topology hook calls it to rebuild the
    world over the new device count — shrink halves the active devices
    (skipping counts the batch does not divide), grow restores the full
    set — and the next attempt restores the newest checkpoint onto the
    rebuilt mesh (the checkpoint topology sidecar makes that a re-shard).
    """
    import contextlib

    from ntxent_tpu.resilience import RetryPolicy
    from ntxent_tpu.training import PreemptionGuard, fit
    from ntxent_tpu.utils import StallWatchdog

    prefetch_depth = getattr(args, "prefetch", 0) or 0
    restore_step = getattr(args, "restore_step", None)

    def wrap_data(raw, shard):
        """The run's data-side wrappers, reapplied on every topology
        rebuild: device prefetch innermost (chaos injection stays
        consumer-aligned; the checkpointable state()/restore() chain
        passes through)."""
        if prefetch_depth <= 0:
            return raw
        import jax

        from ntxent_tpu.training.data import DevicePrefetcher

        if jax.process_count() > 1:
            # Multi-process pipelines (GlobalTwoViewPipeline / the CLIP
            # global_batch path) assemble COMMITTED global arrays with
            # their own per-axis layout; binding a second sharding here
            # would eagerly device_put non-fully-addressable arrays onto
            # a possibly different spec every batch. sharding=None makes
            # the prefetcher pure read-ahead: placed leaves pass through.
            shard = None
        wrapped = DevicePrefetcher(raw, depth=prefetch_depth,
                                   sharding=shard)
        logger.info("device prefetch: depth %d%s", prefetch_depth,
                    f" onto {shard}" if shard is not None else "")
        return wrapped

    data = wrap_data(data, sharding)
    metrics_lag = 1 if getattr(args, "lag_metrics", False) else 0
    if metrics_lag:
        logger.info("lag-1 metrics drain: guard/telemetry reads run one "
                    "step behind dispatch")

    obs_ctx = _setup_observability(args)
    timeline = obs_ctx.timeline
    if overlap_probe is not None:
        # One pre-training A/B (--measure-overlap): the wall clock the
        # chunked ring schedule hides on THIS backend, published through
        # the timeline (trainer.measure_comms_overlap). Best-effort —
        # a capture failure must not stop training.
        try:
            res = overlap_probe(timeline)
            logger.info(
                "comms overlap A/B on %s: monolithic %.3f ms vs chunked "
                "%.3f ms (%d chunks) -> overlap %.3f ms (%.1f%%)",
                res["backend"], res["monolithic_ms"], res["chunked_ms"],
                res["chunks"], res["overlap_ms"],
                100.0 * res["overlap_frac"])
        except Exception:  # noqa: BLE001 — telemetry, not training
            logger.warning("comms-overlap capture failed", exc_info=True)
    keep_last = getattr(args, "ckpt_keep_last", 3)
    ckpt_kwargs = dict(
        checkpoint_verify_writes=not getattr(args, "no_ckpt_verify", False),
        checkpoint_retry_policy=RetryPolicy(
            max_attempts=3, base_delay_s=0.5, max_delay_s=10.0,
            seed=args.seed),
        async_checkpointing=getattr(args, "async_ckpt", False),
        checkpoint_keep_last=keep_last if keep_last else None,
        checkpoint_keep_every=getattr(args, "ckpt_keep_every", None),
        checkpoint_mirror=getattr(args, "ckpt_mirror", None),
        checkpoint_fault_hook=(injector.on_checkpoint_write
                               if injector is not None else None),
        checkpoint_save_ef=getattr(args, "ckpt_save_ef", False))
    max_restarts = getattr(args, "max_restarts", 0)
    try:
        if max_restarts <= 0 and injector is None:
            watchdog = (StallWatchdog(timeout_s=args.stall_timeout)
                        if getattr(args, "stall_timeout", None) else None)
            with PreemptionGuard() as guard, \
                    (watchdog or contextlib.nullcontext()):
                state, history = fit(
                    state, data, step, num_steps=args.steps,
                    checkpoint_dir=args.ckpt_dir,
                    checkpoint_every=args.ckpt_every,
                    log_every=args.log_every, stop_fn=guard.requested,
                    watchdog=watchdog, step_guard=step_guard,
                    timeline=timeline, metrics_lag=metrics_lag,
                    restore_step=restore_step,
                    **ckpt_kwargs)
            _log_final(history)
            if guard.preempted:
                logger.warning("run was preempted; checkpoint saved at "
                               "step %d — relaunch with the same flags "
                               "to resume", int(state.step))
            return 0

        from ntxent_tpu.resilience import Supervisor

        if args.ckpt_dir is None:
            logger.warning("supervised run without --ckpt-dir: every "
                           "restart begins again from step 0 (no "
                           "checkpoint to resume from)")
        if injector is not None:
            data = injector.wrap_iterator(data)
        first_state = state
        # The supervised attempt's world, swapped wholesale by the
        # topology hook (elastic restarts rebuild mesh + step + pipeline).
        current = {"data": data, "step": step,
                   "state_factory": state_factory}

        def run_attempt(attempt, stop_fn, watchdog):
            s = first_state if attempt == 0 \
                or current["state_factory"] is None \
                else current["state_factory"]()
            if step_guard is not None:
                step_guard.reset_attempt()
            return fit(s, current["data"], current["step"],
                       num_steps=args.steps,
                       checkpoint_dir=args.ckpt_dir,
                       checkpoint_every=args.ckpt_every,
                       log_every=args.log_every, stop_fn=stop_fn,
                       watchdog=watchdog, step_guard=step_guard,
                       timeline=timeline, metrics_lag=metrics_lag,
                       restore_step=restore_step if attempt == 0 else None,
                       **ckpt_kwargs)

        topology_hook = None
        if topology_builder is not None:
            import jax

            n_all = jax.device_count()
            active = {"n": n_all}

            def topology_hook(action):
                n = active["n"]
                n_new = n_all if action == "grow" else max(1, n // 2)
                while n_new > 1 and args.batch % n_new:
                    n_new //= 2
                if n_new == n:
                    logger.warning("topology %s: device count stays at "
                                   "%d (batch %d divisibility)", action,
                                   n, args.batch)
                    return
                logger.warning("topology %s: rebuilding the world over "
                               "%d -> %d devices", action, n, n_new)
                raw, new_step, new_factory, new_sharding = \
                    topology_builder(n_new)
                d = wrap_data(raw, new_sharding)
                if injector is not None:
                    d = injector.wrap_iterator(d)
                current.update(data=d, step=new_step,
                               state_factory=new_factory)
                active["n"] = n_new

        supervisor = Supervisor(
            run_attempt, num_steps=args.steps,
            checkpoint_dir=args.ckpt_dir,
            max_restarts=max_restarts,
            stall_timeout_s=getattr(args, "stall_timeout", None),
            injector=injector, topology_hook=topology_hook)
        result = supervisor.run()
        _log_final(result.histories[-1] if result.histories else [])
        if injector is not None and injector.fired:
            logger.info("chaos faults fired: %s",
                        ", ".join(injector.fired))
        if not result.completed:
            logger.error("supervised run did NOT reach step %d (restart "
                         "budget exhausted)", args.steps)
            return 1
        return 0
    finally:
        obs_ctx.close()


def _build_clip_model(args):
    """CLIPModel from resolved flags (one construction shared by train and
    eval, so an evaluated checkpoint's pytree always matches). Requires
    args.image_size / token_len / vocab_size already resolved."""
    from ntxent_tpu import models
    from ntxent_tpu.models import CLIPModel, TextTransformer

    moe = getattr(args, "moe_experts", 0)
    if args.model == "tiny":
        image_enc = functools.partial(
            models.VisionTransformer, hidden_dim=32, depth=2, num_heads=2,
            mlp_dim=64, patch_size=8, moe_experts=moe,
            attention_impl=getattr(args, "vit_attention", "xla"))
        text_enc = functools.partial(
            TextTransformer, vocab_size=args.vocab_size,
            max_len=args.token_len, hidden_dim=32, depth=2, num_heads=2)
        embed_dim = 32
    else:
        image_enc = _make_encoder(args.model, args.image_size,
                                  moe_experts=moe,
                                  vit_attention=getattr(
                                      args, "vit_attention", "xla"))
        text_enc = functools.partial(TextTransformer,
                                     vocab_size=args.vocab_size,
                                     max_len=args.token_len)
        embed_dim = 512
    return CLIPModel(image_encoder=image_enc, text_encoder=text_enc,
                     embed_dim=embed_dim)


def _train_clip(args, info, per_process_batch: int, injector=None) -> int:
    """CLIP pretraining branch: dual encoder + symmetric InfoNCE.

    The BASELINE.json configs[4] workload (text-image contrastive,
    learnable logit scale). Image tower = --model (ViT variants; ResNets
    are refused — make_clip_train_step carries no BatchNorm state);
    multi-device runs default to the shard_map DP step (--clip-parallel,
    fused partial InfoNCE) with a GSPMD (data, model) mesh available for
    towers that need sharding.
    """
    import jax
    import numpy as np
    import optax

    from ntxent_tpu.parallel.mesh import create_mesh, global_batch
    from ntxent_tpu.training.datasets import PairedArrayLoader
    from ntxent_tpu.training.lars import cosine_warmup_schedule
    from ntxent_tpu.training.trainer import TrainState, make_clip_train_step

    if args.model.startswith("resnet"):
        raise SystemExit("--objective clip takes a ViT image tower "
                         "(--model vit_*|tiny); the CLIP step carries no "
                         "BatchNorm state")
    if args.dataset != "synthetic":
        raise SystemExit("--objective clip takes paired data via "
                         "--data-dir pairs.npz (images + tokens arrays); "
                         "--dataset applies to the simclr objective only")
    # NOTE --temperature is ignored here by design: CLIP's temperature is
    # the model's learnable logit scale (models/clip.py).

    # Paired data FIRST — the arrays are the truth for every static shape
    # the towers are built with (a conflicting explicit flag fails loudly
    # here instead of as a broadcast error inside jit).
    if args.data_dir:
        with np.load(args.data_dir) as z:
            images, tokens = z["images"], z["tokens"]
        if images.ndim != 4 or images.shape[1] != images.shape[2] \
                or images.shape[3] != 3:
            raise SystemExit(f"images in {args.data_dir} must be square "
                             f"NHWC with 3 channels, got {images.shape}")
        if args.image_size is not None \
                and args.image_size != images.shape[1]:
            raise SystemExit(f"--image-size {args.image_size} != images in "
                             f"{args.data_dir} ({images.shape[1]})")
        if args.token_len is not None \
                and args.token_len != tokens.shape[1]:
            raise SystemExit(f"--token-len {args.token_len} != tokens in "
                             f"{args.data_dir} ({tokens.shape[1]})")
        args.image_size = int(images.shape[1])
        args.token_len = int(tokens.shape[1])
        tmin, tmax = int(tokens.min()), int(tokens.max())
        if tmax >= args.vocab_size or tmin < 0:
            raise SystemExit(
                f"token ids span [{tmin}, {tmax}] outside [0, --vocab-size "
                f"{args.vocab_size}) (XLA would clamp the embedding gather "
                f"silently)")
    else:
        if args.image_size is None:
            args.image_size = 32
        if args.token_len is None:
            args.token_len = 77
        rng = np.random.RandomState(args.seed)
        n, s = args.synthetic_samples, args.image_size
        images = rng.rand(n, s, s, 3).astype(np.float32)
        tokens = rng.randint(1, args.vocab_size,
                             (n, args.token_len)).astype(np.int32)

    # Towers are built AFTER the data derivation above so the text tower's
    # max_len and the image tower's size match what will be fed.
    model = _build_clip_model(args)
    moe_aux = args.moe_aux_weight if args.moe_experts > 0 else 0.0
    loader = PairedArrayLoader(images, tokens, per_process_batch,
                               seed=args.seed,
                               shard_index=info["process_index"],
                               shard_count=info["process_count"])

    if args.nan_policy != "off":
        logger.warning("--nan-policy %s ignored: the CLIP steps carry no "
                       "in-step divergence guard yet", args.nan_policy)

    schedule = cosine_warmup_schedule(args.base_lr, args.warmup_steps,
                                      args.steps)
    tx = optax.adamw(schedule, weight_decay=args.weight_decay)
    if args.accum_steps > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=args.accum_steps)

    def base_state():
        variables = model.init(
            jax.random.PRNGKey(args.seed),
            np.zeros((1, args.image_size, args.image_size, 3), np.float32),
            np.zeros((1, args.token_len), np.int32),
            train=False)
        return TrainState.create(apply_fn=model.apply,
                                 params=variables["params"], tx=tx)

    state = base_state()
    prepare_state = lambda s: s  # noqa: E731  (see main(): restarts)

    n_dev = info["global_device_count"]
    mesh = sharding = None
    multiprocess = info["process_count"] > 1
    if n_dev > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        if args.clip_parallel == "tp":
            from ntxent_tpu.parallel.tp import (
                make_tp_clip_train_step, shard_train_state)

            if n_dev % args.model_par:
                raise SystemExit(f"--model-par {args.model_par} must "
                                 f"divide {n_dev} devices")
            mesh = create_mesh(shape=(n_dev // args.model_par,
                                      args.model_par),
                               axis_names=("data", "model"))
            if args.fsdp:
                # Megatron + ZeRO-3: TP claims its dimension, the FSDP
                # shape rule shards the largest remaining dim over 'data'
                # (parallel/tp.py:tp_fsdp_param_spec).
                if getattr(args, "dcn_slices", 1) > 1:
                    raise SystemExit(
                        "--dcn-slices > 1 (hybrid ZeRO) does not compose "
                        "with --clip-parallel tp yet — the TP mesh has no "
                        "'dcn' axis, so parameter all-gathers would "
                        "silently span DCN; use --clip-parallel dp for "
                        "hybrid ZeRO")
                from ntxent_tpu.parallel import shard_train_state_tp_fsdp
                from ntxent_tpu.parallel.tp import tp_fsdp_spec_fn

                prepare_state = lambda s: shard_train_state_tp_fsdp(s, mesh)  # noqa: E731,E501
                state = prepare_state(state)
                spec_fn = tp_fsdp_spec_fn(mesh)
                logger.info("CLIP GSPMD Megatron + ZeRO-3 on the "
                            "(%d, %d) (data, model) mesh",
                            n_dev // args.model_par, args.model_par)
            else:
                prepare_state = lambda s: shard_train_state(s, mesh)  # noqa: E731,E501
                state = prepare_state(state)
                spec_fn = None
                logger.info("CLIP GSPMD (%d, %d) (data, model) mesh",
                            n_dev // args.model_par, args.model_par)
            step = make_tp_clip_train_step(
                mesh, remat=args.remat, moe_aux_weight=moe_aux,
                loss_axes=(("data", "model")
                           if args.tp_loss_axes == "both" else None),
                param_spec_fn=spec_fn)
            sharding = NamedSharding(mesh, P("data"))
        elif args.fsdp:
            from ntxent_tpu.parallel import (
                make_fsdp_clip_train_step,
                shard_train_state_fsdp,
            )

            mesh = _data_mesh(args, fsdp=True)
            step = make_fsdp_clip_train_step(mesh, remat=args.remat,
                                             moe_aux_weight=moe_aux)
            prepare_state = lambda s: shard_train_state_fsdp(s, mesh)  # noqa: E731,E501
            state = prepare_state(state)
            _log_hybrid_zero(mesh)
            logger.info("CLIP FSDP (ZeRO-3, dual loss) over %d devices",
                        n_dev)
            # Batch rows span EVERY mesh axis under FSDP (hybrid ZeRO
            # meshes carry ('dcn', 'data')).
            sharding = NamedSharding(mesh, P(tuple(mesh.axis_names)))
        else:
            from ntxent_tpu.training import init_error_feedback
            from ntxent_tpu.training.trainer import (
                make_sharded_clip_train_step)

            mesh = _data_mesh(args)
            step = make_sharded_clip_train_step(
                mesh, remat=args.remat, moe_aux_weight=moe_aux,
                collective_dtype=args.collective_dtype)
            # Same rationale as the SimCLR mesh path: restore must land
            # replicated on the mesh, not committed to one device —
            # and int8 runs carry the error-feedback residual in the
            # state (ISSUE 15 satellite: the CLIP step threads
            # ef_residual exactly like the SimCLR one).
            from ntxent_tpu.parallel.mesh import replicate_state
            if args.collective_dtype == "int8":
                prepare_state = lambda s: init_error_feedback(  # noqa: E731
                    replicate_state(s, mesh), mesh)
            else:
                prepare_state = lambda s: replicate_state(s, mesh)  # noqa: E731,E501
            state = prepare_state(state)
            logger.info("CLIP shard_map data-parallel over %d devices "
                        "(fused partial InfoNCE)", n_dev)
            sharding = NamedSharding(mesh, P("data"))
    else:
        if args.fsdp:
            logger.warning("--fsdp ignored: single-device run has nothing "
                           "to shard over")
        step = make_clip_train_step(remat=args.remat,
                                    moe_aux_weight=moe_aux)
        logger.info("CLIP single-device run")

    import jax.numpy as jnp

    # uint8 -> [0, 1] happens ON DEVICE, after placement: only the raw
    # bytes cross the host boundary (4x fewer than f32 — the same
    # convention GlobalTwoViewPipeline documents for the SimCLR path).
    _normalize = jax.jit(lambda x: x.astype(jnp.float32) / 255.0)

    class ClipBatches:
        """Loader passthrough (checkpointable state) + sharded placement +
        on-device uint8 normalization."""

        def state(self):
            return loader.state()

        def restore(self, s):
            loader.restore(s)

        def __iter__(self):
            return self

        def __next__(self):
            imgs, toks = next(loader)
            if multiprocess:
                imgs, toks = global_batch((imgs, toks), mesh)
            elif sharding is not None:
                imgs = jax.device_put(imgs, sharding)
                toks = jax.device_put(toks, sharding)
            if imgs.dtype == jnp.uint8:
                imgs = _normalize(imgs)
            return imgs, toks

    return _run_fit(ClipBatches(), state, step, args,
                    state_factory=lambda: prepare_state(base_state()),
                    injector=injector, sharding=sharding)


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ntxent-serve",
        description="Embedding inference service: shape-bucketed AOT "
                    "engine + micro-batching scheduler over HTTP "
                    "(/embed, /healthz, /metrics; ntxent_tpu/serving/)")
    m = p.add_argument_group("model (must match the checkpoint's run)")
    m.add_argument("--model", default="resnet50", choices=MODEL_CHOICES)
    m.add_argument("--image-size", type=int, default=32,
                   help="served input resolution (one static shape per "
                        "ladder bucket)")
    m.add_argument("--stem", default="conv",
                   choices=["conv", "space_to_depth"])
    m.add_argument("--vit-attention", default="xla",
                   choices=["xla", "flash"])
    m.add_argument("--proj-hidden-dim", type=int, default=2048)
    m.add_argument("--proj-dim", type=int, default=128)
    m.add_argument("--head", default="features",
                   choices=["features", "embedding"],
                   help="what /embed returns: encoder features (linear-"
                        "eval space) or the projected L2-normalized "
                        "contrastive embedding (similarity-search space)")
    m.add_argument("--ckpt-dir", default=None,
                   help="restore weights from a training checkpoint "
                        "(newest VALID step; omit for random init — "
                        "useful only for smoke/load tests)")
    m.add_argument("--accum-steps", type=int, default=1,
                   help="match the training run (shapes the checkpoint's "
                        "optimizer pytree for restore)")

    s = p.add_argument_group("serving")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8080,
                   help="0 picks a free port (printed at startup)")
    s.add_argument("--buckets", default="1,4,16,64,128",
                   help="batch-size ladder the forward is compiled for; "
                        "requests pad up to the nearest rung (with "
                        "--adaptive-buckets this is the cold-start "
                        "prior; the largest rung stays the chunking "
                        "cap)")
    s.add_argument("--adaptive-buckets", action="store_true",
                   help="learn the ladder from live traffic: an online "
                        "decayed request-size histogram feeds a DP "
                        "optimizer that picks rungs minimizing expected "
                        "padded rows; a background worker AOT-compiles "
                        "the new ladder off the hot path and swaps it "
                        "atomically (serving/ladder.py; requests never "
                        "pay a compile across a swap)")
    s.add_argument("--ladder-max-buckets", type=int, default=6,
                   help="ladder-size budget for the optimizer (total "
                        "rungs incl. the fixed top one)")
    s.add_argument("--ladder-min-requests", type=int, default=200,
                   help="observed device chunks before the first "
                        "re-optimization may swap the ladder")
    s.add_argument("--ladder-interval", type=float, default=2.0,
                   metavar="SECONDS",
                   help="background ladder re-optimization period")
    s.add_argument("--max-batch", type=int, default=None,
                   help="coalescing cap per device call (default: the "
                        "largest bucket)")
    s.add_argument("--max-delay-ms", type=float, default=5.0,
                   help="micro-batching window: how long the scheduler "
                        "holds the first request while coalescing more")
    s.add_argument("--queue-size", type=int, default=64,
                   help="bounded request queue; a full queue rejects "
                        "with 429 + Retry-After (backpressure) instead "
                        "of growing latency")
    s.add_argument("--timeout-ms", type=float, default=10000.0,
                   help="default per-request deadline (overridable per "
                        "request via the timeout_ms JSON field)")
    s.add_argument("--max-request-rows", type=int, default=None,
                   help="per-request row cap (413 above it; default: "
                        "8x the largest bucket) — one request may chunk "
                        "through the ladder but not monopolize the "
                        "device worker")
    s.add_argument("--no-warmup", action="store_true",
                   help="skip compiling the bucket ladder at startup "
                        "(first request per bucket then pays the "
                        "compile)")
    s.add_argument("--dtype", "--serve-dtype", dest="dtype",
                   default="float32",
                   choices=["float32", "bfloat16", "int8"],
                   help="input/compute dtype the buckets compile for; "
                        "int8 (ISSUE 12) serves QUANTIZED rungs — "
                        "chunks are quantized host-side (per-example "
                        "symmetric scales) and dequantized in-graph, "
                        "so every ladder bucket is an int8 executable "
                        "and the host->device transfer shrinks ~4x "
                        "(accuracy delta vs float32 is asserted by "
                        "quant_smoke and the shadow-drift gate)")

    r = p.add_argument_group("resilience (ntxent_tpu/resilience/)")
    r.add_argument("--stall-timeout", type=float, default=None,
                   metavar="SECONDS",
                   help="if a device call wedges for this long the "
                        "watchdog dumps all thread stacks and escalates "
                        "(with --max-restarts: drain + fresh batcher)")
    r.add_argument("--max-restarts", type=int, default=0,
                   help="supervised restarts after stall escalation "
                        "(resilience.Supervisor; 0 = single attempt)")

    w = p.add_argument_group("fleet worker (ntxent_tpu/serving/fleet.py "
                             "spawns ntxent-serve with these)")
    w.add_argument("--port-file", default=None, metavar="PATH",
                   help="publish the bound port to this file and bind "
                        "BEFORE warmup (/readyz 503s and /embed sheds "
                        "with Retry-After until the ladder is compiled "
                        "— the router never routes to a cold worker)")
    w.add_argument("--watch-ckpt", action="store_true",
                   help="watch --ckpt-dir for new manifest-valid steps "
                        "and hot-swap weights (zero-downtime rollout: "
                        "warm first, swap atomically; POST /rollback "
                        "reverts + blocklists a step)")
    w.add_argument("--watch-poll", type=float, default=2.0,
                   metavar="SECONDS",
                   help="checkpoint watch poll interval")
    w.add_argument("--watch-delay", type=float, default=0.0,
                   metavar="SECONDS",
                   help="adoption delay after first seeing a new step "
                        "(the fleet staggers workers so the earliest "
                        "becomes the router's canary cohort)")

    o = p.add_argument_group("observability (ntxent_tpu/obs/)")
    o.add_argument("--log-jsonl", default=None, metavar="PATH",
                   help="append typed JSONL events (request/queue/device "
                        "spans with request ids — export with "
                        "ntxent-trace) to this file")
    o.add_argument("--run-id", default=None, metavar="ID",
                   help="identity stamped on every event and surfaced "
                        "in /metrics (serving_run_info{run_id=...} and "
                        "the JSON run_id key); pass the TRAINING run's "
                        "id to correlate a serving process with the run "
                        "whose checkpoints it serves (default: random)")

    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None, metavar="cpu|tpu")
    return p


def serve_main(argv=None) -> int:
    """``ntxent-serve``: the inference half of the north star."""
    args = build_serve_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")

    try:
        buckets = tuple(int(b) for b in args.buckets.split(",") if b)
        if not buckets or min(buckets) < 1:
            raise ValueError
    except ValueError:
        raise SystemExit(f"--buckets must be a comma list of positive "
                         f"ints, got {args.buckets!r}")

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import jax.numpy as jnp

    from ntxent_tpu.models import SimCLRModel
    from ntxent_tpu.resilience import RetryPolicy
    from ntxent_tpu.serving import EmbeddingServer, InferenceEngine
    from ntxent_tpu.training import TrainerConfig, create_train_state

    encoder = _make_encoder(args.model, args.image_size, stem=args.stem,
                            vit_attention=args.vit_attention)
    model = SimCLRModel(encoder=encoder,
                        proj_hidden_dim=args.proj_hidden_dim,
                        proj_dim=args.proj_dim)
    # Serving state comes from the same template construction eval uses,
    # so any checkpoint ntxent-eval can read, ntxent-serve can serve.
    template = create_train_state(
        model, jax.random.PRNGKey(args.seed),
        (1, args.image_size, args.image_size, 3),
        TrainerConfig(accum_steps=args.accum_steps))
    if args.ckpt_dir is not None:
        from ntxent_tpu.training.checkpoint import CheckpointManager

        manager = CheckpointManager(args.ckpt_dir)
        try:
            if manager.latest_step() is None:
                if not args.watch_ckpt:
                    raise SystemExit(f"no checkpoint under "
                                     f"{args.ckpt_dir}")
                # Watch mode may boot BEFORE the first checkpoint lands
                # (a fleet starting alongside training): serve random
                # weights, stay not-ready-looking via checkpoint_step=-1,
                # adopt the first valid step the watcher sees.
                state = template
                logger.warning("no checkpoint under %s yet — watching "
                               "for the first valid step", args.ckpt_dir)
            else:
                state = manager.restore(template)
                logger.info("serving checkpoint step %d from %s",
                            int(state.step), args.ckpt_dir)
        finally:
            manager.close()
    else:
        state = template
        logger.warning("no --ckpt-dir: serving RANDOM weights (smoke/"
                       "load-test mode)")
    variables = {"params": state.params, "batch_stats": state.batch_stats}

    if args.head == "embedding":
        def apply_fn(v, x):
            return model.apply(v, x, train=False)
    else:
        def apply_fn(v, x):
            return model.apply(v, x, train=False, method="features")

    # Serving-side telemetry identity (ISSUE 7): one EventLog whenever
    # spans should persist (--log-jsonl) or the operator pinned a run id;
    # every span/event then carries run_id, and /metrics exposes it as
    # serving_run_info — the cross-process join key back to the training
    # run. Without either flag the span emits stay the hub's no-op.
    event_log = None
    if args.log_jsonl or args.run_id:
        from ntxent_tpu import obs

        # async_io: span emits ride the micro-batcher's dispatch loop,
        # so the file writes must come off the request hot path (a
        # per-record flush measurably backs up the bounded queue under
        # burst load — obs/events.EventLog docstring).
        event_log = obs.EventLog(args.log_jsonl, run_id=args.run_id,
                                 async_io=True)
        obs.install(event_log)
        logger.info("serving telemetry: run_id=%s%s", event_log.run_id,
                    f" events -> {args.log_jsonl}" if args.log_jsonl
                    else "")

    retry_policy = RetryPolicy(max_attempts=2, base_delay_s=0.05,
                               max_delay_s=1.0, seed=args.seed)
    engine = InferenceEngine(
        apply_fn, variables,
        example_shape=(args.image_size, args.image_size, 3),
        buckets=buckets,
        dtype={"bfloat16": jnp.bfloat16, "int8": jnp.int8}.get(
            args.dtype, jnp.float32),
        retry_policy=retry_policy,  # per-chunk transient-fault retries
        adaptive=args.adaptive_buckets,
        ladder_max_buckets=args.ladder_max_buckets,
        ladder_min_requests=args.ladder_min_requests,
        ladder_interval_s=(args.ladder_interval
                           if args.adaptive_buckets else 0.0))
    if event_log is not None:
        engine.metrics.set_run_id(event_log.run_id)
    initial_step = (int(state.step)
                    if args.ckpt_dir is not None and state is not template
                    else None)
    if initial_step is not None:
        engine.metrics.set_checkpoint_step(initial_step)

    server = EmbeddingServer(
        engine, host=args.host, port=args.port,
        max_batch=args.max_batch, max_delay_s=args.max_delay_ms / 1e3,
        queue_size=args.queue_size,
        retry_policy=retry_policy,  # 429 Retry-After backoff schedule
        stall_timeout_s=args.stall_timeout,
        max_restarts=args.max_restarts,
        default_timeout_s=args.timeout_ms / 1e3,
        max_request_rows=args.max_request_rows)

    watcher = None
    if args.watch_ckpt:
        if args.ckpt_dir is None:
            raise SystemExit("--watch-ckpt requires --ckpt-dir")
        from ntxent_tpu.serving.worker import CheckpointWatcher

        watcher = CheckpointWatcher(
            args.ckpt_dir, template, engine,
            poll_s=args.watch_poll, delay_s=args.watch_delay,
            initial_step=initial_step)
        server.reloader = watcher

    if args.port_file:
        # Fleet-worker boot order: mark the ladder cold BEFORE the
        # listener binds (a probe racing the bind must never see
        # ready=true), then bind (the supervisor learns the port and
        # /readyz immediately), THEN compile — /embed sheds with
        # Retry-After and /readyz stays red until warm, so the router
        # never routes to a cold worker.
        server.begin_warmup()
        server.start()
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(server.port))
        os.replace(tmp, args.port_file)
        if not args.no_warmup:
            engine.warmup()
        server.end_warmup()
    elif not args.no_warmup:
        engine.warmup()

    if watcher is not None:
        watcher.start()
    try:
        completed = server.serve_forever()
    except KeyboardInterrupt:
        logger.info("interrupted — draining")
        server.close()
        return 0
    finally:
        if watcher is not None:
            watcher.stop()
        engine.close()  # stop the ladder re-AOT worker, if any
        if event_log is not None:
            from ntxent_tpu import obs

            obs.install(None)
            event_log.close()
    return 0 if completed else 1


def build_fleet_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ntxent-fleet",
        description="Serving fleet: a fault-tolerant router tier over N "
                    "supervised ntxent-serve worker replicas — embedding "
                    "cache, per-request retry failover, 429 load "
                    "shedding, canaried zero-downtime checkpoint "
                    "rollout (ntxent_tpu/serving/{router,fleet,cache,"
                    "worker}.py)")
    m = p.add_argument_group("model (forwarded to every worker)")
    m.add_argument("--model", default="resnet50", choices=MODEL_CHOICES)
    m.add_argument("--image-size", type=int, default=32)
    m.add_argument("--stem", default="conv",
                   choices=["conv", "space_to_depth"])
    m.add_argument("--vit-attention", default="xla",
                   choices=["xla", "flash"])
    m.add_argument("--proj-hidden-dim", type=int, default=2048)
    m.add_argument("--proj-dim", type=int, default=128)
    m.add_argument("--head", default="features",
                   choices=["features", "embedding"])
    m.add_argument("--ckpt-dir", default=None,
                   help="checkpoint dir the workers restore from AND "
                        "watch for new steps (zero-downtime rollout); "
                        "omit for random weights (smoke/load tests)")
    m.add_argument("--accum-steps", type=int, default=1)

    w = p.add_argument_group("workers")
    w.add_argument("--workers", type=int, default=2,
                   help="worker replica count")
    w.add_argument("--buckets", default="1,4,16,64,128")
    w.add_argument("--adaptive-buckets", action="store_true",
                   help="each worker learns its ladder from its own "
                        "traffic (ntxent-serve --adaptive-buckets); "
                        "workers adapt independently — the router's "
                        "cache keys hash row content, never buckets, "
                        "so per-worker ladders cannot skew routing or "
                        "caching")
    w.add_argument("--ladder-max-buckets", type=int, default=6)
    w.add_argument("--ladder-min-requests", type=int, default=200)
    w.add_argument("--ladder-interval", type=float, default=2.0)
    w.add_argument("--max-batch", type=int, default=None)
    w.add_argument("--max-delay-ms", type=float, default=5.0)
    w.add_argument("--queue-size", type=int, default=64)
    w.add_argument("--timeout-ms", type=float, default=10000.0)
    w.add_argument("--max-request-rows", type=int, default=None)
    w.add_argument("--dtype", "--serve-dtype", dest="dtype",
                   default="float32",
                   choices=["float32", "bfloat16", "int8"],
                   help="forwarded to every worker (int8 = quantized "
                        "rungs, see ntxent-serve --dtype)")
    w.add_argument("--stall-timeout", type=float, default=None)
    w.add_argument("--watch-poll", type=float, default=2.0,
                   help="worker checkpoint-watch poll interval")
    w.add_argument("--worker-stagger", type=float, default=3.0,
                   metavar="SECONDS",
                   help="per-worker delay step before adopting a new "
                        "checkpoint (worker i waits i*stagger): the "
                        "earliest adopter is the router's canary "
                        "cohort")

    rt = p.add_argument_group("router")
    rt.add_argument("--host", default="127.0.0.1")
    rt.add_argument("--port", type=int, default=8080,
                    help="router port (0 picks a free one)")
    rt.add_argument("--port-file", default=None, metavar="PATH",
                    help="publish the router's bound port to this file")
    rt.add_argument("--retries", type=int, default=2,
                    help="per-request failover budget: extra workers "
                         "tried after a 5xx/unreachable forward")
    rt.add_argument("--forward-timeout", type=float, default=30.0)
    rt.add_argument("--cache-rows", type=int, default=4096,
                    help="embedding cache LRU capacity in rows")
    rt.add_argument("--cache-ttl", type=float, default=300.0,
                    help="embedding cache TTL seconds")
    rt.add_argument("--cache-warm-rows", type=int, default=32,
                    help="hot rows replayed through a newly promoted "
                         "model right after the promote flush "
                         "(0 = boot the cache cold as before)")
    rt.add_argument("--no-cache", action="store_true")
    rt.add_argument("--canary-fraction", type=float, default=0.25,
                    help="traffic fraction routed to new-checkpoint "
                         "workers while their canary is undecided")
    rt.add_argument("--canary-min-requests", type=int, default=20,
                    help="canary outcomes before a promote/rollback "
                         "verdict")
    rt.add_argument("--canary-max-error-rate", type=float, default=0.1,
                    help="canary error rate above which the step is "
                         "rolled back fleet-wide")
    rt.add_argument("--shadow-fraction", type=float, default=0.0,
                    help="shadow routing (ISSUE 10): mirror this "
                         "fraction of trusted-cohort traffic to the "
                         "undecided canary OFF the client's critical "
                         "path and diff the embeddings per row "
                         "(cosine distance); 0 disables")
    rt.add_argument("--shadow-max-drift", type=float, default=0.05,
                    help="drift bar: promote requires mirrored-traffic "
                         "drift p99 at or under this cosine distance "
                         "(in addition to the error-rate bar); a "
                         "breach rolls the canary back")
    rt.add_argument("--shadow-min-samples", type=int, default=8,
                    help="mirrored rows diffed before the drift bar "
                         "can judge (the verdict defers until then)")

    ix = p.add_argument_group("retrieval (ntxent_tpu/retrieval/: "
                              "checkpoint-step-versioned ANN index "
                              "over served embeddings — POST /search)")
    ix.add_argument("--index-dir", default=None, metavar="DIR",
                    help="enable the retrieval tier with segment "
                         "persistence under DIR (per-step subdirs; "
                         "stage-fsync-rename sealing). POST /search, "
                         "/index/insert and /embed?store=true go live")
    ix.add_argument("--index-mem", action="store_true",
                    help="enable the retrieval tier fully in memory "
                         "(no segment persistence — smoke/load tests)")
    ix.add_argument("--index-train-rows", type=int, default=2048,
                    metavar="N",
                    help="rows before IVF centroids train; below this "
                         "search is exact brute force (perfect recall "
                         "while small)")
    ix.add_argument("--index-centroids", type=int, default=64,
                    help="IVF list count once trained")
    ix.add_argument("--index-nprobe", type=int, default=16,
                    help="IVF lists scanned per query")
    ix.add_argument("--index-seal-rows", type=int, default=4096,
                    help="mutable-segment rows before a seal to disk")
    ix.add_argument("--index-docstore-rows", type=int, default=65536,
                    help="input rows retained for background "
                         "re-embedding rebuilds (promote/stale); past "
                         "the bound the oldest are evicted")
    ix.add_argument("--index-maintain-interval", type=float,
                    default=2.0, metavar="SECONDS",
                    help="background maintenance tick (train/seal/"
                         "compact/recall probe)")
    ix.add_argument("--index-pq-m", type=int, default=8, metavar="M",
                    help="PQ code bytes per row (0 = raw IVF-flat, "
                         "PR 14 behavior); sealed segments carry "
                         "codes, searches ADC-scan + exact re-rank")
    ix.add_argument("--search-shards", type=int, default=0,
                    metavar="N",
                    help="start N shard servers and fan /search out "
                         "across them (IVF lists placed by rendezvous "
                         "hash over the ring; a dead shard degrades "
                         "recall, never availability, and its rows "
                         "are journaled + repaired on restart)")
    ix.add_argument("--shard-procs", action="store_true",
                    help="run --search-shards workers as supervised "
                         "SUBPROCESSES (readiness probe, eject-after-"
                         "streak, backoff restart) instead of "
                         "in-process servers; a restarted shard is "
                         "refilled from the insert journal")
    ix.add_argument("--shard-journal-dir", default=None, metavar="DIR",
                    help="durable per-shard insert journal (default: "
                         "in-memory): every routed batch is logged "
                         "before delivery, so rows a dead shard "
                         "missed are replayed by the repair loop")
    ix.add_argument("--shard-repair-interval", type=float, default=1.0,
                    metavar="SECONDS",
                    help="repair-loop tick: probe dead shards, drain "
                         "journal debt through the insert path")

    f = p.add_argument_group("fleet supervision")
    f.add_argument("--workdir", default=None,
                   help="port files + per-worker logs (default: a "
                        "temp dir)")
    f.add_argument("--attach-workdir", default=None, metavar="PATH",
                   help="REPLICA router mode: spawn no workers — "
                        "attach to the worker pool a primary "
                        "ntxent-fleet already runs in PATH (its w*.port "
                        "files), probe health, and route. N routers "
                        "over one pool is the stateless-router "
                        "replication proof (ROADMAP item 4 follow-up); "
                        "process supervision stays with the primary")
    f.add_argument("--health-poll", type=float, default=0.5,
                   help="supervision tick: /readyz probe interval")
    f.add_argument("--eject-after", type=int, default=3,
                   help="consecutive probe/forward failures before a "
                        "worker is killed and restarted")
    f.add_argument("--worker-max-restarts", type=int, default=8,
                   help="per-worker restart budget")
    f.add_argument("--chaos", default=None, metavar="PLAN",
                   help="fleet fault plan, e.g. 'killworker@10,"
                        "slowworker@30,spike@20,drainworker@40,"
                        "killshard@15,lagshard@25' (ordinals are "
                        "supervision ticks; resilience/faults.py "
                        "grammar; spike/drainworker exercise the "
                        "autoscaler and need --autoscale; killshard/"
                        "lagshard hit the shard plane and need "
                        "--shard-procs)")

    a = p.add_argument_group("autoscaling (ISSUE 16: closed-loop pool "
                             "sizing over the federated signals — "
                             "serving/autoscale.py; scale-down drains "
                             "to zero in-flight before SIGTERM)")
    a.add_argument("--autoscale", action="store_true",
                   help="size the pool between --min-workers and "
                        "--max-workers from queue depth / in-flight / "
                        "p99 / burn rate (requires federation, "
                        "--fed-interval > 0; --workers is then the "
                        "STARTING size)")
    a.add_argument("--min-workers", type=int, default=None,
                   help="pool floor (default: 1)")
    a.add_argument("--max-workers", type=int, default=None,
                   help="pool ceiling (default: max(--workers, 4))")
    a.add_argument("--scale-up-queue", type=float, default=8.0,
                   help="federated queue depth per routable worker "
                        "that counts as scale-up pressure")
    a.add_argument("--scale-up-inflight", type=float, default=4.0,
                   help="router in-flight per routable worker that "
                        "counts as scale-up pressure")
    a.add_argument("--scale-up-p99-ms", type=float, default=None,
                   help="fleet p99 (ms) that counts as scale-up "
                        "pressure (default: off)")
    a.add_argument("--scale-up-burn", type=float, default=1.0,
                   help="availability burn rate (vs --scale-slo-target "
                        "budget) that counts as scale-up pressure")
    a.add_argument("--scale-slo-target", type=float, default=0.999,
                   help="availability target whose error budget the "
                        "scale-up burn signal is measured against")
    a.add_argument("--scale-up-ticks", type=int, default=2,
                   help="consecutive pressure ticks before adding a "
                        "worker (hysteresis)")
    a.add_argument("--scale-idle-ticks", type=int, default=6,
                   help="consecutive idle ticks before draining one "
                        "(hysteresis)")
    a.add_argument("--scale-up-cooldown", type=float, default=15.0,
                   metavar="SECONDS")
    a.add_argument("--scale-down-cooldown", type=float, default=30.0,
                   metavar="SECONDS")
    a.add_argument("--drain-deadline", type=float, default=30.0,
                   metavar="SECONDS",
                   help="max drain wait before a still-busy victim is "
                        "retired anyway")
    a.add_argument("--predict-horizon", type=float, default=None,
                   metavar="SECONDS",
                   help="predictive scale-up (ISSUE 18): feed Holt-"
                        "Winters forecasters the request-rate and "
                        "queue-depth series and treat the PROJECTED "
                        "value this many seconds out as scale-up "
                        "pressure ('forecast' reason) — capacity "
                        "arrives before a diurnal ramp breaches. "
                        "Scale-down stays purely reactive. Requires "
                        "--autoscale")
    a.add_argument("--predict-capacity", type=float, default=None,
                   metavar="REQ_S",
                   help="rated per-worker throughput (req/s) the "
                        "forecast rate signal is judged against "
                        "(default: off — only the forecast queue-"
                        "depth signal fires)")
    a.add_argument("--predict-season", type=float, default=None,
                   metavar="SECONDS",
                   help="seasonal period for the forecasters (e.g. "
                        "86400 for a diurnal cycle; default: trend "
                        "only)")
    a.add_argument("--scale-up-rss-bytes", type=float, default=None,
                   metavar="BYTES",
                   help="worker vertical memory pressure (ISSUE 18): "
                        "federated max serving_worker_rss_bytes at or "
                        "over this counts as scale-up pressure "
                        "(default: off)")
    a.add_argument("--tenant-quota", default=None,
                   metavar="NAME=RATE[:BURST],...",
                   help="arm per-tenant admission control (X-Tenant "
                        "header; rows/s token buckets; 429 + "
                        "Retry-After on exhaustion). The tenant named "
                        "'default' sets the quota every unlisted "
                        "tenant gets, e.g. "
                        "'default=100,big=1000:2000'. Works with or "
                        "without --autoscale")

    o = p.add_argument_group("observability (ntxent_tpu/obs/)")
    o.add_argument("--log-jsonl", default=None, metavar="PATH",
                   help="router-side typed JSONL events (fleet.request/"
                        "fleet.cache/fleet.forward/fleet.shadow spans; "
                        "workers log to <workdir>/wN.jsonl with the "
                        "same run id — stitch them with "
                        "`ntxent-trace --merge`)")
    o.add_argument("--run-id", default=None, metavar="ID")
    o.add_argument("--fed-interval", type=float, default=2.0,
                   metavar="SECONDS",
                   help="metric-federation tick: how often the router "
                        "scrapes every worker's /metrics?format=state "
                        "into the merged /metrics/fleet view (and "
                        "evaluates SLOs); 0 disables federation")
    o.add_argument("--slo-availability", type=float, default=None,
                   metavar="TARGET",
                   help="availability SLO target (e.g. 0.99): alert "
                        "when the router-edge failure rate burns the "
                        "error budget faster than --slo-burn-factor "
                        "over BOTH burn windows (obs/slo.py)")
    o.add_argument("--slo-latency-ms", type=float, default=None,
                   metavar="MS",
                   help="p99 latency SLO bound on the router's "
                        "fleet_latency_ms{stage=total}")
    o.add_argument("--slo-drift", type=float, default=None,
                   metavar="DIST",
                   help="drift SLO bound on fleet_shadow_drift p99 "
                        "(alerting view of the shadow bar)")
    o.add_argument("--slo-retrieval-degraded", type=float, default=None,
                   metavar="TARGET",
                   help="retrieval health SLO target (e.g. 0.99): "
                        "alert when the fraction of searches served "
                        "degraded (shard timeouts/failures) burns the "
                        "budget over both windows")
    o.add_argument("--slo-fast-window", type=float, default=60.0,
                   metavar="SECONDS")
    o.add_argument("--slo-slow-window", type=float, default=300.0,
                   metavar="SECONDS")
    o.add_argument("--slo-burn-factor", type=float, default=2.0,
                   help="error-budget burn multiple that pages")
    o.add_argument("--history-dir", default=None, metavar="DIR",
                   help="durable spill directory for the metrics-"
                        "history plane (ISSUE 18): the per-series "
                        "rollup store survives router restarts via "
                        "stage-fsync-rename (default: in-memory only). "
                        "History itself is always on with federation — "
                        "/metrics/history")
    o.add_argument("--history-raw", type=int, default=720,
                   metavar="SAMPLES",
                   help="raw ring length per series (rollups keep the "
                        "same count at 10s and 1m resolution)")
    o.add_argument("--anomaly-mad", type=float, default=6.0,
                   metavar="FACTOR",
                   help="anomaly detector sensitivity: |value - "
                        "rolling median| over this many MADs fires a "
                        "typed 'anomaly' alert + flight dump")
    o.add_argument("--anomaly-warmup", type=int, default=20,
                   metavar="SAMPLES",
                   help="per-series samples before the anomaly "
                        "detector arms")
    o.add_argument("--anomaly-series", default=None,
                   metavar="NAME,NAME,...",
                   help="restrict the anomaly watch to these history "
                        "series (default: every recorded series)")

    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None, metavar="cpu|tpu")
    return p


def fleet_main(argv=None) -> int:
    """``ntxent-fleet``: router + N supervised workers in one command.

    The router process imports no JAX — workers pay backend init, the
    router only moves bytes, which is what lets it restart in
    milliseconds and makes its cache a robustness layer (warm keys keep
    serving through any worker's death).
    """
    import signal as _signal
    import tempfile
    import threading
    from pathlib import Path

    args = build_fleet_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    if args.workers < 1:
        raise SystemExit(f"--workers must be >= 1, got {args.workers}")
    try:
        bucket_list = tuple(int(b) for b in args.buckets.split(",") if b)
        if not bucket_list or min(bucket_list) < 1:
            raise ValueError
    except ValueError:
        raise SystemExit(f"--buckets must be a comma list of positive "
                         f"ints, got {args.buckets!r}")

    from ntxent_tpu import obs
    from ntxent_tpu.resilience import FaultInjector, FaultPlan
    from ntxent_tpu.serving import (
        EmbeddingCache,
        FleetRouter,
        ServingFleet,
        WorkerPool,
    )

    attach = args.attach_workdir is not None
    injector = None
    if args.chaos:
        plan = FaultPlan.parse(args.chaos, seed=args.seed)
        has_fleet = bool(plan.killworker_ticks or plan.slowworker_ticks
                         or plan.spike_ticks or plan.drainworker_ticks)
        has_shard = plan.has_shard_actions()
        if attach and has_fleet:
            # Shard chaos still applies: the shard fleet is owned by
            # THIS router even when the embed workers belong to a
            # primary elsewhere.
            logger.warning("--chaos fleet actions are ignored in "
                           "--attach-workdir mode: a replica router "
                           "does not own the worker processes")
            has_fleet = False
        if has_shard and not (args.shard_procs and args.search_shards):
            logger.warning("--chaos shard actions (killshard@T/"
                           "lagshard@T) need --search-shards N with "
                           "--shard-procs — ignored here")
            has_shard = False
        if has_fleet or has_shard:
            injector = FaultInjector(plan)
        else:
            logger.warning("--chaos %r has no applicable actions — "
                           "ignored here", args.chaos)

    if attach:
        workdir = Path(args.attach_workdir)
        if not workdir.is_dir():
            raise SystemExit(f"--attach-workdir {workdir} does not "
                             "exist (start the primary fleet first)")
    else:
        workdir = Path(args.workdir) if args.workdir \
            else Path(tempfile.mkdtemp(prefix="ntxent-fleet-"))
        workdir.mkdir(parents=True, exist_ok=True)

    event_log = None
    if args.log_jsonl or args.run_id:
        event_log = obs.EventLog(args.log_jsonl, run_id=args.run_id,
                                 async_io=True)
        obs.install(event_log)
        logger.info("fleet telemetry: run_id=%s%s", event_log.run_id,
                    f" events -> {args.log_jsonl}" if args.log_jsonl
                    else "")
    run_id = event_log.run_id if event_log is not None else None

    # Worker argv: ntxent-serve through a -c shim (module __main__ is
    # the trainer). Every worker shares the SAME --seed so random-init
    # smoke fleets serve identical weights.
    shim = ("import sys\nfrom ntxent_tpu.cli import serve_main\n"
            "sys.exit(serve_main(sys.argv[1:]))")

    def make_cmd(worker_id: str, port_file) -> list[str]:
        idx = int(worker_id.lstrip("w"))
        cmd = [sys.executable, "-c", shim,
               "--model", args.model,
               "--image-size", str(args.image_size),
               "--stem", args.stem,
               "--vit-attention", args.vit_attention,
               "--proj-hidden-dim", str(args.proj_hidden_dim),
               "--proj-dim", str(args.proj_dim),
               "--head", args.head,
               "--accum-steps", str(args.accum_steps),
               "--buckets", args.buckets,
               "--max-delay-ms", str(args.max_delay_ms),
               "--queue-size", str(args.queue_size),
               "--timeout-ms", str(args.timeout_ms),
               "--dtype", args.dtype,
               "--seed", str(args.seed),
               "--port", "0",
               "--port-file", str(port_file),
               "--watch-poll", str(args.watch_poll),
               "--watch-delay", str(idx * args.worker_stagger)]
        if args.adaptive_buckets:
            cmd += ["--adaptive-buckets",
                    "--ladder-max-buckets", str(args.ladder_max_buckets),
                    "--ladder-min-requests",
                    str(args.ladder_min_requests),
                    "--ladder-interval", str(args.ladder_interval)]
        if args.max_batch is not None:
            cmd += ["--max-batch", str(args.max_batch)]
        if args.max_request_rows is not None:
            cmd += ["--max-request-rows", str(args.max_request_rows)]
        if args.stall_timeout is not None:
            cmd += ["--stall-timeout", str(args.stall_timeout)]
        if args.ckpt_dir is not None:
            cmd += ["--ckpt-dir", args.ckpt_dir, "--watch-ckpt"]
        if args.platform:
            cmd += ["--platform", args.platform]
        if args.log_jsonl:
            cmd += ["--log-jsonl", str(workdir / f"{worker_id}.jsonl")]
        if run_id:
            cmd += ["--run-id", run_id]
        return cmd

    registry = obs.default_registry()
    pool = WorkerPool(canary_fraction=args.canary_fraction,
                      canary_min_requests=args.canary_min_requests,
                      canary_max_error_rate=args.canary_max_error_rate,
                      shadow_max_drift=(args.shadow_max_drift
                                        if args.shadow_fraction > 0
                                        else None),
                      shadow_min_samples=args.shadow_min_samples,
                      registry=registry)
    cache = None
    if not args.no_cache:
        cache = EmbeddingCache(capacity_rows=args.cache_rows,
                               ttl_s=args.cache_ttl,
                               buckets=bucket_list, registry=registry,
                               # the hot store must hold at least what
                               # a promote wants to replay, or
                               # --cache-warm-rows is silently capped
                               hot_rows=max(64, args.cache_warm_rows))
    fleet = ServingFleet(make_cmd, n_workers=args.workers,
                         workdir=workdir, pool=pool,
                         poll_s=args.health_poll,
                         eject_after=args.eject_after,
                         max_restarts=args.worker_max_restarts,
                         injector=injector, registry=registry,
                         attach=attach)
    router = FleetRouter(
        pool, cache=cache,
        example_shape=(args.image_size, args.image_size, 3),
        host=args.host, port=args.port, retries=args.retries,
        forward_timeout_s=args.forward_timeout, registry=registry,
        warm_rows=args.cache_warm_rows)
    router.set_run_id(run_id)

    # Retrieval tier (ISSUE 15): the versioned ANN index rides the
    # router process — JAX-free like everything else here, its rebuild
    # re-embeds through the router's own forward path.
    index_mgr = None
    if args.index_dir or args.index_mem:
        from ntxent_tpu.retrieval import IndexManager

        index_mgr = IndexManager(
            root=args.index_dir, registry=registry,
            docstore_rows=args.index_docstore_rows,
            maintain_interval_s=args.index_maintain_interval,
            train_rows=args.index_train_rows,
            n_centroids=args.index_centroids,
            nprobe=args.index_nprobe,
            seal_rows=args.index_seal_rows,
            pq_m=args.index_pq_m)
        router.attach_index(index_mgr)
        logger.info("retrieval tier: POST /search live (%s, "
                    "train_rows=%d, nprobe=%d/%d, pq_m=%d)",
                    args.index_dir or "in-memory",
                    args.index_train_rows, args.index_nprobe,
                    args.index_centroids, args.index_pq_m)

    # Sharded index plane (ISSUE 17/20): N shard servers, the router
    # fans /search out and merges — the single-process capacity
    # ceiling becomes a fleet-shaped one. --shard-procs runs them as
    # SUPERVISED SUBPROCESSES through the same ServingFleet machinery
    # the embed workers use (readiness probe, eject-after-streak,
    # backoff restart), on a second fleet with its own WorkerPool and
    # a PRIVATE registry (the shard pool's canary state machine must
    # not fight the embed pool's on the shared metric names). In
    # production the servers run on separate hosts
    # (python -m ntxent_tpu.retrieval.shard).
    shard_servers = []
    shard_fleet = None
    if args.search_shards > 0:
        from ntxent_tpu.retrieval import ShardFanout, ShardServer

        dim = args.proj_dim
        if args.shard_procs:
            import socket as _socket

            # FIXED pre-allocated ports: the fan-out routes by URL, so
            # a shard restarted by supervision must rebind the exact
            # port its clients already hold — an ephemeral port would
            # orphan the ring entry and turn every restart into a
            # permanent hole.
            shard_ports = []
            for _ in range(args.search_shards):
                sk = _socket.socket()
                sk.bind(("127.0.0.1", 0))
                shard_ports.append(sk.getsockname()[1])
                sk.close()
            shard_workdir = workdir / "shards"

            def make_shard_cmd(worker_id: str, port_file) -> list[str]:
                idx = int(worker_id.lstrip("w"))
                return [sys.executable, "-m",
                        "ntxent_tpu.retrieval.shard",
                        "--dim", str(dim),
                        "--port", str(shard_ports[idx]),
                        "--port-file", str(port_file)]

            shard_pool = WorkerPool(registry=obs.MetricsRegistry())
            shard_fleet = ServingFleet(
                make_shard_cmd, n_workers=args.search_shards,
                workdir=shard_workdir, pool=shard_pool,
                poll_s=args.health_poll,
                eject_after=args.eject_after,
                max_restarts=args.worker_max_restarts,
                injector=injector, registry=shard_pool.registry,
                chaos_channel="shard")
            shard_urls = [f"http://127.0.0.1:{p}" for p in shard_ports]
        else:
            shard_servers = [ShardServer(dim).start()
                             for _ in range(args.search_shards)]
            shard_urls = [s.url for s in shard_servers]
        fanout = ShardFanout(
            shard_urls, dim=dim,
            train_rows=args.index_train_rows,
            n_centroids=args.index_centroids,
            nprobe=args.index_nprobe, pq_m=max(1, args.index_pq_m),
            journal_dir=args.shard_journal_dir,
            registry=registry)
        router.attach_shards(fanout)
        logger.info("retrieval: shard plane live — %d shard(s)%s, "
                    "rendezvous list placement, journal %s",
                    args.search_shards,
                    " (supervised subprocesses)" if args.shard_procs
                    else "",
                    args.shard_journal_dir or "in-memory")

    # Fleet observability plane (ISSUE 10): shadow mirror, metric
    # federation, SLO engine. All off-hot-path; all optional.
    shadow = None
    if args.shadow_fraction > 0:
        from ntxent_tpu.serving import ShadowMirror

        shadow = ShadowMirror(pool, fraction=args.shadow_fraction,
                              forward_timeout_s=args.forward_timeout)
        router.attach_shadow(shadow)

    slo_flags = (args.slo_availability, args.slo_latency_ms,
                 args.slo_drift, args.slo_retrieval_degraded)
    if any(f is not None for f in slo_flags) and args.fed_interval <= 0:
        # SLOs evaluate on federation ticks: accepting the flags while
        # silently never arming them would look like paging that is on
        # but is dead.
        raise SystemExit("--slo-* objectives require federation "
                         "(--fed-interval > 0)")
    aggregator = None
    history = None
    if args.fed_interval > 0:
        def _fed_targets() -> dict:
            return {w.worker_id: w.url for w in pool.workers()
                    if w.url}

        aggregator = obs.FleetAggregator(
            _fed_targets, local={"router": registry},
            interval_s=args.fed_interval)
        router.aggregator = aggregator
        # Metrics-history plane (ISSUE 18): every federation tick lands
        # one sample per derived series in the rollup store, the MAD
        # detector judges each as it arrives, and the router serves the
        # retained view at /metrics/history. Always on with federation
        # — the plane is bounded memory and off the hot path.
        history = obs.MetricHistory(
            raw_len=args.history_raw, rollup_len=args.history_raw,
            spill_dir=args.history_dir, registry=registry)
        watch = None
        if args.anomaly_series:
            watch = {s.strip() for s in args.anomaly_series.split(",")
                     if s.strip()}
        detector = obs.AnomalyDetector(
            store=router.alerts, warmup=args.anomaly_warmup,
            mad_factor=args.anomaly_mad, watch=watch,
            registry=registry)
        recorder = obs.HistoryRecorder(history, detector=detector)
        aggregator.on_merge.append(recorder.on_merge)
        router.history = history
        objectives = []
        if args.slo_availability is not None:
            objectives.append(obs.Objective(
                name="availability", kind="availability",
                target=args.slo_availability,
                total_metric="fleet_requests_total",
                bad_metric="fleet_rejected_total",
                # Saturation is backpressure, not failure: the client
                # was told to retry.
                bad_exclude={"reason": "saturated"},
                fast_window_s=args.slo_fast_window,
                slow_window_s=args.slo_slow_window,
                burn_factor=args.slo_burn_factor))
        if args.slo_latency_ms is not None:
            objectives.append(obs.Objective(
                name="latency_p99", kind="quantile",
                target=args.slo_latency_ms,
                metric="fleet_latency_ms", labels={"stage": "total"},
                q=0.99))
        if args.slo_drift is not None:
            objectives.append(obs.Objective(
                name="shadow_drift_p99", kind="quantile",
                target=args.slo_drift,
                metric="fleet_shadow_drift", q=0.99,
                min_samples=args.shadow_min_samples))
        if args.slo_retrieval_degraded is not None:
            # Retrieval health rides the same burn machinery as
            # availability (ISSUE 18 satellite): sustained degraded-
            # search fraction over both windows pages through /alerts.
            objectives.append(obs.Objective(
                name="retrieval_degraded", kind="availability",
                target=args.slo_retrieval_degraded,
                total_metric="retrieval_searches_total",
                bad_metric="retrieval_shard_degraded_searches_total",
                fast_window_s=args.slo_fast_window,
                slow_window_s=args.slo_slow_window,
                burn_factor=args.slo_burn_factor))
        if objectives:
            engine = obs.SLOEngine(objectives, store=router.alerts)
            aggregator.on_merge.append(engine.evaluate)

    # Per-tenant admission control (ISSUE 16): independent of
    # --autoscale — quotas make sense on a fixed fleet too.
    if args.tenant_quota:
        from ntxent_tpu.serving import TenantAdmission, parse_tenant_quotas

        try:
            quotas = parse_tenant_quotas(args.tenant_quota)
        except ValueError as e:
            raise SystemExit(f"--tenant-quota: {e}")
        default_rate, default_burst = quotas.pop("default", (100.0, None))
        router.admission = TenantAdmission(
            default_rate=default_rate, default_burst=default_burst,
            quotas=quotas, registry=registry)
        logger.info("admission control: %d named tenant quota(s), "
                    "default %.1f rows/s", len(quotas), default_rate)

    # Closed-loop autoscaling (ISSUE 16): the controller observes the
    # same federated registry the SLO engine does, so it MUST ride a
    # federation tick — accepting --autoscale without --fed-interval
    # would be a controller that never observes.
    controller = None
    if args.predict_horizon is not None and not args.autoscale:
        raise SystemExit("--predict-horizon is a scale-up input: it "
                         "requires --autoscale")
    if args.autoscale:
        if attach:
            raise SystemExit("--autoscale is not available in "
                             "--attach-workdir mode: a replica router "
                             "does not own the worker processes")
        if args.fed_interval <= 0:
            raise SystemExit("--autoscale requires federation "
                             "(--fed-interval > 0): sizing decisions "
                             "consume the federated signals")
        from ntxent_tpu.serving import AutoscaleController, flash_crowd

        min_w = args.min_workers if args.min_workers is not None else 1
        max_w = args.max_workers if args.max_workers is not None \
            else max(args.workers, 4)
        if not 1 <= min_w <= max_w:
            raise SystemExit(f"need 1 <= --min-workers <= "
                             f"--max-workers, got {min_w}..{max_w}")
        controller = AutoscaleController(
            fleet, pool, registry=registry,
            min_workers=min_w, max_workers=max_w,
            up_queue_depth=args.scale_up_queue,
            up_inflight=args.scale_up_inflight,
            up_p99_ms=args.scale_up_p99_ms,
            up_burn=args.scale_up_burn,
            up_ticks=args.scale_up_ticks,
            idle_ticks=args.scale_idle_ticks,
            up_cooldown_s=args.scale_up_cooldown,
            down_cooldown_s=args.scale_down_cooldown,
            drain_deadline_s=args.drain_deadline,
            slo_target=args.scale_slo_target,
            predict_horizon_s=args.predict_horizon,
            predict_capacity=args.predict_capacity,
            predict_season_s=args.predict_season,
            up_rss_bytes=args.scale_up_rss_bytes,
            history=history)
        aggregator.on_merge.append(controller.observe)
        fleet.autoscaler = controller

        def _on_spike(action: str) -> None:
            # chaos 'spike@T': a closed-loop flash crowd against our
            # own router, off the supervision thread. 3 rows/request
            # keeps each forward cheap while the concurrency drives
            # queueing — what the controller must react to.
            import json as _json
            s = args.image_size
            row = [[[0.5, 0.5, 0.5]] * s] * s
            body = _json.dumps({"inputs": [row] * 3}).encode()
            url = f"http://{args.host}:{router.port}"
            threading.Thread(
                target=flash_crowd, args=(url, body),
                kwargs={"duration_s": 3.0, "concurrency": 8,
                        "tenant": "chaos-spike"},
                daemon=True, name="chaos-spike").start()

        fleet.on_spike = _on_spike
        if index_mgr is not None:
            # ISSUE 17 satellite: heavy retrieval maintenance (segment
            # compaction, docstore log compaction) defers to the
            # autoscaler's idle detector instead of running blind
            # against a loaded fleet; the manager bounds the deferral
            # so a permanently busy fleet still compacts.
            index_mgr.heavy_gate = controller.maintenance_ok
            logger.info("retrieval: heavy maintenance gated on fleet "
                        "idleness (forced through after %d deferred "
                        "tick(s))", index_mgr.heavy_defer_ticks)
        logger.info("autoscale: pool %d..%d (start %d), up after %d "
                    "pressure tick(s), drain after %d idle tick(s)",
                    min_w, max_w, args.workers, args.scale_up_ticks,
                    args.scale_idle_ticks)
        if args.predict_horizon is not None:
            logger.info("autoscale: predictive scale-up armed — "
                        "%.0fs horizon%s", args.predict_horizon,
                        f", {args.predict_capacity:.0f} req/s/worker "
                        "rated capacity"
                        if args.predict_capacity is not None else "")

    stop = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001
        logger.info("fleet: signal %d — draining", signum)
        stop.set()

    _signal.signal(_signal.SIGTERM, _on_signal)
    _signal.signal(_signal.SIGINT, _on_signal)

    fleet.start()
    if shard_fleet is not None:
        shard_fleet.start()
    if router.shards is not None:
        # Repair loop (ISSUE 20): probe dead shards, drain journal
        # debt through the normal insert path once they answer.
        router.shards.start(args.shard_repair_interval)
    router.start()
    if index_mgr is not None:
        index_mgr.start()
    if shadow is not None:
        shadow.start()
    if aggregator is not None:
        aggregator.start()
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(router.port))
        os.replace(tmp, args.port_file)
    logger.info("fleet: router on http://%s:%d over %d worker(s) "
                "(workdir %s)", args.host, router.port, args.workers,
                workdir)
    try:
        while not stop.is_set():
            stop.wait(0.2)
    finally:
        if aggregator is not None:
            aggregator.stop()
        if history is not None:
            # Final spill: a clean shutdown leaves the full retained
            # view on disk for the next --history-dir reopen.
            history.close()
        if shadow is not None:
            shadow.stop()
        if index_mgr is not None:
            index_mgr.stop()
        for srv in shard_servers:
            srv.stop()
        if shard_fleet is not None:
            shard_fleet.stop()
        if router.shards is not None:
            router.shards.close()
        router.close()
        fleet.stop()
        if event_log is not None:
            obs.install(None)
            event_log.close()
    return 0


def build_eval_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ntxent-eval",
        description="SSL evaluation of a pretrained checkpoint: linear "
                    "probe and weighted-kNN on frozen encoder features")
    _add_common_args(p)  # model/proj flags must match the training run
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--objective", default="simclr",
                   choices=["simclr", "clip"],
                   help="what the checkpoint was trained with; clip "
                        "evaluates the projected, L2-normalized image "
                        "embeddings (encode_image — CLIP's shared space) "
                        "and needs --vocab-size/--token-len to match the "
                        "run")
    p.add_argument("--vocab-size", type=int, default=49408)
    p.add_argument("--token-len", type=int, default=77)
    p.add_argument("--accum-steps", type=int, default=1,
                   help="match the training run's value (it shapes the "
                        "checkpoint's optimizer-state pytree)")
    p.add_argument("--protocol", default="both",
                   choices=["probe", "knn", "both", "finetune", "zeroshot"],
                   help="frozen-feature probe / kNN; end-to-end "
                        "fine-tuning of the whole encoder (SimCLR-objective "
                        "checkpoints only); or zeroshot — CLIP-objective "
                        "checkpoints classify test images by nearest "
                        "text-prompt embedding (--class-tokens)")
    p.add_argument("--class-tokens", default=None, metavar="NPY",
                   help="zeroshot: (num_classes, token_len) int array of "
                        "pre-tokenized class prompts (the framework has "
                        "no tokenizer — tokenize prompts like 'a photo "
                        "of a dog' with your vocab and save via "
                        "np.save); row i is the prompt for label i")
    p.add_argument("--finetune-steps", type=int, default=500)
    p.add_argument("--finetune-lr", type=float, default=1e-3)
    p.add_argument("--finetune-batch", type=int, default=64,
                   help="fine-tune training minibatch (full backprop "
                        "through the encoder — much heavier than the "
                        "--batch feature-extraction inference batch)")
    p.add_argument("--batch", type=int, default=256,
                   help="feature-extraction batch")
    p.add_argument("--probe-steps", type=int, default=500)
    p.add_argument("--k", type=int, default=20)
    p.add_argument("--max-train", type=int, default=10000,
                   help="subsample caps keep eval wall time bounded")
    p.add_argument("--max-test", type=int, default=2000)
    return p


def _labeled_arrays(args, test_only: bool = False):
    """(train_images, train_labels, test_images, test_labels) as float32
    NHWC in [0, 1]. ``test_only=True`` skips loading/decoding the train
    split (returning empty train arrays) — the zero-shot protocol needs
    no training data, and reading 50k CIFAR images or decoding thousands
    of JPEGs just to discard them is the kind of silent cost the caps
    exist to prevent."""
    import numpy as np

    def subsample(images, labels, cap, seed):
        if cap and len(images) > cap:
            idx = np.random.RandomState(seed).choice(
                len(images), cap, replace=False)
            return images[idx], labels[idx]
        return images, labels

    if args.dataset == "cifar10":
        from ntxent_tpu.training.datasets import Cifar10Source

        if args.data_dir is None:
            raise SystemExit("--dataset cifar10 requires --data-dir")
        te = Cifar10Source(args.data_dir, train=False)
        xte, yte = te.images, te.labels
        if test_only:
            xtr = np.zeros((0,) + xte.shape[1:], xte.dtype)
            ytr = np.zeros((0,), yte.dtype)
        else:
            tr = Cifar10Source(args.data_dir, train=True)
            xtr, ytr = tr.images, tr.labels
    elif args.dataset == "imagefolder":
        from ntxent_tpu.training.datasets import ImageFolderSource

        if args.data_dir is None:
            raise SystemExit("--dataset imagefolder requires --data-dir")
        src = ImageFolderSource(args.data_dir, image_size=args.image_size)
        labels = np.asarray(src.labels_list, np.int32)
        # No held-out split in a bare folder: even/odd split by index.
        # Cap the index lists BEFORE decoding — the caps exist so that an
        # ImageNet-sized folder is never read whole into memory.
        def pick(idxs, cap, seed):
            if cap and len(idxs) > cap:
                idxs = np.random.RandomState(seed).choice(
                    idxs, cap, replace=False)
            return np.sort(idxs)

        te_idx = pick(np.arange(1, len(src), 2), args.max_test,
                      args.seed + 1)
        if len(te_idx) == 0:
            # np.stack([]) below would raise an opaque ValueError; a
            # 1-image folder has no odd-index test half (ADVICE r4 #2).
            raise SystemExit(
                f"imagefolder {args.data_dir} has no test images (the "
                "odd-index half is empty); need at least 2 images")
        xte = np.stack([src[int(i)] for i in te_idx])
        yte = labels[te_idx]
        if test_only:
            xtr = np.zeros((0,) + xte.shape[1:], xte.dtype)
            ytr = np.zeros((0,), yte.dtype)
        else:
            tr_idx = pick(np.arange(0, len(src), 2), args.max_train,
                          args.seed)
            xtr = np.stack([src[int(i)] for i in tr_idx])
            ytr = labels[tr_idx]
    elif args.dataset == "npy":
        raise SystemExit("--dataset npy has no labels; evaluation needs "
                         "cifar10 or imagefolder")
    else:
        rng = np.random.RandomState(args.seed)
        n, s = 512, args.image_size
        labels = rng.randint(0, 4, n).astype(np.int32)
        # Class-dependent mean shift makes the synthetic task learnable.
        imgs = (rng.rand(n, s, s, 3) * 0.5
                + labels[:, None, None, None] * 0.125).astype(np.float32)
        xtr, ytr = imgs[:384], labels[:384]
        xte, yte = imgs[384:], labels[384:]
    xtr, ytr = subsample(xtr, ytr, args.max_train, args.seed)
    xte, yte = subsample(xte, yte, args.max_test, args.seed + 1)
    to_f32 = lambda x: (x.astype(np.float32) / 255.0  # noqa: E731
                        if x.dtype == np.uint8 else x.astype(np.float32))
    return to_f32(xtr), ytr, to_f32(xte), yte


def eval_main(argv=None) -> int:
    args = build_eval_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    if args.protocol == "finetune" and args.objective == "clip":
        # Both flags are known now — fail before any checkpoint restore
        # or dataset scan is paid for.
        logger.error("--protocol finetune needs a SimCLR-objective "
                     "checkpoint (an encoder with a features method)")
        return 2
    if args.protocol == "zeroshot":
        # Same fail-early policy as finetune: both flags are known now.
        if args.objective != "clip":
            logger.error("--protocol zeroshot needs a CLIP-objective "
                         "checkpoint (a text tower to embed the class "
                         "prompts); got --objective %s", args.objective)
            return 2
        if not args.class_tokens:
            logger.error("--protocol zeroshot requires --class-tokens "
                         "(pre-tokenized class prompts; see --help)")
            return 2

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    if args.image_size is None:
        args.image_size = 224 if args.dataset == "imagefolder" else 32

    import jax.numpy as jnp

    from ntxent_tpu.models import SimCLRModel
    from ntxent_tpu.training import (
        TrainerConfig,
        create_train_state,
        extract_features,
        knn_accuracy,
        linear_probe,
    )
    from ntxent_tpu.training.checkpoint import CheckpointManager

    if args.objective == "clip":
        # CLIP checkpoint: the template's pytree must match _train_clip's
        # (CLIPModel params; AdamW opt state, MultiSteps-wrapped if the run
        # accumulated). Features = projected image embeddings.
        if args.model.startswith("resnet"):
            raise SystemExit("--objective clip checkpoints have ViT image "
                             "towers (--model vit_*|tiny); no resnet CLIP "
                             "checkpoint can exist")
        import numpy as np
        import optax

        from ntxent_tpu.training.trainer import TrainState

        model = _build_clip_model(args)
        variables0 = model.init(
            jax.random.PRNGKey(0),
            np.zeros((1, args.image_size, args.image_size, 3), np.float32),
            np.zeros((1, args.token_len), np.int32), train=False)
        # A SCHEDULE (callable), matching _train_clip's tx: adamw with a
        # float LR has an EmptyState where the schedule keeps a count, and
        # checkpoint restore is structure-strict (from_bytes walks the
        # template's state dict).
        tx = optax.adamw(lambda step: 0.0)
        if args.accum_steps > 1:
            tx = optax.MultiSteps(tx, every_k_schedule=args.accum_steps)
        template = TrainState.create(apply_fn=model.apply,
                                     params=variables0["params"], tx=tx)
    else:
        encoder = _make_encoder(args.model, args.image_size,
                                moe_experts=args.moe_experts,
                                stem=args.stem)
        model = SimCLRModel(encoder=encoder,
                            proj_hidden_dim=args.proj_hidden_dim,
                            proj_dim=args.proj_dim)
        template = create_train_state(
            model, jax.random.PRNGKey(0),
            (1, args.image_size, args.image_size, 3),
            TrainerConfig(accum_steps=args.accum_steps))
    manager = CheckpointManager(args.ckpt_dir)
    try:
        if manager.latest_step() is None:
            raise SystemExit(f"no checkpoint under {args.ckpt_dir}")
        state = manager.restore(template)
    finally:
        manager.close()
    logger.info("restored step %d from %s", int(state.step), args.ckpt_dir)

    if args.objective == "clip":
        variables = {"params": state.params}

        def apply_features(x):
            # Projected, L2-normalized image embeddings — CLIP's shared
            # embedding space (the space its transfer results are quoted
            # in), via the tower-only encode_image method.
            return model.apply(variables, x, method="encode_image")
    else:
        variables = {"params": state.params,
                     "batch_stats": state.batch_stats}

        def apply_features(x):
            return model.apply(variables, x, train=False,
                               method="features")

    if args.protocol == "zeroshot":
        # The signature CLIP transfer eval: no training on the target
        # task at all — each class becomes a text-prompt embedding and
        # test images classify to the nearest one in the shared space.
        # The candidate set is the WHOLE prompt file (row i = label i) —
        # not the labels that happened to survive subsampling, which
        # would silently shrink the argmax competition and inflate the
        # accuracy — and only the test split is loaded.
        import json

        import numpy as np

        toks = np.load(args.class_tokens)
        if toks.ndim != 2 or not np.issubdtype(toks.dtype, np.integer):
            raise SystemExit(f"--class-tokens must be a 2-D integer "
                             f"array; got {toks.dtype} {toks.shape}")
        if toks.shape[1] != args.token_len:
            raise SystemExit(f"--class-tokens rows are {toks.shape[1]} "
                             f"tokens but the checkpoint's text tower "
                             f"takes --token-len {args.token_len}")
        # Same both-sided id check as the train-side guard (cli.py token
        # validation): XLA clamps out-of-range embedding gathers
        # silently, so a bad id would yield a plausible, wrong accuracy.
        if int(toks.min()) < 0 or int(toks.max()) >= args.vocab_size:
            raise SystemExit(f"--class-tokens ids must be in [0, "
                             f"{args.vocab_size}); got range "
                             f"[{int(toks.min())}, {int(toks.max())}]")
        _, _, xte, yte = _labeled_arrays(args, test_only=True)
        n_prompt = int(toks.shape[0])
        if len(yte) == 0:
            # yte.max() on an empty split raises numpy's opaque
            # "zero-size array reduction" instead of an actionable exit
            # (ADVICE r4 #2); defense-in-depth behind the per-dataset
            # guards in _labeled_arrays.
            raise SystemExit("zero-shot eval needs a non-empty test "
                             "split; got 0 test examples (check the "
                             "dataset's test half)")
        if int(yte.max()) >= n_prompt:
            raise SystemExit(f"test labels reach {int(yte.max())} but "
                             f"--class-tokens has only {n_prompt} prompt "
                             "rows (row i = label i)")
        # Both encoders L2-normalize (models/clip.py), so the matmul IS
        # cosine similarity; the learnable scale only rescales logits and
        # cannot change the argmax.
        text_emb = model.apply(variables, jnp.asarray(toks),
                               method="encode_text")
        fte = extract_features(apply_features, jnp.asarray(xte),
                               args.batch)
        pred = jnp.argmax(fte @ text_emb.T, axis=1)
        acc = float(jnp.mean((pred == jnp.asarray(yte)).astype(
            jnp.float32)))
        results = {"step": int(state.step), "zeroshot_top1": acc,
                   "num_classes": n_prompt, "num_test": int(len(yte))}
        logger.info("zero-shot top-1: %.4f over %d prompt classes", acc,
                    n_prompt)
        print(json.dumps(results))
        return 0

    xtr, ytr, xte, yte = _labeled_arrays(args)
    num_classes = int(max(int(ytr.max()), int(yte.max()))) + 1

    if args.protocol == "finetune":
        from ntxent_tpu.training import finetune

        import json

        res = finetune(model, variables, jnp.asarray(xtr), jnp.asarray(ytr),
                       jnp.asarray(xte), jnp.asarray(yte),
                       num_classes=num_classes,
                       steps=args.finetune_steps,
                       batch_size=args.finetune_batch,
                       learning_rate=args.finetune_lr,
                       key=jax.random.PRNGKey(args.seed))
        results = {"step": int(state.step),
                   "finetune_top1": float(res["test_accuracy"]),
                   "finetune_train_top1": float(res["train_accuracy"])}
        logger.info("finetune top-1: %.4f", results["finetune_top1"])
        print(json.dumps(results))
        return 0

    # One extraction pass over the concatenation: extract_features jits its
    # argument internally, so two calls would compile the encoder twice.
    import numpy as np

    feats = extract_features(
        apply_features, jnp.asarray(np.concatenate([xtr, xte])), args.batch)
    ftr, fte = feats[:len(xtr)], feats[len(xtr):]
    ytr, yte = jnp.asarray(ytr), jnp.asarray(yte)
    logger.info("features: train %s test %s, %d classes",
                ftr.shape, fte.shape, num_classes)

    results = {"step": int(state.step)}
    if args.protocol in ("knn", "both"):
        results["knn_top1"] = float(
            knn_accuracy(ftr, ytr, fte, yte, k=args.k))
        logger.info("kNN (k=%d) top-1: %.4f", args.k, results["knn_top1"])
    if args.protocol in ("probe", "both"):
        probe = linear_probe(ftr, ytr, fte, yte, num_classes,
                             steps=args.probe_steps,
                             key=jax.random.PRNGKey(args.seed))
        results["probe_top1"] = float(probe["test_accuracy"])
        logger.info("linear probe top-1: %.4f", results["probe_top1"])
    import json

    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
