"""``ntxent-train``: command-line SimCLR pretraining driver.

The runtime config/flag surface for the framework (SURVEY.md §5.6: the
reference's only knobs were build-time CMake options,
/root/reference/CMakeLists.txt:9-16, plus per-call kwargs — it shipped no
way to actually launch the training its name promised). One command covers
the BASELINE.json config ladder: synthetic smoke runs, CIFAR-10 single
chip, ImageNet-layout folders on a data-parallel mesh, multi-host via
explicit coordinator flags (the mpirun role).

Everything here composes public API: datasets.TwoViewPipeline ->
create_mesh/global_batch -> make_train_step/make_sharded_train_step ->
fit under a PreemptionGuard (SIGTERM => checkpoint => clean exit => exact
resume on relaunch).
"""

from __future__ import annotations

import argparse
import functools
import logging
import sys

logger = logging.getLogger("ntxent_tpu.cli")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ntxent-train",
        description="TPU-native SimCLR pretraining (fused NT-Xent loss)")
    d = p.add_argument_group("data")
    d.add_argument("--dataset", default="synthetic",
                   choices=["synthetic", "cifar10", "imagefolder"])
    d.add_argument("--data-dir", default=None,
                   help="CIFAR-10 pickle dir / ImageNet-layout root")
    d.add_argument("--image-size", type=int, default=None,
                   help="default: 32 (synthetic/cifar10) or 224")
    d.add_argument("--synthetic-samples", type=int, default=512)

    m = p.add_argument_group("model")
    m.add_argument("--model", default="resnet50",
                   choices=["resnet18", "resnet34", "resnet50", "resnet50x2",
                            "resnet101", "resnet152", "vit_t16", "vit_s16",
                            "vit_b16", "vit_l16", "tiny"])
    m.add_argument("--proj-hidden-dim", type=int, default=2048)
    m.add_argument("--proj-dim", type=int, default=128)

    t = p.add_argument_group("training")
    t.add_argument("--batch", type=int, default=256,
                   help="GLOBAL batch (split across devices and processes)")
    t.add_argument("--steps", type=int, default=1000)
    t.add_argument("--temperature", type=float, default=0.1)
    t.add_argument("--base-lr", type=float, default=0.3)
    t.add_argument("--weight-decay", type=float, default=1e-6)
    t.add_argument("--warmup-steps", type=int, default=100)
    t.add_argument("--accum-steps", type=int, default=1)
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--ckpt-dir", default=None)
    t.add_argument("--ckpt-every", type=int, default=500)
    t.add_argument("--log-every", type=int, default=50)

    dist = p.add_argument_group("distributed (multi-host rendezvous; "
                                "single-host multi-chip needs no flags)")
    dist.add_argument("--coordinator", default=None,
                      help="host:port of process 0 (mpirun role; "
                           "auto-detected on Cloud TPU)")
    dist.add_argument("--num-processes", type=int, default=None)
    dist.add_argument("--process-id", type=int, default=None)

    p.add_argument("--platform", default=None, metavar="cpu|tpu",
                   help="force a JAX platform before backend init")
    return p


def _make_encoder(name: str, image_size: int):
    from ntxent_tpu import models

    if name == "tiny":
        return functools.partial(models.ResNet, stage_sizes=(1,),
                                 small_images=True)
    table = {
        "resnet18": models.ResNet18, "resnet34": models.ResNet34,
        "resnet50": models.ResNet50, "resnet50x2": models.ResNet50x2,
        "resnet101": models.ResNet101, "resnet152": models.ResNet152,
        "vit_t16": models.ViT_Ti16, "vit_s16": models.ViT_S16,
        "vit_b16": models.ViT_B16, "vit_l16": models.ViT_L16,
    }
    enc = table[name]
    if name.startswith("resnet") and image_size <= 64:
        enc = functools.partial(enc, small_images=True)
    return enc


def _make_pipeline(args, per_process_batch: int, sharding=None, mesh=None):
    import numpy as np

    import jax

    from ntxent_tpu.training.datasets import (
        ArraySource,
        Cifar10Source,
        GlobalTwoViewPipeline,
        ImageFolderSource,
        StreamingLoader,
        TwoViewPipeline,
    )

    size = args.image_size
    if args.dataset == "cifar10":
        if args.data_dir is None:
            raise SystemExit("--dataset cifar10 requires --data-dir")
        source = Cifar10Source(args.data_dir)
    elif args.dataset == "imagefolder":
        if args.data_dir is None:
            raise SystemExit("--dataset imagefolder requires --data-dir")
        source = ImageFolderSource(args.data_dir, image_size=size)
    else:
        rng = np.random.RandomState(args.seed)
        source = ArraySource(rng.rand(
            args.synthetic_samples, size, size, 3).astype(np.float32))
    # Multi-process: each process streams ITS slice of every global batch
    # (seeded identically, offset by process_id — the per-rank DataLoader).
    loader = StreamingLoader(source, per_process_batch, seed=args.seed,
                             shard_index=jax.process_index(),
                             shard_count=jax.process_count())
    key = jax.random.PRNGKey(args.seed + 1)
    if mesh is not None and jax.process_count() > 1:
        # Global assembly before augmentation: only raw bytes cross the
        # host boundary, views are born sharded (one replicated program —
        # same key everywhere; per-row randomness is global-position-based).
        return GlobalTwoViewPipeline(loader, key=key, mesh=mesh)
    return TwoViewPipeline(loader, key=key, sharding=sharding)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    # Rendezvous BEFORE any backend touch (explicit flags or cloud
    # auto-detect; a plain single-process run is a logged no-op).
    from ntxent_tpu.parallel.mesh import (
        create_mesh, init_distributed, process_info)

    init_distributed(coordinator_address=args.coordinator,
                     num_processes=args.num_processes,
                     process_id=args.process_id)
    info = process_info()
    logger.info("topology: %s", info)

    if args.image_size is None:
        args.image_size = 224 if args.dataset == "imagefolder" else 32
    if args.batch % info["global_device_count"]:
        raise SystemExit(
            f"--batch {args.batch} must divide across "
            f"{info['global_device_count']} devices")
    per_process_batch = args.batch // info["process_count"]

    from ntxent_tpu.models import SimCLRModel
    from ntxent_tpu.training import (
        PreemptionGuard,
        TrainerConfig,
        create_train_state,
        fit,
        make_train_step,
    )
    from ntxent_tpu.training.trainer import make_sharded_train_step

    encoder = _make_encoder(args.model, args.image_size)
    model = SimCLRModel(encoder=encoder,
                        proj_hidden_dim=args.proj_hidden_dim,
                        proj_dim=args.proj_dim)
    cfg = TrainerConfig(
        batch_size=args.batch, temperature=args.temperature,
        base_lr=args.base_lr, weight_decay=args.weight_decay,
        warmup_steps=args.warmup_steps, total_steps=args.steps,
        accum_steps=args.accum_steps)
    state = create_train_state(
        model, jax.random.PRNGKey(args.seed),
        (1, args.image_size, args.image_size, 3), cfg)

    n_dev = info["global_device_count"]
    if n_dev > 1:
        from ntxent_tpu.parallel.mesh import data_sharding

        mesh = create_mesh(axis_names=("data",))
        step = make_sharded_train_step(mesh, cfg.temperature)
        # Batches arrive already sharded over the mesh: single-process via
        # sharded device_put + sharded augmentation, multi-process via
        # GlobalTwoViewPipeline's uint8 global assembly.
        data = _make_pipeline(args, per_process_batch,
                              sharding=data_sharding(mesh), mesh=mesh)
        logger.info("data-parallel over %d devices (%d process(es))",
                    n_dev, info["process_count"])
    else:
        step = make_train_step(cfg.temperature)
        data = _make_pipeline(args, per_process_batch)
        logger.info("single-device run")

    with PreemptionGuard() as guard:
        state, history = fit(
            state, data, step, num_steps=args.steps,
            checkpoint_dir=args.ckpt_dir, checkpoint_every=args.ckpt_every,
            log_every=args.log_every, stop_fn=guard.requested)
    if history:
        last = history[-1]
        logger.info("final: step %d loss %.4f (%.2f steps/s%s)",
                    last["step"], last["loss"], last["steps_per_sec"],
                    f", MFU {last['mfu']:.1%}" if "mfu" in last else "")
    if guard.preempted:
        logger.warning("run was preempted; checkpoint saved at step %d — "
                       "relaunch with the same flags to resume",
                       int(state.step))
    return 0


if __name__ == "__main__":
    sys.exit(main())
