"""ntxent_tpu — TPU-native contrastive-learning framework.

Built from scratch in JAX/XLA/Pallas with the capabilities of the reference
CUDA framework (sanowl/CUDA-NT-Xent-MPI-NCCL-SimCLR). This top-level module
exports the loss core: the jnp oracles, the fused Pallas NT-Xent kernel with
exact custom-VJP gradients, and the reference-compatible
forward/backward/check_tensor_core_support API; ``ntxent_tpu.utils`` holds
the capability/memory/profiling helpers. See SURVEY.md at the repo root for
the full mapping to the reference.

Exports resolve lazily (PEP 562): importing ``ntxent_tpu`` does NOT import
JAX. That keeps the JAX-free processes honest — the fleet router tier
(``ntxent-fleet``), the crashsim harness, and bench.py's parent all live
inside this package namespace but must never pay the multi-second JAX
import (let alone backend init) just to exist. The first access to a loss
API name triggers the real import.
"""

import importlib

__version__ = "0.1.0"

# name -> defining submodule; resolved on first attribute access.
_EXPORTS = {
    "forward": "ntxent_tpu.api",
    "backward": "ntxent_tpu.api",
    "check_tensor_core_support": "ntxent_tpu.api",
    "ntxent": "ntxent_tpu.api",
    "info_nce_fused": "ntxent_tpu.ops.infonce_pallas",
    "ntxent_loss_and_lse": "ntxent_tpu.ops.ntxent_pallas",
    "ntxent_loss_fused": "ntxent_tpu.ops.ntxent_pallas",
    "ntxent_partial_fused": "ntxent_tpu.ops.ntxent_pallas",
    "cosine_normalize": "ntxent_tpu.ops.oracle",
    "info_nce_loss": "ntxent_tpu.ops.oracle",
    "ntxent_loss": "ntxent_tpu.ops.oracle",
    "ntxent_loss_compat": "ntxent_tpu.ops.oracle",
    "ntxent_loss_paired": "ntxent_tpu.ops.oracle",
}

__all__ = [
    "forward",
    "backward",
    "check_tensor_core_support",
    "ntxent",
    "ntxent_loss",
    "ntxent_loss_paired",
    "ntxent_loss_compat",
    "ntxent_loss_fused",
    "ntxent_loss_and_lse",
    "ntxent_partial_fused",
    "cosine_normalize",
    "info_nce_loss",
    "info_nce_fused",
    "__version__",
]


def __getattr__(name):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    value = getattr(importlib.import_module(module), name)
    globals()[name] = value  # cache: later access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
