"""ntxent_tpu — TPU-native contrastive-learning framework.

Built from scratch in JAX/XLA/Pallas with the capabilities of the reference
CUDA framework (sanowl/CUDA-NT-Xent-MPI-NCCL-SimCLR). This top-level module
exports the loss core: the jnp oracles, the fused Pallas NT-Xent kernel with
exact custom-VJP gradients, and the reference-compatible
forward/backward/check_tensor_core_support API; ``ntxent_tpu.utils`` holds
the capability/memory/profiling helpers. See SURVEY.md at the repo root for
the full mapping to the reference.
"""

from ntxent_tpu.api import backward, check_tensor_core_support, forward, ntxent
from ntxent_tpu.ops.infonce_pallas import info_nce_fused
from ntxent_tpu.ops.ntxent_pallas import (
    ntxent_loss_and_lse,
    ntxent_loss_fused,
    ntxent_partial_fused,
)
from ntxent_tpu.ops.oracle import (
    cosine_normalize,
    info_nce_loss,
    ntxent_loss,
    ntxent_loss_compat,
    ntxent_loss_paired,
)

__version__ = "0.1.0"

__all__ = [
    "forward",
    "backward",
    "check_tensor_core_support",
    "ntxent",
    "ntxent_loss",
    "ntxent_loss_paired",
    "ntxent_loss_compat",
    "ntxent_loss_fused",
    "ntxent_loss_and_lse",
    "ntxent_partial_fused",
    "cosine_normalize",
    "info_nce_loss",
    "info_nce_fused",
    "__version__",
]
