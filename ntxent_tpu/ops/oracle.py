"""Pure-jnp NT-Xent oracles: the gold standard every kernel is tested against.

This module is the TPU-native re-design of the reference's loss semantics
(reference: /root/reference/src/ntxent_kernel.cu:138-239). Two semantics are
provided:

* ``ntxent_loss`` / ``ntxent_loss_paired`` — **canonical** SimCLR NT-Xent
  (Chen et al. 2020): input is 2N embeddings of N positive pairs, the positive
  of row i sits at ``(i + N) mod 2N``, and the self-similarity diagonal is
  masked to -inf. This is the *intended* capability of the reference (its
  as-written code deviates; see SURVEY.md §2.3-D10).

* ``ntxent_loss_compat`` — the reference's **as-written** behavior for
  comparison: ``z_cat = concat([z, z])`` duplicates the same B embeddings
  (ntxent_kernel.cu:161) and the *diagonal* is treated as the positive with no
  masking (compute_loss_kernel, ntxent_kernel.cu:105-134), i.e.
  ``-mean_i log softmax(sim)_ii``.

All oracles are differentiable; ``jax.grad`` of these functions is the
gradient gold standard the reference's backward never was (SURVEY.md §2.3-D8:
the reference keeps only an incorrect diagonal term and ignores grad_out).

Everything here runs through XLA on CPU/GPU/TPU unchanged — one correctness
suite for all backends.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "cosine_normalize",
    "similarity_matrix",
    "ntxent_loss",
    "ntxent_loss_paired",
    "ntxent_loss_and_softmax",
    "ntxent_loss_compat",
    "ntxent_grad_oracle",
    "info_nce_loss",
]

_NEG_INF = -1e30  # large-negative mask value; avoids inf-inf NaN pitfalls


def cosine_normalize(z: jax.Array, axis: int = -1, eps: float = 1e-12) -> jax.Array:
    """L2-normalize embeddings (mirror of tests/test_utils.hpp:7-14)."""
    norm = jnp.sqrt(jnp.sum(jnp.square(z), axis=axis, keepdims=True))
    return z / jnp.maximum(norm, eps)


def similarity_matrix(z: jax.Array, temperature: float | jax.Array) -> jax.Array:
    """(2N, 2N) scaled cosine-similarity Gram matrix ``z @ z.T / T``.

    The correct form of the reference's cuBLAS SGEMM
    (ntxent_kernel.cu:165-173, which mis-strides for D != 2B; SURVEY §2.3-D7).
    Accumulates in fp32 on the MXU regardless of input dtype.
    """
    logits = jnp.dot(z, z.T, preferred_element_type=jnp.float32)
    return logits / jnp.asarray(temperature, dtype=jnp.float32)


def _masked_logits(z: jax.Array, temperature) -> tuple[jax.Array, jax.Array]:
    """Return (masked logits, positive-pair logits) for canonical NT-Xent."""
    two_n = z.shape[0]
    if two_n % 2 != 0:
        raise ValueError(f"canonical NT-Xent needs an even row count, got {two_n}")
    n = two_n // 2
    logits = similarity_matrix(z, temperature)
    rows = jnp.arange(two_n)
    # Self-similarity masked out (canonical; reference failed to, D10).
    logits = logits.at[rows, rows].set(_NEG_INF)
    pos_idx = (rows + n) % two_n
    positives = logits[rows, pos_idx]
    return logits, positives


def ntxent_loss(z: jax.Array, temperature: float | jax.Array = 0.07) -> jax.Array:
    """Canonical NT-Xent on stacked views ``z = concat([view1, view2])``.

    z: (2N, D) L2-normalized embeddings; positive of row i at (i+N) mod 2N.
    Returns the scalar mean loss ``mean_i [logsumexp_{j!=i} s_ij - s_i,pos(i)]``.
    """
    logits, positives = _masked_logits(z, temperature)
    lse = jax.nn.logsumexp(logits, axis=-1)
    return jnp.mean(lse - positives)


def ntxent_loss_paired(
    z1: jax.Array, z2: jax.Array, temperature: float | jax.Array = 0.07
) -> jax.Array:
    """Canonical NT-Xent on the two augmented views separately (N, D) + (N, D)."""
    return ntxent_loss(jnp.concatenate([z1, z2], axis=0), temperature)


def ntxent_loss_and_softmax(
    z: jax.Array, temperature: float | jax.Array = 0.07
) -> tuple[jax.Array, jax.Array]:
    """Loss plus the (2N, 2N) masked softmax matrix.

    Implements the residual-saving contract the reference *intended* but broke:
    its forward computes softmax_output then discards it (ntxent_kernel.cu:202)
    while backward demands it as input (ntxent_kernel.cuh:46-52; SURVEY §2.3-D9).
    """
    logits, positives = _masked_logits(z, temperature)
    lse = jax.nn.logsumexp(logits, axis=-1)
    softmax = jnp.exp(logits - lse[:, None])
    return jnp.mean(lse - positives), softmax


def ntxent_loss_compat(z: jax.Array, temperature: float | jax.Array = 0.07) -> jax.Array:
    """Reference as-written semantics (SURVEY §2.3-D10), for comparison only.

    z: (B, D). Duplicates rows (z_cat = [z; z], ntxent_kernel.cu:161), no
    diagonal mask, positive = self: ``-mean_i log softmax(sim)_ii``.
    """
    z_cat = jnp.concatenate([z, z], axis=0)
    logits = similarity_matrix(z_cat, temperature)
    lse = jax.nn.logsumexp(logits, axis=-1)
    diag = jnp.diagonal(logits)
    return jnp.mean(lse - diag)


def ntxent_grad_oracle(
    z: jax.Array, temperature: float | jax.Array = 0.07
) -> jax.Array:
    """Exact ``d ntxent_loss / d z`` via autodiff — the gradient gold standard."""
    return jax.grad(lambda zz: ntxent_loss(zz, temperature))(z)


def info_nce_loss(
    za: jax.Array, zb: jax.Array, temperature: float | jax.Array = 0.07
) -> jax.Array:
    """Cross-modal InfoNCE (CLIP-style): positives on the a↔b diagonal.

    za, zb: (N, D) normalized embeddings from the two modalities. Symmetric
    cross-entropy over ``za @ zb.T / T`` rows and columns. This is the
    BASELINE.json configs[4] workload (CLIP text-image, global batch 32768).
    """
    logits = jnp.dot(za, zb.T, preferred_element_type=jnp.float32)
    logits = logits / jnp.asarray(temperature, dtype=jnp.float32)
    diag = jnp.diagonal(logits)
    loss_a = jnp.mean(jax.nn.logsumexp(logits, axis=1) - diag)
    loss_b = jnp.mean(jax.nn.logsumexp(logits, axis=0) - diag)
    return 0.5 * (loss_a + loss_b)
