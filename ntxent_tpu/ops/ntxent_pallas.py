"""Fused blockwise NT-Xent loss as Pallas TPU kernels with exact custom VJP.

TPU-native re-design of the reference's CUDA pipeline
(/root/reference/src/ntxent_kernel.cu): where the reference materializes the
full (2N, 2N) similarity matrix in HBM and walks it in four passes
(cuBLAS SGEMM :165-173, row_max_kernel :8-51, softmax_kernel :53-103,
compute_loss_kernel :105-134), this implementation tiles the similarity
matrix into VMEM blocks and runs a **single fused pass**: each (row-block x
col-block) tile is produced on the MXU and immediately folded into
flash-attention-style online-softmax statistics (running max m, running sum
l) plus the positive-pair logit — the (2N, 2N) matrix never exists in HBM.
Residuals are O(N): only the per-row logsumexp survives the forward pass.

The backward pass recomputes similarity tiles (flash-style) and produces the
**exact dense gradient** — fixing the reference's backward, which kept only a
(wrong) diagonal term and ignored the upstream gradient entirely
(ntxent_kernel.cu:205-239; SURVEY.md §2.3-D8). For the symmetric single-array
case, both gradient contributions (z_i as row and as column of the similarity
matrix) fold into one kernel using the identity
``grad_z[a] = (1/T) sum_b [p[a,b] + p~[a,b] - 2*onehot_pos] z[b]`` where
``p[a,b] = exp(s[a,b] - lse[a])`` and ``p~[a,b] = exp(s[a,b] - lse[b])``
(s is symmetric and the positive mapping is an involution).

The general (rows != cols) variant powers the distributed data-parallel path:
each device computes its local-row block of the global similarity matrix
against the all-gathered column embeddings (SURVEY.md §5.7/§5.8), with
explicit global row indices so diagonal masking and positive lookup stay
correct under sharding.

Semantics are canonical NT-Xent (positives at (i+N) mod 2N, diagonal masked;
see ops/oracle.py and SURVEY.md §2.3-D10 for the reference's deviation).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .blocks import choose_blocks, round_up

__all__ = [
    "ntxent_loss_fused",
    "ntxent_partial_fused",
    "ntxent_loss_and_lse",
    "block_lse",
    "block_grads",
    "block_lse_dual",
    "block_grads_dual",
]

_NEG_INF = -1e30


def _exp0(x):
    """``exp(min(x, 0))`` — the online-softmax/softmax-prob exponent.

    Every exp in these kernels has a mathematically non-positive argument
    (``s - rowmax(s)`` or ``s - lse``), so the clamp is exact. It exists
    because a compiler may FUSE the similarity matmul into both the
    max/lse consumer and the exp consumer, recomputing it with different
    reassociation; at extreme logit magnitudes (|s| ≳ 1e9 in fp32) the
    skew between the two evaluations can exceed 88 and a mathematically
    impossible ``exp(>88) = inf`` appears (observed under XLA:CPU with the
    interpret-mode kernels; flash-attention implementations carry the
    same guard). Clamping caps the damage at exp(0) = 1.
    """
    return jnp.exp(jnp.minimum(x, 0.0))


def _log_l(l):
    """``log(l)`` with a tiny floor. Mathematically l >= 1 after any fold
    (the row-max entry contributes exp(0)); it can only reach 0 through the
    cross-evaluation skew described in _exp0, where a floor turns a
    harmless relative error into a finite lse instead of -inf."""
    return jnp.log(jnp.maximum(l, 1e-37))


def _default_interpret() -> bool:
    from ..utils.capability import is_tpu_backend

    return not is_tpu_backend(jax.devices()[0].platform)


def _tile_ids(i, j, br: int, bc: int):
    """Global (row, col) index grids for the current (BR, BC) tile."""
    rid = jax.lax.broadcasted_iota(jnp.int32, (br, 1), 0) + i * br
    cid = jax.lax.broadcasted_iota(jnp.int32, (br, bc), 1) + j * bc
    return rid, cid


def _masked_sim_tile(zr, zc, row_gid, cid, inv_t, cols_actual,
                     diag_pos: bool = False):
    """Scaled similarity tile with padded columns masked.

    NT-Xent mode (``diag_pos=False``) additionally masks the self-similarity
    diagonal; InfoNCE mode (``diag_pos=True``) keeps it — the diagonal IS the
    positive there (cross-modal za/zb, so it is not a self-pair).
    """
    s = jax.lax.dot_general(
        zr, zc,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * inv_t
    mask = cid >= cols_actual
    if not diag_pos:
        mask = jnp.logical_or(mask, cid == row_gid)
    return jnp.where(mask, _NEG_INF, s), s


def _pos_gid(row_gid, n_half: int, diag_pos: bool = False):
    """Positive-pair column per global row id.

    NT-Xent: the paired view at (gid + N) mod 2N; InfoNCE: the diagonal.
    """
    if diag_pos:
        return row_gid
    return jnp.where(row_gid < n_half, row_gid + n_half, row_gid - n_half)


# ---------------------------------------------------------------------------
# Forward kernel (general rows x cols)
# ---------------------------------------------------------------------------


def _fwd_kernel(zr_ref, zc_ref, gid_ref, cgid_ref, scale_ref, loss_ref,
                lse_ref, m_ref, l_ref, p_ref,
                *, br, bc, inv_t, cols_actual, n_half, diag_pos=False):
    i = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _():
        loss_ref[0, 0] = jnp.float32(0.0)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full((br, 1), _NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros((br, 1), jnp.float32)
        p_ref[:] = jnp.zeros((br, 1), jnp.float32)

    row_gid = gid_ref[:]                      # (BR, 1) global row ids
    cid = cgid_ref[:]                         # (1, BC) global col ids —
    # an operand, not tile arithmetic: ring blocks carry arbitrary gids
    s_masked, s_raw = _masked_sim_tile(
        zr_ref[:], zc_ref[:], row_gid, cid, inv_t * scale_ref[0, 0],
        cols_actual, diag_pos
    )

    # Positive-pair logit (unmasked: the positive is never the diagonal).
    pos_hit = cid == _pos_gid(row_gid, n_half, diag_pos)
    p_ref[:] += jnp.sum(jnp.where(pos_hit, s_raw, 0.0), axis=1, keepdims=True)

    # Online softmax update.
    m_old = m_ref[:]
    m_new = jnp.maximum(m_old, jnp.max(s_masked, axis=1, keepdims=True))
    l_ref[:] = l_ref[:] * jnp.exp(m_old - m_new) + jnp.sum(
        _exp0(s_masked - m_new), axis=1, keepdims=True
    )
    m_ref[:] = m_new

    @pl.when(j == nj - 1)
    def _():
        lse = m_ref[:] + _log_l(l_ref[:])
        lse_ref[:] = lse
        valid = row_gid < cols_actual
        loss_ref[0, 0] += jnp.sum(jnp.where(valid, lse - p_ref[:], 0.0))


def _scale_arr(scale) -> jax.Array:
    """Traced logit scale as the (1, 1) SMEM operand the kernels expect."""
    if scale is None:
        return jnp.ones((1, 1), jnp.float32)
    return jnp.asarray(scale, jnp.float32).reshape(1, 1)


def _col_gid_row(cp: int, col_gid=None) -> jax.Array:
    """(1, CP) int32 global-column-id operand; defaults to [0..CP) (the
    gathered/symmetric layouts, where column position IS the global id)."""
    if col_gid is None:
        return jnp.arange(cp, dtype=jnp.int32).reshape(1, cp)
    return col_gid.astype(jnp.int32).reshape(1, cp)


def _fwd_call(z_rows, z_cols, row_gid, *, br, bc, inv_t, cols_actual, n_half,
              interpret, diag_pos=False, scale=None, col_gid=None):
    rp, d = z_rows.shape
    cp = z_cols.shape[0]
    grid = (rp // br, cp // bc)
    kernel = functools.partial(
        _fwd_kernel, br=br, bc=bc, inv_t=inv_t,
        cols_actual=cols_actual, n_half=n_half, diag_pos=diag_pos,
    )
    loss_sum, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bc), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((rp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((br, 1), jnp.float32),
            pltpu.VMEM((br, 1), jnp.float32),
            pltpu.VMEM((br, 1), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * rp * cp * d,
            bytes_accessed=(rp * d + (rp // br) * cp * d) * z_rows.dtype.itemsize,
            transcendentals=rp * cp,
        ),
        interpret=interpret,
    )(z_rows, z_cols, row_gid, _col_gid_row(cp, col_gid), _scale_arr(scale))
    return loss_sum[0, 0], lse


# ---------------------------------------------------------------------------
# Triangular forward kernel (symmetric case): each tile computed ONCE
# ---------------------------------------------------------------------------


def _fwd_tri_kernel(zr_ref, zc_ref, loss_ref, lse_ref, m_all, l_all, p_all,
                    *, b, inv_t, cols_actual, n_half, nb):
    """Upper-triangle-only forward for the symmetric (z vs z) case.

    The similarity matrix is symmetric, so tile (i, j) with j > i carries
    the same numbers as tile (j, i) transposed. This kernel walks only
    j >= i, folding each tile into row-block i's online-softmax stats
    directly AND into row-block j's stats transposed — half the MXU work
    of the rectangular kernel. The running (m, l, p) stats live in
    full-length VMEM scratch because a row block keeps receiving
    transposed contributions from earlier grid rows; TPU grid execution is
    sequential (the accumulation pattern the rectangular kernel already
    relies on), so block r's stats are complete exactly at tile
    (r, nb-1), where its logsumexp is finalized.
    """
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _():
        loss_ref[0, 0] = jnp.float32(0.0)
        m_all[:] = jnp.full(m_all.shape, _NEG_INF, jnp.float32)
        l_all[:] = jnp.zeros(l_all.shape, jnp.float32)
        p_all[:] = jnp.zeros(p_all.shape, jnp.float32)

    @pl.when(j >= i)
    def _():
        rid, cid = _tile_ids(i, j, b, b)
        s_masked, s_raw = _masked_sim_tile(
            zr_ref[:], zc_ref[:], rid, cid, inv_t, cols_actual
        )
        pos_hit = cid == _pos_gid(rid, n_half)

        # Direct fold into row-block i.
        rs = pl.ds(i * b, b)
        p_all[rs] += jnp.sum(jnp.where(pos_hit, s_raw, 0.0),
                             axis=1, keepdims=True)
        m_old = m_all[rs]
        m_new = jnp.maximum(m_old, jnp.max(s_masked, axis=1, keepdims=True))
        l_all[rs] = l_all[rs] * jnp.exp(m_old - m_new) + jnp.sum(
            _exp0(s_masked - m_new), axis=1, keepdims=True
        )
        m_all[rs] = m_new

        # Transposed fold into row-block j (strict upper tiles only: the
        # diagonal tile's transpose is itself).
        @pl.when(j > i)
        def _():
            st = s_masked.T
            cs = pl.ds(j * b, b)
            p_all[cs] += jnp.sum(jnp.where(pos_hit, s_raw, 0.0),
                                 axis=0).reshape(b, 1)
            m_old_c = m_all[cs]
            m_new_c = jnp.maximum(
                m_old_c, jnp.max(st, axis=1, keepdims=True))
            l_all[cs] = l_all[cs] * jnp.exp(m_old_c - m_new_c) + jnp.sum(
                _exp0(st - m_new_c), axis=1, keepdims=True
            )
            m_all[cs] = m_new_c

    # Row-block i's stats are complete once the grid finishes its row.
    @pl.when(j == nb - 1)
    def _():
        rs = pl.ds(i * b, b)
        lse = m_all[rs] + _log_l(l_all[rs])
        lse_ref[:] = lse
        rid = jax.lax.broadcasted_iota(jnp.int32, (b, 1), 0) + i * b
        valid = rid < cols_actual
        loss_ref[0, 0] += jnp.sum(jnp.where(valid, lse - p_all[rs], 0.0))


def _fwd_tri_call(zp, *, b, inv_t, cols_actual, n_half, interpret):
    rp, d = zp.shape
    nb = rp // b
    kernel = functools.partial(
        _fwd_tri_kernel, b=b, inv_t=inv_t,
        cols_actual=cols_actual, n_half=n_half, nb=nb,
    )
    loss_sum, lse = pl.pallas_call(
        kernel,
        grid=(nb, nb),
        in_specs=[
            pl.BlockSpec((b, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((b, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((rp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((rp, 1), jnp.float32),
            pltpu.VMEM((rp, 1), jnp.float32),
            pltpu.VMEM((rp, 1), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=rp * rp * d,  # half the rectangular kernel's 2*rp*cp*d
            bytes_accessed=(rp * d + (rp // b) * rp * d) * zp.dtype.itemsize,
            transcendentals=rp * rp,
        ),
        interpret=interpret,
    )(zp, zp)
    return loss_sum[0, 0], lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _bwd_tri_kernel(zr_ref, zc_ref, lse_r_ref, lse_c_ref, grad_ref, acc,
                    *, b, inv_t, cols_actual, n_half, nb):
    """Upper-triangle-only symmetric backward.

    Per strict-upper tile the similarity is recomputed ONCE and drives both
    ``acc[i] += g @ z[j]`` and ``acc[j] += g^T @ z[i]`` (g is symmetric in
    the p/p~ exchange, so the mirrored tile's gradient matrix is exactly
    g^T). Versus the rectangular symmetric backward (one s + one dot per
    full-grid tile) this is 1 s + 2 dots per half-grid tile: 25% less MXU
    work. The full-length fp32 accumulator lives in VMEM scratch — callers
    gate on rp*d*4 fitting the budget (ntxent_loss_fused's default path
    falls back to the rectangular kernel otherwise).
    """
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _():
        acc[:] = jnp.zeros(acc.shape, acc.dtype)

    @pl.when(j >= i)
    def _():
        rid, cid = _tile_ids(i, j, b, b)
        s_masked, _ = _masked_sim_tile(
            zr_ref[:], zc_ref[:], rid, cid, inv_t, cols_actual
        )
        p_row = _exp0(s_masked - lse_r_ref[:])      # exp(s - lse[row])
        p_col = _exp0(s_masked - lse_c_ref[:])      # exp(s - lse[col])
        pos = (cid == _pos_gid(rid, n_half)).astype(jnp.float32)
        valid_row = (rid < cols_actual).astype(jnp.float32)
        valid_col = (cid < cols_actual).astype(jnp.float32)
        g = (p_row - pos) * valid_row + (p_col - pos) * valid_col

        rs = pl.ds(i * b, b)
        acc[rs] += jax.lax.dot_general(
            g, zc_ref[:].astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        @pl.when(j > i)
        def _():
            cs = pl.ds(j * b, b)
            acc[cs] += jax.lax.dot_general(
                g, zr_ref[:].astype(jnp.float32),
                dimension_numbers=(((0,), (0,)), ((), ())),  # g^T @ z_i
                preferred_element_type=jnp.float32,
            )

    # Block i's gradient is complete when its grid row ends (transposed
    # contributions into it came from earlier grid rows).
    @pl.when(j == nb - 1)
    def _():
        grad_ref[:] = acc[pl.ds(i * b, b)]


def _bwd_tri_call(zp, lse, *, b, inv_t, cols_actual, n_half, interpret):
    rp, d = zp.shape
    nb = rp // b
    kernel = functools.partial(
        _bwd_tri_kernel, b=b, inv_t=inv_t,
        cols_actual=cols_actual, n_half=n_half, nb=nb,
    )
    lse_t = lse.reshape(1, rp)
    return pl.pallas_call(
        kernel,
        grid=(nb, nb),
        in_specs=[
            pl.BlockSpec((b, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b, d), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rp, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((rp, d), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=3 * rp * rp * d,  # vs the rectangular sym kernel's 4
            bytes_accessed=(2 * rp * d + rp) * 4,
            transcendentals=rp * rp,
        ),
        interpret=interpret,
    )(zp, zp, lse, lse_t)


def _tri_bwd_fits(rp: int, d: int, b: int) -> bool:
    """Does the triangular backward's working set (full-length fp32
    accumulator + two z blocks + output block) fit the VMEM budget?"""
    from .blocks import VMEM_BUDGET_BYTES

    working = rp * d * 4 + 3 * b * d * 4 + b * b * 4
    return working <= VMEM_BUDGET_BYTES


def _bwd_sym_kernel(z_row_ref, z_col_ref, gid_ref, scale_ref, lse_r_ref,
                    lse_c_ref, grad_ref, *, br, bc, inv_t, cols_actual,
                    n_half, diag_pos=False):
    """Symmetric-case backward: both row and column gradient terms per tile.

    ``lse_c_ref`` is the same logsumexp vector pre-transposed to (1, Rp) so
    the column-side broadcast needs no in-kernel transpose.
    """
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        grad_ref[:] = jnp.zeros(grad_ref.shape, grad_ref.dtype)

    row_gid = gid_ref[:]
    _, cid = _tile_ids(i, j, br, bc)
    s_masked, _ = _masked_sim_tile(
        z_row_ref[:], z_col_ref[:], row_gid, cid, inv_t * scale_ref[0, 0],
        cols_actual, diag_pos
    )
    p_row = _exp0(s_masked - lse_r_ref[:])          # exp(s - lse[row])
    p_col = _exp0(s_masked - lse_c_ref[:])          # exp(s - lse[col]), (1, BC)
    pos = (cid == _pos_gid(row_gid, n_half, diag_pos)).astype(jnp.float32)
    valid_row = (row_gid < cols_actual).astype(jnp.float32)
    valid_col = (cid < cols_actual).astype(jnp.float32)
    g = (p_row - pos) * valid_row + (p_col - pos) * valid_col
    grad_ref[:] += jax.lax.dot_general(
        g, z_col_ref[:].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _bwd_sym_cols_kernel(z_row_ref, z_col_ref, gid_ref, scale_ref,
                         lse_r_ref, lse_c_ref, grad_ref,
                         *, br, bc, inv_t, cols_actual, n_half,
                         diag_pos=False):
    """Column-side twin of ``_bwd_sym_kernel``: the same combined
    ``G = (P_row - pos)·vr + (P_col - pos)·vc`` tile, but the output is
    ``G^T @ z_rows`` accumulated per COLUMN block — the partial gradient
    of the gathered column operand (what flows back through all_gather as
    a reduce-scatter in the distributed dual-InfoNCE path). Grid is
    (col_block, row_block), rows innermost.
    """
    j = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        grad_ref[:] = jnp.zeros(grad_ref.shape, grad_ref.dtype)

    row_gid = gid_ref[:]
    _, cid = _tile_ids(i, j, br, bc)
    s_masked, _ = _masked_sim_tile(
        z_row_ref[:], z_col_ref[:], row_gid, cid, inv_t * scale_ref[0, 0],
        cols_actual, diag_pos
    )
    p_row = _exp0(s_masked - lse_r_ref[:])
    p_col = _exp0(s_masked - lse_c_ref[:])
    pos = (cid == _pos_gid(row_gid, n_half, diag_pos)).astype(jnp.float32)
    valid_row = (row_gid < cols_actual).astype(jnp.float32)
    valid_col = (cid < cols_actual).astype(jnp.float32)
    g = (p_row - pos) * valid_row + (p_col - pos) * valid_col
    grad_ref[:] += jax.lax.dot_general(
        g, z_row_ref[:].astype(jnp.float32),
        dimension_numbers=(((0,), (0,)), ((), ())),   # (BC, D)
        preferred_element_type=jnp.float32,
    )


def _bwd_sym_cols_call(z_rows, z_cols, row_gid, lse_rows, lse_cols, *,
                       br, bc, inv_t, cols_actual, n_half, interpret,
                       diag_pos=False, scale=None):
    """(Cp, D) partial gradient of the column operand under the combined-G
    identity — pairs with ``_bwd_sym_call`` (which produces the row side).
    ``lse_cols`` must already be the GLOBAL column logsumexp."""
    rp, d = z_rows.shape
    cp = z_cols.shape[0]
    kernel = functools.partial(
        _bwd_sym_cols_kernel, br=br, bc=bc, inv_t=inv_t,
        cols_actual=cols_actual, n_half=n_half, diag_pos=diag_pos,
    )
    return pl.pallas_call(
        kernel,
        grid=(cp // bc, rp // br),
        in_specs=[
            pl.BlockSpec((br, d), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, d), lambda j, i: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda j, i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((br, 1), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bc), lambda j, i: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bc, d), lambda j, i: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((cp, d), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=4 * rp * cp * d,
            bytes_accessed=(rp + cp) * d * 4,
            transcendentals=2 * rp * cp,
        ),
        interpret=interpret,
    )(z_rows, z_cols, row_gid, _scale_arr(scale), lse_rows,
      lse_cols.reshape(1, cp))


def _bwd_rows_kernel(z_row_ref, z_col_ref, gid_ref, cgid_ref, scale_ref,
                     lse_r_ref, grad_ref,
                     *, br, bc, inv_t, cols_actual, n_half, diag_pos=False):
    """General case: d(loss_sum)/d(z_rows) = (P - E) @ z_cols."""
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        grad_ref[:] = jnp.zeros(grad_ref.shape, grad_ref.dtype)

    row_gid = gid_ref[:]
    cid = cgid_ref[:]
    s_masked, _ = _masked_sim_tile(
        z_row_ref[:], z_col_ref[:], row_gid, cid, inv_t * scale_ref[0, 0],
        cols_actual, diag_pos
    )
    p = _exp0(s_masked - lse_r_ref[:])
    pos = (cid == _pos_gid(row_gid, n_half, diag_pos)).astype(jnp.float32)
    valid_row = (row_gid < cols_actual).astype(jnp.float32)
    g = (p - pos) * valid_row
    grad_ref[:] += jax.lax.dot_general(
        g, z_col_ref[:].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _bwd_cols_kernel(z_row_ref, z_col_ref, gid_ref, cgid_ref, scale_ref,
                     lse_r_ref, grad_ref,
                     *, br, bc, inv_t, cols_actual, n_half, diag_pos=False):
    """General case: d(loss_sum)/d(z_cols) = (P - E)^T @ z_rows.

    Grid is (col_block, row_block) with rows innermost so each output column
    block accumulates over all row blocks in consecutive grid steps.
    """
    j = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        grad_ref[:] = jnp.zeros(grad_ref.shape, grad_ref.dtype)

    row_gid = gid_ref[:]
    cid = cgid_ref[:]
    s_masked, _ = _masked_sim_tile(
        z_row_ref[:], z_col_ref[:], row_gid, cid, inv_t * scale_ref[0, 0],
        cols_actual, diag_pos
    )
    p = _exp0(s_masked - lse_r_ref[:])
    pos = (cid == _pos_gid(row_gid, n_half, diag_pos)).astype(jnp.float32)
    valid_row = (row_gid < cols_actual).astype(jnp.float32)
    g = (p - pos) * valid_row                         # (BR, BC)
    grad_ref[:] += jax.lax.dot_general(
        g, z_row_ref[:].astype(jnp.float32),
        dimension_numbers=(((0,), (0,)), ((), ())),   # (BC, D)
        preferred_element_type=jnp.float32,
    )


def _bwd_sym_call(z, row_gid, lse, *, br, bc, inv_t, cols_actual, n_half,
                  interpret, diag_pos=False, z_cols=None, lse_cols=None,
                  scale=None):
    rp, d = z.shape
    kernel = functools.partial(
        _bwd_sym_kernel, br=br, bc=bc, inv_t=inv_t,
        cols_actual=cols_actual, n_half=n_half, diag_pos=diag_pos,
    )
    zc = z if z_cols is None else z_cols
    cp = zc.shape[0]
    grid = (rp // br, cp // bc)
    # column-side broadcast layout; defaults to the row-side lse (symmetric)
    lse_t = (lse if lse_cols is None else lse_cols).reshape(1, cp)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bc), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((br, d), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rp, d), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=4 * rp * cp * d,
            bytes_accessed=(rp + cp) * d * 4,
            transcendentals=2 * rp * cp,
        ),
        interpret=interpret,
    )(z, zc, row_gid, _scale_arr(scale), lse, lse_t)


def _bwd_general_call(z_rows, z_cols, row_gid, lse, *, br, bc, inv_t,
                      cols_actual, n_half, interpret, diag_pos=False,
                      scale=None, col_gid=None):
    rp, d = z_rows.shape
    cp = z_cols.shape[0]
    cg = _col_gid_row(cp, col_gid)
    row_kernel = functools.partial(
        _bwd_rows_kernel, br=br, bc=bc, inv_t=inv_t,
        cols_actual=cols_actual, n_half=n_half, diag_pos=diag_pos,
    )
    grad_rows = pl.pallas_call(
        row_kernel,
        grid=(rp // br, cp // bc),
        in_specs=[
            pl.BlockSpec((br, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bc), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((br, d), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rp, d), jnp.float32),
        interpret=interpret,
    )(z_rows, z_cols, row_gid, cg, _scale_arr(scale), lse)

    col_kernel = functools.partial(
        _bwd_cols_kernel, br=br, bc=bc, inv_t=inv_t,
        cols_actual=cols_actual, n_half=n_half, diag_pos=diag_pos,
    )
    grad_cols = pl.pallas_call(
        col_kernel,
        grid=(cp // bc, rp // br),
        in_specs=[
            pl.BlockSpec((br, d), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, d), lambda j, i: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bc), lambda j, i: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda j, i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((br, 1), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bc, d), lambda j, i: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((cp, d), jnp.float32),
        interpret=interpret,
    )(z_rows, z_cols, row_gid, cg, _scale_arr(scale), lse)
    return grad_rows, grad_cols


# ---------------------------------------------------------------------------
# Padding helpers
# ---------------------------------------------------------------------------


def _pad_rows(x: jax.Array, multiple: int) -> jax.Array:
    r = x.shape[0]
    rp = round_up(r, multiple)
    if rp == r:
        return x
    return jnp.pad(x, ((0, rp - r),) + ((0, 0),) * (x.ndim - 1))


def _gid_column(row_gid: jax.Array, multiple: int, sentinel: int) -> jax.Array:
    """Pad a 1-D global-row-id vector and shape it (Rp, 1) for the kernel."""
    r = row_gid.shape[0]
    rp = round_up(r, multiple)
    padded = jnp.full((rp, 1), sentinel, jnp.int32)
    return padded.at[:r, 0].set(row_gid.astype(jnp.int32))


# ---------------------------------------------------------------------------
# Public API: symmetric (single-array) fused loss
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _ntxent_sym(z, temperature, br, bc, interpret, triangular=False):
    return _ntxent_sym_fwd(z, temperature, br, bc, interpret, triangular)[0]


def _ntxent_sym_fwd(z, temperature, br, bc, interpret, triangular=False):
    two_n, _ = z.shape
    if triangular and br == bc:
        zp = _pad_rows(z, br)
        loss_sum, lse = _fwd_tri_call(
            zp, b=br, inv_t=1.0 / temperature,
            cols_actual=two_n, n_half=two_n // 2, interpret=interpret,
        )
        return loss_sum, (z, lse)
    pad = math.lcm(br, bc)  # one padded array serves as both rows and columns
    zp = _pad_rows(z, pad)
    gid = _gid_column(jnp.arange(zp.shape[0]), pad, sentinel=two_n)
    loss_sum, lse = _fwd_call(
        zp, zp, gid,
        br=br, bc=bc, inv_t=1.0 / temperature,
        cols_actual=two_n, n_half=two_n // 2, interpret=interpret,
    )
    return loss_sum, (z, lse)


def _ntxent_sym_bwd(temperature, br, bc, interpret, triangular, res, g):
    z, lse = res
    two_n, d = z.shape
    if triangular and br == bc \
            and _tri_bwd_fits(round_up(two_n, br), d, br):
        zp = _pad_rows(z, br)
        grad = _bwd_tri_call(
            zp, lse,
            b=br, inv_t=1.0 / temperature,
            cols_actual=two_n, n_half=two_n // 2, interpret=interpret,
        )
        grad = grad[:two_n] * (g / temperature)
        return (grad.astype(z.dtype),)
    pad = math.lcm(br, bc)
    zp = _pad_rows(z, pad)
    gid = _gid_column(jnp.arange(zp.shape[0]), pad, sentinel=two_n)
    grad = _bwd_sym_call(
        zp, gid, lse,
        br=br, bc=bc, inv_t=1.0 / temperature,
        cols_actual=two_n, n_half=two_n // 2, interpret=interpret,
    )
    grad = grad[:two_n] * (g / temperature)
    return (grad.astype(z.dtype),)


_ntxent_sym.defvjp(_ntxent_sym_fwd, _ntxent_sym_bwd)


def ntxent_loss_fused(
    z: jax.Array,
    temperature: float = 0.07,
    *,
    block_rows: int | None = None,
    block_cols: int | None = None,
    interpret: bool | None = None,
    triangular: bool = False,
) -> jax.Array:
    """Fused canonical NT-Xent mean loss over stacked views z: (2N, D).

    Drop-in fused equivalent of ``ops.oracle.ntxent_loss`` — same semantics,
    O(N) memory, exact gradients via custom VJP. ``temperature`` must be a
    static Python float (it is baked into the kernel).

    ``triangular=True`` switches the forward to the upper-triangle kernel
    (each similarity tile computed once, folded into both row blocks —
    half the forward MXU work; requires square blocks, which are forced
    when the flag is set). Numerics differ from the rectangular kernel
    only by online-softmax fold order.
    """
    two_n = z.shape[0]
    if two_n % 2 != 0:
        raise ValueError(f"NT-Xent needs an even number of rows, got {two_n}")
    br, bc = choose_blocks(two_n, two_n, z.shape[1], z.dtype,
                           block_rows, block_cols)
    if triangular:
        br = bc = min(br, bc)
    if interpret is None:
        interpret = _default_interpret()
    loss_sum = _ntxent_sym(z, float(temperature), br, bc, interpret,
                           triangular)
    return loss_sum / two_n


# ---------------------------------------------------------------------------
# Public API: general (rows x cols) partial loss for the distributed path
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _ntxent_partial(z_rows, z_cols, row_gid, lscale, temperature, br, bc,
                    interpret, diag_pos=False):
    """Partial loss sum with a traced logit scale (effective 1/T = lscale/T).

    ``lscale`` is differentiable (CLIP's learnable ``exp(logit_scale)``);
    the NT-Xent path passes a constant 1."""
    return _ntxent_partial_fwd(z_rows, z_cols, row_gid, lscale, temperature,
                               br, bc, interpret, diag_pos)[0]


def _ntxent_partial_prepare(z_rows, z_cols, row_gid, br, bc):
    two_n = z_cols.shape[0]
    zr = _pad_rows(z_rows, br)
    zc = _pad_rows(z_cols, bc)
    gid = _gid_column(row_gid, br, sentinel=two_n)
    return zr, zc, gid, two_n


def _ntxent_partial_fwd(z_rows, z_cols, row_gid, lscale, temperature, br, bc,
                        interpret, diag_pos=False):
    zr, zc, gid, two_n = _ntxent_partial_prepare(z_rows, z_cols, row_gid, br, bc)
    loss_sum, lse = _fwd_call(
        zr, zc, gid,
        br=br, bc=bc, inv_t=1.0 / temperature,
        cols_actual=two_n, n_half=two_n // 2, interpret=interpret,
        diag_pos=diag_pos, scale=lscale,
    )
    return loss_sum, (z_rows, z_cols, row_gid, lscale, lse)


def _ntxent_partial_bwd(temperature, br, bc, interpret, diag_pos, res, g):
    z_rows, z_cols, row_gid, lscale, lse = res
    zr, zc, gid, two_n = _ntxent_partial_prepare(z_rows, z_cols, row_gid, br, bc)
    gr, gc = _bwd_general_call(
        zr, zc, gid, lse,
        br=br, bc=bc, inv_t=1.0 / temperature,
        cols_actual=two_n, n_half=two_n // 2, interpret=interpret,
        diag_pos=diag_pos, scale=lscale,
    )
    gr = gr[: z_rows.shape[0]]
    coef = g / temperature
    grad_rows = (gr * (coef * lscale)).astype(z_rows.dtype)
    grad_cols = (gc[: z_cols.shape[0]] * (coef * lscale)).astype(z_cols.dtype)
    # d loss_sum/d lscale = (1/T) sum_ij G_ij (zr_i . zc_j)
    #                     = (1/T) sum_i (G @ zc)_i . zr_i  — gr IS G @ zc.
    grad_lscale = (coef * jnp.sum(gr * z_rows.astype(jnp.float32))).reshape(
        jnp.shape(lscale)).astype(lscale.dtype)
    return grad_rows, grad_cols, None, grad_lscale


_ntxent_partial.defvjp(_ntxent_partial_fwd, _ntxent_partial_bwd)


def ntxent_partial_fused(
    z_rows: jax.Array,
    z_cols: jax.Array,
    row_gid: jax.Array,
    temperature: float = 0.07,
    *,
    block_rows: int | None = None,
    block_cols: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Partial NT-Xent loss **sum** over a set of rows of the global matrix.

    z_rows: (R, D) local embeddings (this shard's rows of the similarity
        matrix); z_cols: (2N, D) global (gathered) embeddings; row_gid: (R,)
        global index of each local row in the [0, 2N) stacked-view order.
    Returns sum_i (logsumexp_j s_ij - s_i,pos(i)) over the local rows —
    divide by 2N (after psum across shards) for the global mean loss.
    Differentiable w.r.t. both z_rows and z_cols (the z_cols gradient is what
    flows back through ``lax.all_gather`` as a reduce-scatter).
    """
    if z_cols.shape[0] % 2 != 0:
        raise ValueError(
            f"NT-Xent needs an even global row count, got {z_cols.shape[0]}"
        )
    br, bc = choose_blocks(z_rows.shape[0], z_cols.shape[0], z_rows.shape[1],
                           z_rows.dtype, block_rows, block_cols)
    if interpret is None:
        interpret = _default_interpret()
    return _ntxent_partial(z_rows, z_cols, row_gid.astype(jnp.int32),
                           jnp.float32(1.0), float(temperature), br, bc,
                           interpret)


def ntxent_loss_and_lse(
    z: jax.Array,
    temperature: float = 0.07,
    *,
    block_rows: int | None = None,
    block_cols: int | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Mean loss plus per-row logsumexp residuals (no VJP wiring).

    The O(N) analog of the reference's intended "(loss, softmax) residual"
    contract (SURVEY.md §2.3-D9): from lse the full masked softmax row i is
    ``exp(s_i - lse_i)`` — materialize it lazily instead of storing (2N)^2.
    """
    two_n = z.shape[0]
    if two_n % 2 != 0:
        raise ValueError(f"NT-Xent needs an even number of rows, got {two_n}")
    br, bc = choose_blocks(two_n, two_n, z.shape[1], z.dtype,
                           block_rows, block_cols)
    if interpret is None:
        interpret = _default_interpret()
    pad = math.lcm(br, bc)
    zp = _pad_rows(z, pad)
    gid = _gid_column(jnp.arange(zp.shape[0]), pad, sentinel=two_n)
    loss_sum, lse = _fwd_call(
        zp, zp, gid,
        br=br, bc=bc, inv_t=1.0 / float(temperature),
        cols_actual=two_n, n_half=two_n // 2, interpret=interpret,
    )
    return loss_sum / two_n, lse[:two_n, 0]


# ---------------------------------------------------------------------------
# Mid-level block primitives for the ring (context-parallel) loss
# ---------------------------------------------------------------------------


def block_lse(
    z_rows: jax.Array,
    z_cols: jax.Array,
    row_gid: jax.Array,
    col_gid: jax.Array,
    temperature: float,
    total_cols: int,
    *,
    block_rows: int | None = None,
    block_cols: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Per-row logsumexp over ONE column block of the global similarity
    matrix, self-columns masked — the fused fold step of the ring NT-Xent
    (parallel/ring.py), where the visiting block's columns carry arbitrary
    global ids (``col_gid``).

    Not wired for AD (the ring's custom VJP calls block_grads explicitly).
    Positive-pair extraction is disabled by pointing ``n_half`` past every
    real column id; the ring handles positives locally.
    """
    rows, d = z_rows.shape
    cols = z_cols.shape[0]
    br, bc = choose_blocks(rows, cols, d, z_rows.dtype,
                           block_rows, block_cols)
    if interpret is None:
        interpret = _default_interpret()
    zr = _pad_rows(z_rows, br)
    zc = _pad_rows(z_cols, bc)
    gid = _gid_column(row_gid, br, sentinel=total_cols)
    cg = _pad_gid_row(col_gid, bc, sentinel=total_cols)
    _, lse = _fwd_call(
        zr, zc, gid,
        br=br, bc=bc, inv_t=1.0 / float(temperature),
        cols_actual=total_cols, n_half=total_cols, interpret=interpret,
        col_gid=cg,
    )
    return lse[:rows, 0]


def block_grads(
    z_rows: jax.Array,
    z_cols: jax.Array,
    row_gid: jax.Array,
    col_gid: jax.Array,
    lse_rows: jax.Array,
    temperature: float,
    total_cols: int,
    *,
    block_rows: int | None = None,
    block_cols: int | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Gradients of ``S = sum_r lse_r`` restricted to this column block:
    ``(dS/dz_rows, dS/dz_cols) * temperature`` — i.e. the raw softmax-prob
    matmuls ``P @ z_cols`` and ``P^T @ z_rows``; the caller multiplies by
    ``cotangent / temperature`` once (matching _ntxent_partial_bwd).

    The backward fold of the fused ring: per hop, dS/dz_rows accumulates
    locally and dS/dz_cols circulates home with the visiting block.
    """
    rows, d = z_rows.shape
    cols = z_cols.shape[0]
    br, bc = choose_blocks(rows, cols, d, z_rows.dtype,
                           block_rows, block_cols)
    if interpret is None:
        interpret = _default_interpret()
    zr = _pad_rows(z_rows, br)
    zc = _pad_rows(z_cols, bc)
    gid = _gid_column(row_gid, br, sentinel=total_cols)
    cg = _pad_gid_row(col_gid, bc, sentinel=total_cols)
    # Padded rows carry sentinel gids (valid_row = 0 in-kernel); pad their
    # lse with zeros so exp(s - lse) stays finite before masking.
    lse_p = jnp.zeros((zr.shape[0], 1), jnp.float32
                      ).at[:rows, 0].set(lse_rows)
    gr, gc = _bwd_general_call(
        zr, zc, gid, lse_p,
        br=br, bc=bc, inv_t=1.0 / float(temperature),
        cols_actual=total_cols, n_half=total_cols, interpret=interpret,
        col_gid=cg,
    )
    return gr[:rows], gc[:cols]


def _pad_gid_row(col_gid: jax.Array, multiple: int, sentinel: int):
    """Pad a 1-D global-col-id vector to a block multiple with sentinel ids
    (>= total_cols, so padded columns are masked in-kernel). Same padding
    core as the row side — only the shape differs."""
    return _gid_column(col_gid, multiple, sentinel)[:, 0]


# ---------------------------------------------------------------------------
# Dual-direction block primitives for the pair-parallel (symmetric) loss
# ---------------------------------------------------------------------------


def _dual_stats_kernel(zr_ref, zc_ref, rgid_ref, cgid_ref, lse_r_ref,
                       lse_c_ref, m_r, l_r, m_c, l_c,
                       *, br, bc, inv_t, total):
    """NT-Xent dual stats over ONE shard-pair tile of the symmetric global
    matrix: each s tile is produced once and folded into the ROW side's
    online softmax directly and the COLUMN side's transposed (the global
    matrix is symmetric, so the tile's transpose is the mirror tile the
    pair-parallel schedule never computes). Both sides carry explicit
    global ids (sentinel >= total on padding); self-similarity
    (cid == rid) and padding are masked per direction.
    """
    i = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _():
        m_r[:] = jnp.full(m_r.shape, _NEG_INF, jnp.float32)
        l_r[:] = jnp.zeros(l_r.shape, jnp.float32)
        m_c[:] = jnp.full(m_c.shape, _NEG_INF, jnp.float32)
        l_c[:] = jnp.zeros(l_c.shape, jnp.float32)

    rid = rgid_ref[:]                       # (BR, 1) global row ids
    cid = cgid_ref[:]                       # (1, BC) global col ids
    s = jax.lax.dot_general(
        zr_ref[:], zc_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * inv_t
    self_hit = cid == rid
    s_row = jnp.where(jnp.logical_or(cid >= total, self_hit), _NEG_INF, s)
    s_col = jnp.where(jnp.logical_or(rid >= total, self_hit), _NEG_INF, s)

    rs = pl.ds(i * br, br)
    m_old = m_r[rs]
    m_new = jnp.maximum(m_old, jnp.max(s_row, axis=1, keepdims=True))
    l_r[rs] = l_r[rs] * jnp.exp(m_old - m_new) + jnp.sum(
        _exp0(s_row - m_new), axis=1, keepdims=True)
    m_r[rs] = m_new

    cs = pl.ds(j * bc, bc)
    st = s_col.T
    m_old_c = m_c[cs]
    m_new_c = jnp.maximum(m_old_c, jnp.max(st, axis=1, keepdims=True))
    l_c[cs] = l_c[cs] * jnp.exp(m_old_c - m_new_c) + jnp.sum(
        _exp0(st - m_new_c), axis=1, keepdims=True)
    m_c[cs] = m_new_c

    @pl.when(j == nj - 1)
    def _():
        lse_r_ref[:] = m_r[rs] + _log_l(l_r[rs])

    # The (j, 0) window is revisited every grid row; its final visit (last
    # grid row) publishes complete column-side stats.
    lse_c_ref[:] = m_c[cs] + _log_l(l_c[cs])


def block_lse_dual(
    z_rows: jax.Array,
    z_cols: jax.Array,
    row_gid: jax.Array,
    col_gid: jax.Array,
    temperature: float,
    total: int,
    *,
    block_rows: int | None = None,
    block_cols: int | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(lse_rows, lse_cols) of ONE shard-pair tile from a single walk.

    lse_rows[a] = logsumexp over this tile's columns for row a;
    lse_cols[b] = logsumexp over this tile's ROWS for column b (the
    symmetric mirror tile's row direction). Fold results across a
    device's assigned tiles with logaddexp; weight a tile by adding
    log(w) to both outputs. Not AD-wired — the pair-parallel loss's
    custom VJP calls block_grads_dual explicitly.
    """
    rows, d = z_rows.shape
    cols = z_cols.shape[0]
    br, bc = choose_blocks(rows, cols, d, z_rows.dtype,
                           block_rows, block_cols)
    if interpret is None:
        interpret = _default_interpret()
    zr = _pad_rows(z_rows, br)
    zc = _pad_rows(z_cols, bc)
    gid_r = _gid_column(row_gid, br, sentinel=total)
    gid_c = _pad_gid_row(col_gid, bc, total).reshape(1, -1)
    rp, cp = zr.shape[0], zc.shape[0]
    kernel = functools.partial(
        _dual_stats_kernel, br=br, bc=bc,
        inv_t=1.0 / float(temperature), total=total,
    )
    lse_r, lse_c = pl.pallas_call(
        kernel,
        grid=(rp // br, cp // bc),
        in_specs=[
            pl.BlockSpec((br, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bc), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, 1), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, 1), jnp.float32),
            jax.ShapeDtypeStruct((cp, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((rp, 1), jnp.float32)] * 2
        + [pltpu.VMEM((cp, 1), jnp.float32)] * 2,
        cost_estimate=pl.CostEstimate(
            flops=2 * rp * cp * d,
            bytes_accessed=(rp + cp) * d * 4,
            transcendentals=2 * rp * cp,
        ),
        interpret=interpret,
    )(zr, zc, gid_r, gid_c)
    return lse_r[:rows, 0], lse_c[:cols, 0]


def _dual_grads_kernel(zr_ref, zc_ref, rgid_ref, cgid_ref, lse_r_ref,
                       lse_c_ref, gr_ref, gc_ref, acc_c,
                       *, br, bc, inv_t, total):
    """Shared-G gradients of the pair-parallel lse sum over one tile:
    ``G = exp(s - lse_row) + exp(s - lse_col)`` (self/padding masked, no
    positive term — positives are handled locally by the caller), with
    ``gr += G @ z_cols`` per row block and ``gc += G^T @ z_rows``
    accumulated in full-length scratch (shard-sized, so it always fits).
    One s recompute + two dots per tile — the mirror tile is never walked.
    """
    i = pl.program_id(0)
    j = pl.program_id(1)
    ni = pl.num_programs(0)

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _():
        acc_c[:] = jnp.zeros(acc_c.shape, acc_c.dtype)

    @pl.when(j == 0)
    def _():
        gr_ref[:] = jnp.zeros(gr_ref.shape, gr_ref.dtype)

    rid = rgid_ref[:]
    cid = cgid_ref[:]
    s = jax.lax.dot_general(
        zr_ref[:], zc_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * inv_t
    self_hit = cid == rid
    s_row = jnp.where(jnp.logical_or(cid >= total, self_hit), _NEG_INF, s)
    s_col = jnp.where(jnp.logical_or(rid >= total, self_hit), _NEG_INF, s)
    valid_row = (rid < total).astype(jnp.float32)
    valid_col = (cid < total).astype(jnp.float32)
    g = _exp0(s_row - lse_r_ref[:]) * valid_row \
        + _exp0(s_col - lse_c_ref[:]) * valid_col

    gr_ref[:] += jax.lax.dot_general(
        g, zc_ref[:].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    cs = pl.ds(j * bc, bc)
    acc_c[cs] += jax.lax.dot_general(
        g, zr_ref[:].astype(jnp.float32),
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == ni - 1)
    def _():
        gc_ref[:] = acc_c[cs]


def block_grads_dual(
    z_rows: jax.Array,
    z_cols: jax.Array,
    row_gid: jax.Array,
    col_gid: jax.Array,
    lse_rows: jax.Array,
    lse_cols: jax.Array,
    temperature: float,
    total: int,
    *,
    block_rows: int | None = None,
    block_cols: int | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Both sides' gradient contributions of one pair tile, times T.

    With ``S = sum_rows (lse - pos)`` over the GLOBAL matrix and the
    tile's rows/cols carrying global lse values, returns
    ``(dS/dz_rows, dS/dz_cols) * temperature`` restricted to this tile's
    softmax terms (no positive term); the caller multiplies by
    ``cotangent / temperature`` once and adds the local positive
    gradient. Self/padding masking matches block_lse_dual.
    """
    rows, d = z_rows.shape
    cols = z_cols.shape[0]
    br, bc = choose_blocks(rows, cols, d, z_rows.dtype,
                           block_rows, block_cols)
    if interpret is None:
        interpret = _default_interpret()
    zr = _pad_rows(z_rows, br)
    zc = _pad_rows(z_cols, bc)
    gid_r = _gid_column(row_gid, br, sentinel=total)
    gid_c = _pad_gid_row(col_gid, bc, total).reshape(1, -1)
    lse_rp = _pad_rows(lse_rows.reshape(rows, 1), br)
    lse_cp = _pad_rows(lse_cols.reshape(cols, 1), bc).reshape(1, -1)
    rp, cp = zr.shape[0], zc.shape[0]
    kernel = functools.partial(
        _dual_grads_kernel, br=br, bc=bc,
        inv_t=1.0 / float(temperature), total=total,
    )
    gr, gc = pl.pallas_call(
        kernel,
        grid=(rp // br, cp // bc),
        in_specs=[
            pl.BlockSpec((br, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bc), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bc), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, d), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, d), jnp.float32),
            jax.ShapeDtypeStruct((cp, d), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((cp, d), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=6 * rp * cp * d,
            bytes_accessed=(2 * rp + 2 * cp) * d * 4,
            transcendentals=2 * rp * cp,
        ),
        interpret=interpret,
    )(zr, zc, gid_r, gid_c, lse_rp, lse_cp)
    return gr[:rows], gc[:cols]
