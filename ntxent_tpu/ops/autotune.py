"""Measurement-based block autotuning for the fused loss kernels.

The static heuristic (blocks.choose_blocks) picks safe VMEM-fitting tiles;
this module refines it the way the hardware actually votes: time a small
candidate grid of (block_rows, block_cols) on the live device and cache the
winner per (rows, cols, dim, dtype, backend). The role the reference gave
``get_optimal_block_size`` (/root/reference/include/ntxent_kernel.cuh:80-96)
— a static occupancy formula — done by measurement, which is the only thing
that survives hardware generations.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

from ..utils.profiling import time_fn
from .blocks import VMEM_BUDGET_BYTES, _working_set_bytes, round_up

logger = logging.getLogger(__name__)

__all__ = ["autotune_blocks", "clear_cache"]

_CACHE: dict[tuple, tuple[int, int]] = {}

_ROW_CANDIDATES = (64, 128, 256, 512)
_COL_CANDIDATES = (128, 256, 512, 1024)


def clear_cache() -> None:
    _CACHE.clear()


def _candidates(rows: int, cols: int, dim: int, itemsize: int):
    for br in _ROW_CANDIDATES:
        if br > round_up(rows, 8):
            continue
        for bc in _COL_CANDIDATES:
            if bc > round_up(cols, 128):
                continue
            if _working_set_bytes(br, bc, dim, itemsize) > VMEM_BUDGET_BYTES:
                continue
            yield br, bc


def autotune_blocks(
    rows: int,
    cols: int,
    dim: int,
    dtype=jnp.float32,
    *,
    include_backward: bool = True,
    warmup: int = 2,
    runs: int = 5,
) -> tuple[int, int]:
    """Time the candidate grid on the live device; return the fastest tile.

    Results are cached per shape/dtype/backend for the process lifetime.
    Falls back to the static heuristic when nothing can be measured (e.g.
    interpret mode on CPU, where timing votes are meaningless anyway).
    """
    from .blocks import choose_blocks
    from .ntxent_pallas import ntxent_loss_fused

    key = (rows, cols, dim, jnp.dtype(dtype).str, jax.default_backend())
    if key in _CACHE:
        return _CACHE[key]
    if jax.default_backend() not in ("tpu", "axon"):
        return choose_blocks(rows, cols, dim, dtype)

    z = jax.random.normal(jax.random.PRNGKey(0), (rows, dim), jnp.float32)
    z = (z / jnp.linalg.norm(z, axis=-1, keepdims=True)).astype(dtype)

    best, best_ms = None, float("inf")
    for br, bc in _candidates(rows, cols, dim, jnp.dtype(dtype).itemsize):
        def loss(zz, _br=br, _bc=bc):
            return ntxent_loss_fused(zz, 0.07, block_rows=_br, block_cols=_bc)

        fn = jax.jit(jax.value_and_grad(loss)) if include_backward \
            else jax.jit(loss)
        try:
            r = time_fn(fn, z, warmup=warmup, runs=runs)
        except Exception as e:  # candidate failed to compile/fit: skip it
            logger.debug("autotune candidate (%d, %d) failed: %s", br, bc, e)
            continue
        logger.info("autotune (%d, %d): %.4f ms", br, bc, r.mean_ms)
        if r.mean_ms < best_ms:
            best, best_ms = (br, bc), r.mean_ms
    if best is None:
        best = choose_blocks(rows, cols, dim, dtype)
    _CACHE[key] = best
    return best
