"""Measurement-based block autotuning for the fused Pallas kernels
(NT-Xent/InfoNCE loss tiles and flash-attention tiles).

The static heuristic (blocks.choose_blocks) picks safe VMEM-fitting tiles;
this module refines it the way the hardware actually votes: time a small
candidate grid of (block_rows, block_cols) on the live device and cache the
winner per (rows, cols, dim, dtype, backend, device_kind). The role the
reference gave ``get_optimal_block_size``
(/root/reference/include/ntxent_kernel.cuh:80-96) — a static occupancy
formula — done by measurement, which is the only thing that survives
hardware generations.

Two guarantees for unattended callers (bench.py runs this on the critical
path of the headline benchmark):

* **Wall-time bound**: ``budget_s`` caps the whole sweep; when it runs out
  the best tile measured so far wins (or the heuristic if none finished).
* **Persistent cache**: winners are stored in a JSON file keyed by device
  kind (``NTXENT_TPU_CACHE`` dir, default ``~/.cache/ntxent_tpu``), so a
  tile tuned once on a given TPU generation is reused across processes.
"""

from __future__ import annotations

import json
import logging
import os
import time
from pathlib import Path

import jax
import jax.numpy as jnp

from ..utils.profiling import time_fn_chained
from .blocks import VMEM_BUDGET_BYTES, _working_set_bytes, round_up

logger = logging.getLogger(__name__)

__all__ = ["autotune_blocks", "autotune_attention_blocks",
           "choose_ring_chunks", "resolve_ring_chunks",
           "autotune_ring_chunks", "clear_cache", "cache_path"]

_CACHE: dict[tuple, tuple[int, int]] = {}
# Values: [br, bc] = served full-sweep vote; the "...|partial" twin key
# holds a truncated sweep's progress record (dict) — see _disk_lookup.
_DISK_CACHE: dict[str, list[int] | dict] | None = None

# Bumped whenever cached votes stop being comparable — a timing-protocol
# change OR a candidate-grid change (old votes were best-of-a-smaller-
# grid). History, newest first:
# v4 = candidate grid extended to 1024-row / 2048-col tiles: the round-5
# headline vote landed exactly on the old (512, 1024) corner, the classic
# sign the optimum may lie outside the sweep; the VMEM working-set filter
# still prunes illegal corners (e.g. 1024x2048 at D=128 is 10.5 MB > the
# 8 MB budget), so the grid only grows where it can actually run.
# Measured: (256, 2048) wins the 4096x128 headline, 0.151 vs 0.161 ms.
# v3 = span-amortized votes (v2 chains were too short at fast shapes —
# ~64 ms of fixed tunnel dispatch on a 50x1.7 ms span made sub-ms votes
# noise; measured consequence: a pinned 1024-causal attention tile 2.4x
# slower than the heuristic, benchmark_results/tpu/attention_ab.json).
# v2 = scanned-chain votes (v1 per-iteration votes are relay-distorted
# and must not be reused).
_PROTOCOL_VERSION = 4

_ROW_CANDIDATES = (64, 128, 256, 512, 1024)
_COL_CANDIDATES = (128, 256, 512, 1024, 2048)


def cache_path() -> Path:
    root = Path(os.environ.get("NTXENT_TPU_CACHE",
                               Path.home() / ".cache" / "ntxent_tpu"))
    return root / "autotune.json"


def clear_cache(disk: bool = False) -> None:
    global _DISK_CACHE
    _CACHE.clear()
    _DISK_CACHE = None
    if disk:
        cache_path().unlink(missing_ok=True)


def _device_kind() -> str:
    try:
        return jax.local_devices()[0].device_kind
    except Exception:
        return "unknown"


def _disk_key(key: tuple) -> str:
    return "|".join(str(k) for k in key)


def _load_disk_cache() -> dict[str, list[int] | dict]:
    global _DISK_CACHE
    if _DISK_CACHE is None:
        try:
            _DISK_CACHE = json.loads(cache_path().read_text())
        except (OSError, ValueError):
            _DISK_CACHE = {}
    return _DISK_CACHE


def _disk_lookup(key: tuple):
    """``(final, partial)`` for a sweep key.

    ``final`` is a served full-sweep vote (the plain ``[br, bc]`` entry
    under the sweep key — the only format older readers ever see).
    ``partial`` is a truncated sweep's progress record, stored under a
    separate ``...|partial`` key so old checkouts sharing the cache file
    never parse it: ``{"blocks": [br, bc], "ms": float,
    "measured": [[br, bc], ...]}``. It is never served as a vote;
    instead the next sweep anchors its enumeration on ``blocks``
    (re-measuring it FRESH — the recorded ms came from another process
    and possibly other load/thermal conditions, and finalizing on a
    cross-condition comparison is exactly how the v2 protocol pinned
    bad tiles) and skips the other already-measured candidates, so
    successive under-budget sweeps partition the grid and the entry
    finalizes into a served vote once the grid is exhausted.
    """
    cache = _load_disk_cache()
    entry = cache.get(_disk_key(key))
    final = None
    if isinstance(entry, list):
        final = (int(entry[0]), int(entry[1]))
    partial = cache.get(_disk_key(key) + "|partial")
    return final, (partial if isinstance(partial, dict) else None)


def _partial_anchor(partial: dict | None) -> tuple[int, int] | None:
    if partial and partial.get("blocks"):
        b = partial["blocks"]
        return int(b[0]), int(b[1])
    return None


def _mutate_disk_cache(mutate) -> None:
    """Read-merge-write under this process: progress records make writes
    routine, and serializing this process's stale memo would drop other
    processes' concurrent votes and progress (lost update). The file is
    re-read immediately before writing and only the caller's keys are
    changed; the remaining read-modify-write window is one json dump
    wide, vs. a whole sweep before."""
    global _DISK_CACHE
    try:
        fresh = json.loads(cache_path().read_text())
        if not isinstance(fresh, dict):
            fresh = {}
    except (OSError, ValueError):
        fresh = {}
    mutate(fresh)
    _DISK_CACHE = fresh
    try:
        path = cache_path()
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(fresh, indent=1, sort_keys=True))
        tmp.replace(path)
    except OSError as e:  # read-only home etc.: in-process cache still holds
        logger.debug("autotune cache not persisted: %s", e)


def _store_final(key: tuple, best: tuple[int, int]) -> None:
    def m(cache):
        cache[_disk_key(key)] = list(best)
        cache.pop(_disk_key(key) + "|partial", None)

    _mutate_disk_cache(m)


def _store_partial(key: tuple, record: dict) -> None:
    def m(cache):
        prev = cache.get(_disk_key(key) + "|partial")
        if isinstance(prev, dict):  # merge concurrent sweeps' progress
            union = {tuple(c) for c in prev.get("measured", [])}
            union |= {tuple(c) for c in record.get("measured", [])}
            record["measured"] = sorted(list(c) for c in union)
        cache[_disk_key(key) + "|partial"] = record

    _mutate_disk_cache(m)


def _candidates(rows: int, cols: int, dim: int, itemsize: int,
                ws_fn=_working_set_bytes, near=None):
    """(row, col) tile grid filtered by shape caps and the kernel's VMEM
    working set (``ws_fn``: loss tiles by default, attention tiles via
    ``attention_working_set_bytes`` — ONE generator for both sweeps).

    ``near``: a (row, col) anchor — usually the static heuristic's pick —
    that orders the grid by log-distance from it. Sweeps run under a wall
    budget and truncate; a fixed row-major order made a truncated sweep's
    "best so far" whatever corner happened to be enumerated first, while
    anchor-ordering means truncation degrades toward the heuristic
    instead of toward an arbitrary tile.
    """
    cands = []
    for br in _ROW_CANDIDATES:
        if br > round_up(rows, 8):
            continue
        for bc in _COL_CANDIDATES:
            if bc > round_up(cols, 128):
                continue
            if ws_fn(br, bc, dim, itemsize) > VMEM_BUDGET_BYTES:
                continue
            cands.append((br, bc))
    if near is not None:
        import math

        def dist(c):
            return (abs(math.log2(c[0] / near[0]))
                    + abs(math.log2(c[1] / near[1])))

        cands.sort(key=dist)
    yield from cands


def _resolve_budget_s(budget_s) -> float | None:
    """Resolve the sweep wall budget: callers that pass nothing get the
    env-overridable default (one place, so every sweep entry point keeps
    the same budget); ``None`` stays 'unbounded'. 240 s covers the full
    v4 loss grid in one process; an under-budgeted sweep persists only
    a progress record (anchor + measured set, never served as a vote),
    so repeated short sweeps advance through the grid and finalize —
    but each pays its own chip time until the grid is exhausted (a
    120 s truncated sweep once voted a 1.4x-slower 8192-causal
    attention tile before progress records existed)."""
    if budget_s == "env":
        return float(os.environ.get("NTXENT_AUTOTUNE_BUDGET_S", "240"))
    return budget_s


def autotune_blocks(
    rows: int,
    cols: int,
    dim: int,
    dtype=jnp.float32,
    *,
    include_backward: bool = True,
    length: int = 100,
    spans: int = 2,
    budget_s: float | None | str = "env",
) -> tuple[int, int]:
    """Time the candidate grid on the live device; return the fastest tile.

    Results are cached per shape/dtype/backend/device-kind, in-process and
    on disk. Falls back to the static heuristic when nothing can be measured
    (e.g. interpret mode on CPU, where timing votes are meaningless anyway).

    Each candidate is voted on with the scanned-chain protocol
    (``time_fn_chained``): ``spans`` timed spans of ``length``
    data-dependent steps each, so one candidate costs one compile plus
    ``(spans + 1) * length`` executions. ``budget_s`` bounds total sweep
    wall time (None = unbounded); it is checked between candidates, so the
    sweep can overshoot by at most one candidate's cost.
    """
    from .blocks import choose_blocks
    from .ntxent_pallas import ntxent_loss_fused

    from ..utils.capability import is_tpu_backend
    if not is_tpu_backend():
        return choose_blocks(rows, cols, dim, dtype)

    key = (f"v{_PROTOCOL_VERSION}", rows, cols, dim, jnp.dtype(dtype).str,
           jax.default_backend(), _device_kind())
    if key in _CACHE:
        return _CACHE[key]
    on_disk, partial = _disk_lookup(key)
    if on_disk is not None:
        _CACHE[key] = on_disk
        return on_disk
    anchor = _partial_anchor(partial)

    z = jax.random.normal(jax.random.PRNGKey(0), (rows, dim), jnp.float32)
    z = (z / jnp.linalg.norm(z, axis=-1, keepdims=True)).astype(dtype)

    def make_loss(cand):
        # The candidate rides as keyword defaults (introspectable via
        # fn.__defaults__ — the sweep tests identify candidates that way).
        def loss(zz, _br=cand[0], _bc=cand[1]):
            return ntxent_loss_fused(zz, 0.07, block_rows=_br,
                                     block_cols=_bc)

        return loss

    best = _measured_sweep(
        key, _candidates(rows, cols, dim, jnp.dtype(dtype).itemsize,
                         near=anchor
                         or choose_blocks(rows, cols, dim, dtype)),
        make_loss, z, length=length, spans=spans,
        with_grad=include_backward, budget_s=budget_s, prior=partial)
    if best is None:
        best = choose_blocks(rows, cols, dim, dtype)
        _CACHE[key] = best
    return best


def _measured_sweep(key, candidates, make_loss, example, *, length, spans,
                    with_grad, budget_s, prior: dict | None = None):
    """Vote a candidate grid with the scanned-chain protocol; cache the
    winner. Returns None when no candidate could be measured — the caller
    supplies (and caches) its static fallback.

    ``prior`` is an earlier truncated sweep's progress record
    (_disk_lookup): its measured candidates are skipped, its (blocks, ms)
    seeds the best-so-far, and the union of measured sets persists — so
    under-budget sweeps advance through the grid instead of re-measuring
    the same prefix, and the entry finalizes into a served vote once the
    grid is exhausted. A still-incomplete sweep stores only the progress
    record; the winner is served in-process but never from disk.

    Per-iteration timing is relay-distorted on tunneled backends
    (time_fn_chained docstring), and a mis-timed vote here would silently
    pin a bad tile in the persistent cache — hence chained votes only.
    """
    budget_s = _resolve_budget_s(budget_s)
    deadline = None if budget_s is None else time.monotonic() + budget_s
    best, best_ms = None, float("inf")
    seen: set[tuple[int, int]] = set()
    ok: set[tuple[int, int]] = set()
    if prior:
        seen = {tuple(c) for c in prior.get("measured", [])}
        # Re-measure the prior best-so-far under THIS process's
        # conditions rather than trusting its recorded ms (anchor
        # ordering puts it first): one candidate re-paid per resumed
        # sweep buys out the cross-condition comparison entirely.
        seen.discard(_partial_anchor(prior))
        ok = set(seen)
    truncated = False
    for cand in candidates:
        if tuple(cand) in seen:
            continue
        if deadline is not None and time.monotonic() > deadline:
            logger.warning("autotune budget (%.0fs) exhausted; best so far "
                           "wins", budget_s)
            truncated = True
            break
        try:
            # min_span_ms: a short-chain vote on a tunneled backend is
            # noise (fixed ~64 ms dispatch overhead vs sub-ms steps) and
            # would pin a random tile in the persistent cache.
            ms, _ = time_fn_chained(make_loss(cand), example, length=length,
                                    spans=spans, with_grad=with_grad,
                                    min_span_ms=400.0)
        except Exception as e:  # candidate failed to compile/fit: skip it
            # for THIS sweep only — a transient failure (OOM under a
            # concurrent job, relay hiccup) persisted as "measured"
            # would permanently exclude the tile on this device kind.
            logger.debug("autotune candidate %s failed: %s", cand, e)
            seen.add(tuple(cand))
            continue
        seen.add(tuple(cand))
        ok.add(tuple(cand))
        logger.info("autotune %s: %.4f ms", cand, ms)
        if ms < best_ms:
            best, best_ms = tuple(cand), ms
    if best is not None:
        if truncated:
            _store_partial(key, {"blocks": list(best), "ms": best_ms,
                                 "measured": sorted(list(c) for c in ok)})
        else:
            _store_final(key, best)
        _CACHE[key] = best
    return best


# ---------------------------------------------------------------------------
# Ring transfer chunks (ISSUE 19): how many independent ppermutes one
# ring hop of the chunked dist_loss splits into. Same cache machinery as
# the tile sweeps — candidates ride as (chunks, 0) 2-tuples so the disk
# format (a 2-element list per served vote) stays shared.
# ---------------------------------------------------------------------------

_RING_CHUNK_CANDIDATES = (1, 2, 4, 8, 16)
# ~64 KiB per circulating chunk: small enough that the first chunk's
# fold starts while the second is still on the wire, large enough that
# per-collective launch latency doesn't eat the overlap.
_RING_CHUNK_TARGET_BYTES = 64 * 1024


def choose_ring_chunks(rows: int, dim: int, num_devices: int,
                       itemsize: int = 4) -> int:
    """CPU-safe static chunk-count heuristic for the chunked ring
    schedule — a pure function of (rows, dim, mesh size, itemsize), so
    interpreter-mode traces are deterministic across processes. ``rows``
    is the circulating block's row count (2 * n_local for the stacked
    NT-Xent block). One chunk per ~64 KiB of payload, capped at 8 and
    at the row count; degenerate meshes (P <= 1) never chunk."""
    if num_devices <= 1 or rows <= 1:
        return 1
    payload = int(rows) * int(dim) * int(itemsize)
    return int(max(1, min(payload // _RING_CHUNK_TARGET_BYTES, 8, rows)))


def _ring_chunk_key(rows: int, dim: int, num_devices: int, dtype) -> tuple:
    return (f"v{_PROTOCOL_VERSION}", "ringchunks", rows, dim, num_devices,
            jnp.dtype(dtype).str, jax.default_backend(), _device_kind())


def resolve_ring_chunks(rows: int, dim: int, num_devices: int,
                        dtype=jnp.float32, *,
                        chunks: int | None = None) -> int:
    """Trace-safe chunk-count resolution: explicit override -> cached
    measured vote -> static heuristic. NEVER measures — this is called
    at loss-build/trace time (dist_loss.local_ntxent_chunked), where a
    sweep would compile the very function being traced; measurement
    belongs to ``autotune_ring_chunks``."""
    if chunks is not None:
        return max(1, min(int(chunks), max(int(rows), 1)))
    key = _ring_chunk_key(rows, dim, num_devices, dtype)
    if key in _CACHE:
        return int(_CACHE[key][0])
    on_disk, _ = _disk_lookup(key)
    if on_disk is not None:
        _CACHE[key] = on_disk
        return int(on_disk[0])
    return choose_ring_chunks(rows, dim, num_devices,
                              jnp.dtype(dtype).itemsize)


def _ring_chunk_candidates(rows: int, near: tuple | None = None):
    import math

    cands = [(c, 0) for c in _RING_CHUNK_CANDIDATES
             if c <= max(int(rows), 1)]
    if near is not None and near[0] > 0:
        cands.sort(key=lambda c: abs(math.log2(c[0] / near[0])))
    yield from cands


def autotune_ring_chunks(
    mesh,
    n_local: int,
    dim: int,
    dtype=jnp.float32,
    *,
    axis: str = "data",
    temperature: float = 0.1,
    include_backward: bool = True,
    length: int = 50,
    spans: int = 2,
    budget_s: float | None | str = "env",
) -> int:
    """Measured transfer-chunk count for the chunked ring dist_loss.

    Same contract as the tile sweeps: scanned-chain votes on the live
    device, winner cached per (rows, dim, mesh size, dtype, device
    kind), ``choose_ring_chunks`` as the off-device fallback. The vote
    times the full sharded chunked loss (forward + backward when
    ``include_backward``), so what wins is the chunk count whose
    transfer/compute interleave the real schedule prefers — the
    overlap window itself, not a proxy.
    """
    from ..utils.capability import is_tpu_backend

    num_devices = int(mesh.shape[axis])
    rows = 2 * int(n_local)
    itemsize = jnp.dtype(dtype).itemsize
    fallback = choose_ring_chunks(rows, dim, num_devices, itemsize)
    if not is_tpu_backend():
        return fallback

    key = _ring_chunk_key(rows, dim, num_devices, dtype)
    if key in _CACHE:
        return int(_CACHE[key][0])
    on_disk, partial = _disk_lookup(key)
    if on_disk is not None:
        _CACHE[key] = on_disk
        return int(on_disk[0])
    anchor = _partial_anchor(partial)

    n_global = n_local * num_devices
    z = jax.random.normal(jax.random.PRNGKey(0), (n_global, dim),
                          jnp.float32)
    z = (z / jnp.linalg.norm(z, axis=-1, keepdims=True)).astype(dtype)

    def make_loss(cand):
        from ..parallel.dist_loss import make_sharded_ntxent

        fn = make_sharded_ntxent(mesh, temperature, axis=axis,
                                 impl="chunked", ring_chunks=int(cand[0]))

        def loss(zz, _c=cand[0]):
            return fn(zz, zz)

        return loss

    best = _measured_sweep(
        key, _ring_chunk_candidates(rows, near=anchor or (fallback, 0)),
        make_loss, z, length=length, spans=spans,
        with_grad=include_backward, budget_s=budget_s, prior=partial)
    if best is None:
        best = (fallback, 0)
        _CACHE[key] = best
    return int(best[0])


def _attention_candidates(l_q: int, l_kv: int, d: int, itemsize: int,
                          include_backward: bool = False, near=None):
    import functools as _ft

    from .attention_pallas import attention_working_set_bytes

    return _candidates(
        l_q, l_kv, d, itemsize,
        ws_fn=_ft.partial(attention_working_set_bytes,
                          backward=include_backward),
        near=near)


def autotune_attention_blocks(
    l_q: int,
    l_kv: int,
    head_dim: int,
    dtype=jnp.bfloat16,
    *,
    causal: bool = False,
    batch_heads: int = 8,
    include_backward: bool = True,
    length: int = 50,
    spans: int = 2,
    budget_s: float | None | str = "env",
) -> tuple[int, int]:
    """Measured (block_q, block_kv) for the fused flash-attention kernels.

    Same contract as ``autotune_blocks``, applied to
    ``ops.attention_pallas.flash_attention``: scanned-chain votes on the
    live device, winner cached per shape/causality/dtype/device-kind,
    static VMEM heuristic as the off-device fallback. ``batch_heads``
    sizes the representative B*H grid dimension the vote runs under.
    """
    from ..utils.capability import is_tpu_backend
    from .attention_pallas import _blocks, flash_attention

    itemsize = jnp.dtype(dtype).itemsize
    fallback = _blocks(l_q, l_kv, head_dim, None, None, itemsize)
    if not is_tpu_backend():
        return fallback

    # include_backward and batch_heads are part of the key: a forward-only
    # vote (bench_attention.py) must never be served to a training-path
    # caller whose backward kernels may prefer a different tile.
    key = (f"v{_PROTOCOL_VERSION}", "attn", l_q, l_kv, head_dim,
           bool(causal), bool(include_backward), batch_heads,
           jnp.dtype(dtype).str, jax.default_backend(), _device_kind())
    if key in _CACHE:
        return _CACHE[key]
    on_disk, partial = _disk_lookup(key)
    if on_disk is not None:
        _CACHE[key] = on_disk
        return on_disk
    anchor = _partial_anchor(partial)

    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    shape = (1, l_q, batch_heads, head_dim)
    q = (jax.random.normal(kq, shape) * 0.5).astype(dtype)
    k = (jax.random.normal(kk, (1, l_kv, batch_heads, head_dim))
         * 0.5).astype(dtype)
    v = (jax.random.normal(kv, k.shape) * 0.5).astype(dtype)

    def make_loss(cand):
        def loss(qq, _bq=cand[0], _bk=cand[1]):
            # The chain timer differentiates w.r.t. qq ONLY; tying k and
            # v to qq keeps the dK/dV recompute kernel live in the vote —
            # with independent k/v its cotangents feed nothing and XLA
            # DCEs the very kernel a backward-inclusive vote must time.
            tie = 1e-3 * jnp.mean(qq) if include_backward else 0.0
            kk = k + tie  # scalar tie: shape-safe for l_q != l_kv
            vv = v + tie
            return jnp.sum(flash_attention(
                qq, kk, vv, causal=causal, block_q=_bq, block_kv=_bk
            ).astype(jnp.float32))

        return loss

    best = _measured_sweep(
        key, _attention_candidates(l_q, l_kv, head_dim, itemsize,
                                   include_backward=include_backward,
                                   near=anchor or fallback),
        make_loss, q, length=length, spans=spans,
        with_grad=include_backward, budget_s=budget_s, prior=partial)
    if best is None:
        best = fallback
        _CACHE[key] = best
    return best
