"""Fused flash-attention Pallas kernels for the long-context hot path.

The sequence-parallel family (parallel/ring_attention.py) decomposes
attention ACROSS chips; this module is the single-chip hot path UNDER
those decompositions: softmax(Q K^T / sqrt(d)) V computed blockwise on
the MXU with online-softmax statistics in VMEM — the (L, L) matrix never
touches HBM. Same design as the loss kernels (ops/ntxent_pallas.py):

* forward: one tile walk; running (m, l, acc) in VMEM scratch; each
  (q-block, kv-block) tile is one MXU matmul + a VPU fold; the row
  logsumexp is published as a residual for the backward;
* backward: flash recompute — a dQ kernel (walks kv blocks for each home
  q block) and a dK/dV kernel (walks q blocks for each home kv block),
  each rebuilding its s tile from the saved lse instead of reading a
  stored probability matrix (O(L) residuals, O(block²) live memory);
* numerics: fp32 statistics regardless of input dtype, the same
  ``_exp0``/``_log_l`` compiler-skew hardening the loss kernels use, and
  explicit zeroing of fully-masked folds (causal ring hops).

Layout: the public entry takes the towers' (B, L, H, D) and flattens to
(B*H, L, D) — batch*heads becomes the outer grid axis, so every tile is
a clean (block, D) MXU operand. Causal masking takes global position
OFFSETS so sequence-sharded callers (ring hops) mask correctly.

Off-TPU the kernels run in Pallas interpret mode (exact, slow) — the
tests pin them against `attention_oracle` there; on TPU they compile
natively (tests/test_tpu_only.py asserts the backend).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .blocks import VMEM_BUDGET_BYTES, round_up
from .ntxent_pallas import _default_interpret, _exp0, _log_l, _pad_rows

__all__ = ["flash_attention", "resolve_attention_scale"]

_NEG_INF = -1e30


def resolve_attention_scale(scale, head_dim) -> float:
    """The ONE copy of the default-scale rule (None -> 1/sqrt(head_dim));
    shared by every attention form (parallel/ring_attention.py included)
    so a convention change cannot silently diverge between them."""
    return float(scale) if scale is not None else 1.0 / math.sqrt(head_dim)


def _tile_live(i, j, bq, bk, q_off, k_off):
    """False iff the (i, j) tile is ENTIRELY above the causal diagonal
    (its smallest key position exceeds its largest query position) — the
    MXU work for such a tile is all-masked and skippable."""
    return (k_off + j * bk) <= (q_off + (i + 1) * bq - 1)


def _causal_mask(s, i, j, bq, bk, q_off, k_off):
    qpos = q_off + i * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = k_off + j * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(kpos > qpos, _NEG_INF, s)


def _pad_mask(s, j, bk, cols_actual):
    kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(kpos >= cols_actual, _NEG_INF, s)


def _fwd_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s,
                acc_s, *, bq, bk, sc, causal, cols_actual):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    q_off = offs_ref[0, 0]
    k_off = offs_ref[0, 1]

    @pl.when(j == 0)
    def _():
        m_s[:] = jnp.full(m_s.shape, _NEG_INF, jnp.float32)
        l_s[:] = jnp.zeros(l_s.shape, jnp.float32)
        acc_s[:] = jnp.zeros(acc_s.shape, jnp.float32)

    def compute():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sc
        s = _pad_mask(s, j, bk, cols_actual)
        if causal:
            s = _causal_mask(s, i, j, bq, bk, q_off, k_off)

        m_old = m_s[:]
        m_new = jnp.maximum(m_old, jnp.max(s, axis=1, keepdims=True))
        # A fully-masked fold leaves m_new at -inf and s - m_new == 0; the
        # raw exp would weight masked entries 1 (same edge the jnp fold
        # guards — still reachable via q padding even with tile skipping).
        p = jnp.where(s <= _NEG_INF * 0.5, 0.0, _exp0(s - m_new))
        alpha = _exp0(m_old - m_new)
        l_s[:] = l_s[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)
        m_s[:] = m_new

    if causal:
        # Tiles entirely above the diagonal are all-masked: skip their
        # MXU matmuls outright (~2x at long L) instead of masking them.
        pl.when(_tile_live(i, j, bq, bk, q_off, k_off))(compute)
    else:
        compute()

    @pl.when(j == nj - 1)
    def _():
        # Rows that saw nothing (q padding) divide by l=0 -> guard to 1.
        l_safe = jnp.where(l_s[:] == 0.0, 1.0, l_s[:])
        o_ref[0] = (acc_s[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_s[:] + _log_l(l_s[:])


def _dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_s, *, bq, bk, sc, causal, cols_actual):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    q_off = offs_ref[0, 0]
    k_off = offs_ref[0, 1]

    @pl.when(j == 0)
    def _():
        dq_s[:] = jnp.zeros(dq_s.shape, jnp.float32)

    def compute():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sc
        s = _pad_mask(s, j, bk, cols_actual)
        if causal:
            s = _causal_mask(s, i, j, bq, bk, q_off, k_off)
        p = jnp.where(s <= _NEG_INF * 0.5, 0.0,
                      _exp0(s - lse_ref[0]))
        dp = jax.lax.dot_general(
            do_ref[0].astype(jnp.float32), v_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * sc
        dq_s[:] += jax.lax.dot(ds.astype(k_ref.dtype), k_ref[0],
                               preferred_element_type=jnp.float32)

    if causal:
        pl.when(_tile_live(i, j, bq, bk, q_off, k_off))(compute)
    else:
        compute()

    @pl.when(j == nj - 1)
    def _():
        dq_ref[0] = dq_s[:].astype(dq_ref.dtype)


def _dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_s, dv_s, *, bq, bk, sc, causal,
                cols_actual):
    j = pl.program_id(1)   # home kv block
    i = pl.program_id(2)   # visiting q block
    ni = pl.num_programs(2)
    q_off = offs_ref[0, 0]
    k_off = offs_ref[0, 1]

    @pl.when(i == 0)
    def _():
        dk_s[:] = jnp.zeros(dk_s.shape, jnp.float32)
        dv_s[:] = jnp.zeros(dv_s.shape, jnp.float32)

    def compute():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sc
        s = _pad_mask(s, j, bk, cols_actual)
        if causal:
            s = _causal_mask(s, i, j, bq, bk, q_off, k_off)
        p = jnp.where(s <= _NEG_INF * 0.5, 0.0,
                      _exp0(s - lse_ref[0]))
        do32 = do_ref[0].astype(jnp.float32)
        # dV_j += P^T dO_i ; dS = P*(dO V_j^T - delta) ; dK_j += dS^T Q_i
        dv_s[:] += jax.lax.dot_general(
            p, do32, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do32, v_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * sc
        dk_s[:] += jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(_tile_live(i, j, bq, bk, q_off, k_off))(compute)
    else:
        compute()

    @pl.when(i == ni - 1)
    def _():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _fold_kernel(offs_ref, q_ref, k_ref, v_ref, m_in, l_in, acc_in,
                 m_out, l_out, acc_out, *, bq, bk, sc, causal, cols_actual):
    """One flash fold with CARRIED statistics: (m, l, acc) arrive as
    inputs (a previous fold's — or ring hop's — running state), are
    updated with this call's K/V, and leave as outputs. The ring
    attention hot path: each ppermute hop is one of these calls, so the
    across-hop softmax state never re-normalizes and the final
    ``out = acc / l`` is exact regardless of hop order."""
    i = pl.program_id(1)
    j = pl.program_id(2)
    q_off = offs_ref[0, 0]
    k_off = offs_ref[0, 1]

    @pl.when(j == 0)
    def _():
        m_out[:] = m_in[:]
        l_out[:] = l_in[:]
        acc_out[:] = acc_in[:]

    def compute():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sc
        s = _pad_mask(s, j, bk, cols_actual)
        if causal:
            s = _causal_mask(s, i, j, bq, bk, q_off, k_off)
        m_old = m_out[0]
        m_new = jnp.maximum(m_old, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(s <= _NEG_INF * 0.5, 0.0, _exp0(s - m_new))
        alpha = _exp0(m_old - m_new)
        l_out[0] = l_out[0] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_out[0] = acc_out[0] * alpha + jax.lax.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)
        m_out[0] = m_new

    if causal:
        pl.when(_tile_live(i, j, bq, bk, q_off, k_off))(compute)
    else:
        compute()


def flash_fold(qf, kf, vf, m, l, acc, *, q_offset, k_offset,
               scale, causal=False, block_q=None, block_kv=None,
               cols_actual=None, interpret=None):
    """Fold one K/V segment into running flash statistics (flattened
    (BH, L, D) layout; caller pads L to block multiples).

    The building block of the fused ring attention
    (parallel/ring_attention.py, impl="flash"): state (m, l: (BH, Lq)
    fp32; acc: (BH, Lq, D) fp32) threads through successive calls —
    offsets are TRACED, so a device-dependent ring hop can mask
    causally against global positions. Row padding to block multiples is
    handled here: padded keys are masked, padded query rows' stats are
    sliced away before returning.
    """
    bh, lq_a, d = qf.shape
    lk_a = kf.shape[1]
    bq, bk = _blocks(lq_a, lk_a, d, block_q, block_kv,
                     jnp.dtype(qf.dtype).itemsize)
    if interpret is None:
        interpret = _default_interpret()
    qf = _pad_axis1(qf, bq)
    kf, vf = _pad_axis1(kf, bk), _pad_axis1(vf, bk)
    m = _pad_axis1(m, bq)[..., None]
    l = _pad_axis1(l, bq)[..., None]
    acc = _pad_axis1(acc, bq)
    lq, lk = qf.shape[1], kf.shape[1]
    offspec, qspec, kspec, rowvec = _specs(bq, bk, d)
    kernel = functools.partial(
        _fold_kernel, bq=bq, bk=bk, sc=scale, causal=causal,
        cols_actual=lk_a if cols_actual is None else cols_actual)
    m, l, acc = pl.pallas_call(
        kernel,
        grid=(bh, lq // bq, lk // bk),
        in_specs=[offspec, qspec, kspec, kspec, rowvec, rowvec, qspec],
        out_specs=[rowvec, rowvec, qspec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
            jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
            jax.ShapeDtypeStruct((bh, lq, d), jnp.float32),
        ],
        interpret=interpret,
    )(_offs_arr(q_offset, k_offset), qf, kf, vf, m, l, acc)
    return m[:, :lq_a, 0], l[:, :lq_a, 0], acc[:, :lq_a]


def flash_dq_hop(qf, kf, vf, dof, lsef, deltaf, *, q_offset, k_offset,
                 scale, causal=False, block_q=None, block_kv=None,
                 cols_actual=None, interpret=None):
    """This K/V segment's contribution to dQ (flattened layout, fp32) —
    the per-hop unit of the fused ring backward; caller sums over hops."""
    bh, lq_a, d = qf.shape
    lk_a = kf.shape[1]
    bq, bk = _blocks(lq_a, lk_a, d, block_q, block_kv,
                     jnp.dtype(qf.dtype).itemsize)
    if interpret is None:
        interpret = _default_interpret()
    qf, dof = _pad_axis1(qf, bq), _pad_axis1(dof, bq)
    kf, vf = _pad_axis1(kf, bk), _pad_axis1(vf, bk)
    lsef = _pad_axis1(lsef, bq)[..., None]
    deltaf = _pad_axis1(deltaf, bq)[..., None]
    lq, lk = qf.shape[1], kf.shape[1]
    offspec, qspec, kspec, rowvec = _specs(bq, bk, d)
    common = dict(bq=bq, bk=bk, sc=scale, causal=causal,
                  cols_actual=lk_a if cols_actual is None else cols_actual)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid=(bh, lq // bq, lk // bk),
        in_specs=[offspec, qspec, kspec, kspec, qspec, rowvec, rowvec],
        out_specs=[pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                                memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((bh, lq, d), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(_offs_arr(q_offset, k_offset), qf, kf, vf, dof, lsef, deltaf)[0]
    return dq[:, :lq_a]


def flash_dkv_hop(qf, kf, vf, dof, lsef, deltaf, *, q_offset, k_offset,
                  scale, causal=False, block_q=None, block_kv=None,
                  cols_actual=None, interpret=None):
    """The local rows' contribution to this visiting K/V segment's
    (dK, dV) (flattened layout, fp32) — circulated home by the ring."""
    bh, lq_a, d = qf.shape
    lk_a = kf.shape[1]
    bq, bk = _blocks(lq_a, lk_a, d, block_q, block_kv,
                     jnp.dtype(qf.dtype).itemsize)
    if interpret is None:
        interpret = _default_interpret()
    qf, dof = _pad_axis1(qf, bq), _pad_axis1(dof, bq)
    kf, vf = _pad_axis1(kf, bk), _pad_axis1(vf, bk)
    lsef = _pad_axis1(lsef, bq)[..., None]
    deltaf = _pad_axis1(deltaf, bq)[..., None]
    lq, lk = qf.shape[1], kf.shape[1]
    common = dict(bq=bq, bk=bk, sc=scale, causal=causal,
                  cols_actual=lk_a if cols_actual is None else cols_actual)
    offspec_v = pl.BlockSpec((1, 2), lambda b, j, i: (0, 0),
                             memory_space=pltpu.SMEM)
    qspec_v = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    kspec_h = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0),
                           memory_space=pltpu.VMEM)
    rowvec_v = pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0),
                            memory_space=pltpu.VMEM)

    def dkv_kernel(*refs):
        return _dkv_kernel(*refs, **common)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, lk // bk, lq // bq),
        in_specs=[offspec_v, qspec_v, kspec_h, kspec_h, qspec_v, rowvec_v,
                  rowvec_v],
        out_specs=[kspec_h, kspec_h],
        out_shape=[jax.ShapeDtypeStruct((bh, lk, d), jnp.float32),
                   jax.ShapeDtypeStruct((bh, lk, d), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(_offs_arr(q_offset, k_offset), qf, kf, vf, dof, lsef, deltaf)
    return dk[:, :lk_a], dv[:, :lk_a]


def _pad_axis1(x, mult):
    perm = (1, 0) if x.ndim == 2 else (1, 0, 2)
    return _pad_rows(x.transpose(*perm), mult).transpose(*perm)


def _flat(x):
    b, l, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, l, d)


def _unflat(x, b, h):
    bh, l, d = x.shape
    return x.reshape(b, h, l, d).transpose(0, 2, 1, 3)


def attention_working_set_bytes(bq: int, bk: int, d: int,
                                itemsize: int = 4,
                                backward: bool = False) -> int:
    """VMEM bytes one (q-block, kv-block) attention tile keeps live:
    q/k/v blocks + the fp32 s/p tile + fp32 accumulators. Shared by the
    static chooser below and the measured sweep (ops/autotune.py).
    ``backward=True`` adds the recompute kernels' extra residents (the
    do block and the dk/dv accumulator pair) so a backward-inclusive
    sweep never admits tiles only the forward fits."""
    ws = ((bq + 2 * bk) * d * itemsize           # q + k + v blocks
          + bq * bk * 4 * 2                      # s and p, fp32
          + (bq + bk) * d * 4 + bq * 8)          # accs + m/l
    if backward:
        ws += bq * d * itemsize + (bq + bk) * d * 4  # do + dq/dk/dv accs
    return ws


def _blocks(l, lk, d, block_q, block_kv, itemsize=4):
    if (block_q is not None and block_q <= 0) or \
            (block_kv is not None and block_kv <= 0):
        raise ValueError(f"block_q/block_kv must be positive, got "
                         f"{(block_q, block_kv)}")
    bq = block_q or min(256, round_up(l, 8))
    bk = block_kv or min(256, round_up(lk, 128))
    bq = round_up(min(bq, round_up(l, 8)), 8)
    bk = round_up(min(bk, round_up(lk, 128)), 128)
    if (block_q is not None and bq != block_q) or \
            (block_kv is not None and bk != block_kv):
        # An explicit pin (e.g. an autotune winner recorded for another
        # shape) that is not a legal tile here gets aligned/clamped —
        # say so, or the caller believes their measured tile is running.
        import logging

        logging.getLogger(__name__).warning(
            "attention tile pin (%s, %s) adjusted to legal (%s, %s) "
            "for shape l=%s lk=%s", block_q, block_kv, bq, bk, l, lk)
    # Shrink un-pinned dimensions until the tile working set fits VMEM.
    while attention_working_set_bytes(bq, bk, d, itemsize) \
            > VMEM_BUDGET_BYTES:
        if block_kv is None and bk > 128:
            bk //= 2
        elif block_q is None and bq > 8:
            bq //= 2
        else:
            break  # caller pinned both: their responsibility
    return bq, bk


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, sc, causal, q_off, k_off, bq, bk, interpret):
    return _flash_fwd(q, k, v, sc, causal, q_off, k_off, bq, bk,
                      interpret)[0]


def _specs(bq, bk, d):
    offspec = pl.BlockSpec((1, 2), lambda b, i, j: (0, 0),
                           memory_space=pltpu.SMEM)
    qspec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM)
    # Row statistics (lse/delta/m/l) ride as (bh, L, 1) column vectors:
    # a (1, bq) block over a (bh, L) array is not a legal TPU tile
    # (second-to-last block dim must be 8-divisible or span the array),
    # but (1, bq, 1) over (bh, L, 1) is — the same layout the loss
    # kernels use for their (rows, 1) statistics.
    rowvec = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    return offspec, qspec, kspec, rowvec


def _offs_arr(q_off, k_off):
    return jnp.stack(
        [jnp.asarray(q_off, jnp.int32),
         jnp.asarray(k_off, jnp.int32)]).reshape(1, 2)


def _flash_fwd(q, k, v, sc, causal, q_off, k_off, bq, bk, interpret):
    b, lq_a, h, d = q.shape
    lk_a = k.shape[1]
    qf = _pad_axis1(_flat(q), bq)
    kf = _pad_axis1(_flat(k), bk)
    vf = _pad_axis1(_flat(v), bk)
    bh, lq, _ = qf.shape
    lk = kf.shape[1]
    offspec, qspec, kspec, rowvec = _specs(bq, bk, d)

    kernel = functools.partial(
        _fwd_kernel, bq=bq, bk=bk, sc=sc, causal=causal,
        cols_actual=lk_a)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, lq // bq, lk // bk),
        in_specs=[offspec, qspec, kspec, kspec],
        out_specs=[qspec, rowvec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * lq * lk * d,
            bytes_accessed=(bh * lq * d * 2
                            + (lq // bq) * bh * lk * d * 2)
            * q.dtype.itemsize,
            transcendentals=bh * lq * lk,
        ),
        interpret=interpret,
    )(_offs_arr(q_off, k_off), qf, kf, vf)
    out = _unflat(o[:, :lq_a], b, h)
    return out, (q, k, v, out, lse[:, :lq_a, 0])


def _flash_bwd(sc, causal, q_off, k_off, bq, bk, interpret, res, g):
    # ONE backward implementation: the hop wrappers (flash_dq_hop /
    # flash_dkv_hop) own the padding and pallas_call wiring; the
    # single-chip backward is simply the one-hop case.
    q, k, v, out, lse = res
    b, _, h, _ = q.shape
    qf, kf, vf, dof, outf = (_flat(x) for x in (q, k, v, g, out))
    # delta_i = sum_d do_i o_i (the softmax-backward row correction):
    # cheap jnp preprocessing, O(L) memory.
    delta = jnp.sum(dof.astype(jnp.float32) * outf.astype(jnp.float32),
                    axis=-1)
    kwargs = dict(q_offset=q_off, k_offset=k_off, scale=sc, causal=causal,
                  block_q=bq, block_kv=bk, interpret=interpret)
    dq = flash_dq_hop(qf, kf, vf, dof, lse, delta, **kwargs)
    dk, dv = flash_dkv_hop(qf, kf, vf, dof, lse, delta, **kwargs)
    return (_unflat(dq, b, h).astype(q.dtype),
            _unflat(dk, b, h).astype(k.dtype),
            _unflat(dv, b, h).astype(v.dtype))


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
    q_offset: int = 0,
    k_offset: int = 0,
    block_q: int | None = None,
    block_kv: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused blockwise attention: softmax(q k^T * scale) v on the MXU.

    q, k, v: (B, L, H, D) (k/v may have a different L than q). Exact
    forward and gradients (flash recompute backward); the (L, L) matrix
    never exists in HBM. ``q_offset``/``k_offset`` give the blocks'
    global positions for causal masking under sequence sharding. Drop-in
    for ``parallel.ring_attention.attention_oracle`` and usable as a
    ``LongContextTransformer.attention_fn``.
    """
    if (q.ndim != 4 or k.shape != v.shape or q.shape[::2] != k.shape[::2]
            or q.shape[3] != k.shape[3]):
        raise ValueError(
            f"expected (B, L, H, D) q/k/v with shared B/H/D, got "
            f"{q.shape} {k.shape} {v.shape}")
    sc = resolve_attention_scale(scale, q.shape[-1])
    bq, bk = _blocks(q.shape[1], k.shape[1], q.shape[-1], block_q, block_kv,
                     jnp.dtype(q.dtype).itemsize)
    if interpret is None:
        interpret = _default_interpret()
    return _flash(q, k, v, sc, causal, int(q_offset), int(k_offset),
                  bq, bk, interpret)
