"""Block-shape selection for the fused NT-Xent Pallas kernels.

TPU-native replacement for the reference's ``get_optimal_block_size``
(/root/reference/include/ntxent_kernel.cuh:80-96, which picked a CUDA block
size as min(nextPowerOf2(n), 1024) — with nextPowerOf2 never defined,
SURVEY.md §2.3-D2). Here the tunable is the (row, col) tile of the similarity
matrix: tiles must respect TPU tiling (sublane multiples of 8, lane multiples
of 128 for fp32) and the working set must fit VMEM (~16 MB/core) with room
for double buffering.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["choose_blocks", "round_up", "VMEM_BUDGET_BYTES"]

# Leave headroom below the ~16 MB/core VMEM for pipeline double-buffering.
VMEM_BUDGET_BYTES = 8 * 1024 * 1024

_SUBLANE = 8
_LANE = 128


def round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _working_set_bytes(br: int, bc: int, dim: int, itemsize: int) -> int:
    # row block + col block + fp32 similarity tile + fp32 (BR, D) grad accum.
    return (br * dim + bc * dim) * itemsize + br * bc * 4 + br * dim * 4


def choose_blocks(
    rows: int,
    cols: int,
    dim: int,
    dtype=jnp.float32,
    block_rows: int | None = None,
    block_cols: int | None = None,
) -> tuple[int, int]:
    """Pick (block_rows, block_cols) for a rows x cols similarity computation.

    Explicit overrides are honored (rounded to hardware multiples). Defaults
    favor wide column tiles (the contraction that feeds the MXU) and shrink
    until the working set fits the VMEM budget.
    """
    itemsize = jnp.dtype(dtype).itemsize
    br = block_rows if block_rows is not None else min(256, round_up(rows, _SUBLANE))
    bc = block_cols if block_cols is not None else min(512, round_up(cols, _LANE))
    br = max(_SUBLANE, round_up(min(br, round_up(rows, _SUBLANE)), _SUBLANE))
    bc = max(_LANE, round_up(min(bc, round_up(cols, _LANE)), _LANE))
    # Shrink whichever dimensions were NOT explicitly pinned until the
    # working set fits; explicit overrides are the caller's responsibility.
    while _working_set_bytes(br, bc, dim, itemsize) > VMEM_BUDGET_BYTES:
        can_shrink_bc = block_cols is None and bc > _LANE
        can_shrink_br = block_rows is None and br > _SUBLANE
        if can_shrink_bc and (bc >= br or not can_shrink_br):
            bc //= 2
        elif can_shrink_br:
            br //= 2
        else:
            break
        br = round_up(br, _SUBLANE)
        bc = round_up(bc, _LANE)
    return br, bc
