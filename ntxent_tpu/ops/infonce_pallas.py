"""Fused cross-modal InfoNCE (CLIP-style) on the shared Pallas kernel family.

The BASELINE.json configs[4] workload (CLIP text-image InfoNCE, global batch
32768) — the scale the reference's declared-but-absent NCCL path was named
for (SURVEY.md §2.2). Same blockwise online-softmax design as the NT-Xent
kernels (ops/ntxent_pallas.py): the (N, N) cross-modal similarity matrix
``s = scale * za @ zb.T`` is tiled into VMEM, never materialized in HBM, and
only the per-row/per-column logsumexp survives as the O(N) residual.

Differences from NT-Xent, expressed through the kernels' ``diag_pos`` mode:
positives sit on the a<->b diagonal (not at offset N) and the diagonal is NOT
masked (za_i / zb_i are different modalities, so s_ii is a real pair, not a
self-similarity). The loss is the symmetric cross-entropy
``0.5 * (mean_i [lse_row_i - s_ii] + mean_j [lse_col_j - s_jj])``
(= ops.oracle.info_nce_loss).

The logit scale is a **traced, differentiable** input (CLIP's learnable
``exp(logit_scale)``): it enters the kernels as a (1, 1) SMEM scalar and
multiplies the fp32 MXU product — same arithmetic as the oracle, and
d(loss)/d(scale) falls out of the row-gradient identity
``dL/dscale = sum_i (G @ zb)_i . za_i`` with no extra kernel pass.

Backward runs ONE fused kernel per input: for grad_za the row-softmax term
(via row lse) and the column-softmax term (via column lse) are combined into
a single ``G`` tile before one MXU matmul — half the passes of composing two
one-direction VJPs.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from .blocks import choose_blocks
from .ntxent_pallas import (
    _bwd_sym_call,
    _default_interpret,
    _fwd_call,
    _gid_column,
    _ntxent_partial,
    _pad_rows,
)

__all__ = ["info_nce_fused", "info_nce_partial_fused", "resolve_scale"]


def resolve_scale(temperature: float, scale) -> jax.Array:
    """Logit scale as a traced fp32 scalar: ``scale`` if given, else 1/T."""
    if scale is None:
        scale = 1.0 / float(temperature)
    return jnp.asarray(scale, jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _infonce(za, zb, scale, br, bc, interpret):
    return _infonce_fwd(za, zb, scale, br, bc, interpret)[0]


def _infonce_prepare(za, zb, br, bc):
    n = za.shape[0]
    pad = math.lcm(br, bc)  # each side serves as both rows and columns
    zap = _pad_rows(za, pad)
    zbp = _pad_rows(zb, pad)
    gid = _gid_column(jnp.arange(zap.shape[0]), pad, sentinel=n)
    return zap, zbp, gid, n


def _infonce_fwd(za, zb, scale, br, bc, interpret):
    zap, zbp, gid, n = _infonce_prepare(za, zb, br, bc)
    common = dict(br=br, bc=bc, inv_t=1.0, cols_actual=n, n_half=0,
                  interpret=interpret, diag_pos=True, scale=scale)
    loss_a, lse_a = _fwd_call(zap, zbp, gid, **common)   # rows of s
    loss_b, lse_b = _fwd_call(zbp, zap, gid, **common)   # rows of s.T = cols
    loss = (loss_a + loss_b) / (2 * n)
    return loss, (za, zb, scale, lse_a, lse_b)


def _infonce_bwd(br, bc, interpret, res, g):
    za, zb, scale, lse_a, lse_b = res
    zap, zbp, gid, n = _infonce_prepare(za, zb, br, bc)
    common = dict(br=br, bc=bc, inv_t=1.0, cols_actual=n, n_half=0,
                  interpret=interpret, diag_pos=True, scale=scale)
    # o_a[i] = sum_j G_ij zb_j with G = P_row + P_col - 2I (the total dL/ds
    # before scale/normalization); o_b[j] = sum_i G_ij za_i.
    o_a = _bwd_sym_call(zap, gid, lse_a, z_cols=zbp, lse_cols=lse_b,
                        **common)[:n]
    o_b = _bwd_sym_call(zbp, gid, lse_b, z_cols=zap, lse_cols=lse_a,
                        **common)[:n]
    coef = g / (2 * n)
    grad_za = (o_a * (coef * scale)).astype(za.dtype)
    grad_zb = (o_b * (coef * scale)).astype(zb.dtype)
    # dL/dscale = coef * sum_ij G_ij (za_i . zb_j) = coef * sum_i o_a[i].za[i]
    grad_scale = (coef * jnp.sum(o_a * za.astype(jnp.float32))).reshape(
        jnp.shape(scale)).astype(scale.dtype)
    return grad_za, grad_zb, grad_scale


_infonce.defvjp(_infonce_fwd, _infonce_bwd)


def info_nce_fused(
    za: jax.Array,
    zb: jax.Array,
    temperature: float = 0.07,
    *,
    scale: jax.Array | float | None = None,
    block_rows: int | None = None,
    block_cols: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused symmetric InfoNCE over paired embeddings za, zb: (N, D) each.

    Drop-in fused equivalent of ``ops.oracle.info_nce_loss`` — same
    semantics, O(N) memory, exact gradients for za, zb AND the logit scale.
    Pass ``scale`` (= 1/T, e.g. CLIP's learnable ``exp(logit_scale)``) as a
    traced array to train it; otherwise ``temperature`` is used.
    """
    if za.shape != zb.shape:
        raise ValueError(f"paired embeddings must match: {za.shape} vs {zb.shape}")
    scale = resolve_scale(temperature, scale)
    br, bc = choose_blocks(za.shape[0], za.shape[0], za.shape[1], za.dtype,
                           block_rows, block_cols)
    if interpret is None:
        interpret = _default_interpret()
    return _infonce(za, zb, scale, br, bc, interpret)


def info_nce_partial_fused(
    z_rows: jax.Array,
    z_cols: jax.Array,
    row_gid: jax.Array,
    *,
    scale: jax.Array | float = 1.0,
    block_rows: int | None = None,
    block_cols: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """One-direction partial InfoNCE **sum** over rows of the global matrix.

    Returns ``sum_i [logsumexp_j s_ij - s_i,gid(i)]`` where
    ``s = scale * z_rows @ z_cols.T`` and the positive of local row i is
    global column ``row_gid[i]`` — the diagonal of the global matrix.
    Differentiable w.r.t. both operands and ``scale``; powers the distributed
    CLIP path (all-gather columns, local rows, psum — see
    parallel/dist_loss.py) the way ``ntxent_partial_fused`` powers SimCLR.
    """
    br, bc = choose_blocks(z_rows.shape[0], z_cols.shape[0], z_rows.shape[1],
                           z_rows.dtype, block_rows, block_cols)
    if interpret is None:
        interpret = _default_interpret()
    return _ntxent_partial(z_rows, z_cols, row_gid.astype(jnp.int32),
                           jnp.asarray(scale, jnp.float32), 1.0, br, bc,
                           interpret, True)
