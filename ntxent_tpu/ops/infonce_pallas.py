"""Fused cross-modal InfoNCE (CLIP-style) on the shared Pallas kernel family.

The BASELINE.json configs[4] workload (CLIP text-image InfoNCE, global batch
32768) — the scale the reference's declared-but-absent NCCL path was named
for (SURVEY.md §2.2). Same blockwise online-softmax design as the NT-Xent
kernels (ops/ntxent_pallas.py): the (N, N) cross-modal similarity matrix
``s = scale * za @ zb.T`` is tiled into VMEM, never materialized in HBM, and
only the per-row/per-column logsumexp survives as the O(N) residual.

Differences from NT-Xent, expressed through the kernels' ``diag_pos`` mode:
positives sit on the a<->b diagonal (not at offset N) and the diagonal is NOT
masked (za_i / zb_i are different modalities, so s_ii is a real pair, not a
self-similarity). The loss is the symmetric cross-entropy
``0.5 * (mean_i [lse_row_i - s_ii] + mean_j [lse_col_j - s_jj])``
(= ops.oracle.info_nce_loss).

The logit scale is a **traced, differentiable** input (CLIP's learnable
``exp(logit_scale)``): it enters the kernels as a (1, 1) SMEM scalar and
multiplies the fp32 MXU product — same arithmetic as the oracle, and
d(loss)/d(scale) falls out of the row-gradient identity
``dL/dscale = sum_i (G @ zb)_i . za_i`` with no extra kernel pass.

Both passes walk the similarity matrix ONCE for BOTH softmax directions:

* forward (``_dual_fwd_kernel``): each s tile is produced once on the MXU
  and folded directly into the row direction's online-softmax stats and
  transposed into the column direction's — half the matmul work of running
  the one-direction forward twice;
* backward (``_dual_bwd_kernel``): one s recompute and one shared
  ``G = P_row + P_col - 2I`` tile drive both gradients
  (``G @ zb`` and ``G^T @ za``) — 3 matmuls per tile vs 4 for two
  one-direction VJPs, falling back to the two-pass form when the
  full-length accumulators exceed VMEM.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .blocks import VMEM_BUDGET_BYTES, choose_blocks
from .ntxent_pallas import (
    _NEG_INF,
    _bwd_sym_call,
    _default_interpret,
    _exp0,
    _log_l,
    _gid_column,
    _ntxent_partial,
    _pad_rows,
    _tile_ids,
)

__all__ = ["info_nce_fused", "info_nce_partial_fused",
           "info_nce_dual_partial", "resolve_scale"]


def resolve_scale(temperature: float, scale) -> jax.Array:
    """Logit scale as a traced fp32 scalar: ``scale`` if given, else 1/T."""
    if scale is None:
        scale = 1.0 / float(temperature)
    return jnp.asarray(scale, jnp.float32)


# ---------------------------------------------------------------------------
# Dual-direction kernels: ONE walk of s per pass, both softmax directions
# ---------------------------------------------------------------------------


def _dual_fwd_kernel(za_ref, zb_ref, scale_ref, loss_ref, lse_a_ref,
                     lse_b_ref, m_a, l_a, p_a, m_b, l_b, p_b,
                     *, br, bc, rows_actual, cols_actual,
                     stats_only=False):
    """Cross-modal forward: each s tile is produced ONCE on the MXU and
    folded into BOTH direction's online-softmax stats — the row direction
    (za rows over zb columns) directly, the column direction (zb rows over
    za columns, i.e. s.T) transposed. Halves the forward matmul work of
    running _fwd_kernel twice. Full-length stats live in VMEM scratch; a
    row block's stats complete when its grid row ends, a column block's
    when the grid's LAST row visits it.

    ``stats_only=True`` (static) strips the positive-logit accumulation
    and the SMEM loss folds: the distributed dual-partial path
    (_infonce_dual_local_fwd) wants ONLY the two lse vectors — its
    positives live on the global diagonal, so the local-iota positives
    this kernel would fold are meaningless there, and the two (br, bc)
    masked reductions per tile are pure wasted VPU work on that hot path.
    """
    i = pl.program_id(0)
    j = pl.program_id(1)
    ni = pl.num_programs(0)
    nj = pl.num_programs(1)

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _():
        loss_ref[0, 0] = jnp.float32(0.0)
        m_a[:] = jnp.full(m_a.shape, _NEG_INF, jnp.float32)
        l_a[:] = jnp.zeros(l_a.shape, jnp.float32)
        m_b[:] = jnp.full(m_b.shape, _NEG_INF, jnp.float32)
        l_b[:] = jnp.zeros(l_b.shape, jnp.float32)
        if not stats_only:
            p_a[:] = jnp.zeros(p_a.shape, jnp.float32)
            p_b[:] = jnp.zeros(p_b.shape, jnp.float32)

    rid, cid = _tile_ids(i, j, br, bc)
    s = jax.lax.dot_general(
        za_ref[:], zb_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale_ref[0, 0]
    # Cross-modal: the diagonal IS the positive; only padding is masked,
    # separately per direction (padded zb rows are fake columns of s,
    # padded za rows are fake columns of s.T).
    s_rowdir = jnp.where(cid >= cols_actual, _NEG_INF, s)
    s_coldir = jnp.where(rid >= rows_actual, _NEG_INF, s)

    rs = pl.ds(i * br, br)
    if not stats_only:
        pos_hit = cid == rid
        p_a[rs] += jnp.sum(jnp.where(pos_hit, s, 0.0), axis=1, keepdims=True)
    m_old = m_a[rs]
    m_new = jnp.maximum(m_old, jnp.max(s_rowdir, axis=1, keepdims=True))
    l_a[rs] = l_a[rs] * jnp.exp(m_old - m_new) + jnp.sum(
        _exp0(s_rowdir - m_new), axis=1, keepdims=True)
    m_a[rs] = m_new

    cs = pl.ds(j * bc, bc)
    st = s_coldir.T
    if not stats_only:
        p_b[cs] += jnp.sum(jnp.where(pos_hit, s, 0.0), axis=0).reshape(bc, 1)
    m_old_b = m_b[cs]
    m_new_b = jnp.maximum(m_old_b, jnp.max(st, axis=1, keepdims=True))
    l_b[cs] = l_b[cs] * jnp.exp(m_old_b - m_new_b) + jnp.sum(
        _exp0(st - m_new_b), axis=1, keepdims=True)
    m_b[cs] = m_new_b

    @pl.when(j == nj - 1)
    def _():
        lse = m_a[rs] + _log_l(l_a[rs])
        lse_a_ref[:] = lse
        if not stats_only:
            valid = (jax.lax.broadcasted_iota(jnp.int32, (br, 1), 0) + i * br
                     ) < rows_actual
            loss_ref[0, 0] += jnp.sum(jnp.where(valid, lse - p_a[rs], 0.0))

    # The (j, 0) output window is revisited every grid row; only its LAST
    # visit (final grid row) publishes complete column-side stats, and the
    # loss fold runs once there too.
    lse_b_ref[:] = m_b[cs] + _log_l(l_b[cs])

    if not stats_only:
        @pl.when(i == ni - 1)
        def _():
            validc = (jax.lax.broadcasted_iota(jnp.int32, (bc, 1), 0)
                      + j * bc) < cols_actual
            loss_ref[0, 0] += jnp.sum(
                jnp.where(validc, lse_b_ref[:] - p_b[cs], 0.0))


def _dual_fwd_call(zap, zbp, scale, *, br, bc, rows_actual, cols_actual,
                   interpret, stats_only=False):
    rp, d = zap.shape
    cp = zbp.shape[0]
    kernel = functools.partial(
        _dual_fwd_kernel, br=br, bc=bc,
        rows_actual=rows_actual, cols_actual=cols_actual,
        stats_only=stats_only,
    )
    loss_sum, lse_a, lse_b = pl.pallas_call(
        kernel,
        grid=(rp // br, cp // bc),
        in_specs=[
            pl.BlockSpec((br, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, 1), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((rp, 1), jnp.float32),
            jax.ShapeDtypeStruct((cp, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((rp, 1), jnp.float32)] * 3
        + [pltpu.VMEM((cp, 1), jnp.float32)] * 3,
        cost_estimate=pl.CostEstimate(
            flops=2 * rp * cp * d,
            bytes_accessed=(rp * d + (rp // br) * cp * d) * zap.dtype.itemsize,
            transcendentals=2 * rp * cp,
        ),
        interpret=interpret,
    )(zap, zbp, jnp.asarray(scale, jnp.float32).reshape(1, 1))
    return loss_sum[0, 0], lse_a, lse_b


def _dual_bwd_kernel(za_ref, zb_ref, gid_ref, scale_ref, lse_a_ref,
                     lse_bt_ref, grad_a_ref, grad_b_ref, acc_a, acc_b,
                     *, br, bc, rows_actual, cols_actual):
    """Cross-modal backward: ONE s recompute and ONE shared G per tile
    drive both gradients — ``acc_a[i] += G @ zb_j`` and
    ``acc_b[j] += G^T @ za_i`` (G is the total dL/ds, so its transpose is
    exactly the other operand's gradient matrix). 3 matmuls per tile vs 4
    for two independent one-direction backward passes.

    Row identity comes from the ``gid_ref`` operand (global row ids,
    sentinel >= rows_actual on padded rows): the symmetric case passes
    [0..n), the distributed dual-partial case its shard's global ids —
    positives sit at ``cid == gid``.
    """
    i = pl.program_id(0)
    j = pl.program_id(1)
    ni = pl.num_programs(0)
    nj = pl.num_programs(1)

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _():
        acc_a[:] = jnp.zeros(acc_a.shape, acc_a.dtype)
        acc_b[:] = jnp.zeros(acc_b.shape, acc_b.dtype)

    rid = gid_ref[:]                                  # (BR, 1) global ids
    _, cid = _tile_ids(i, j, br, bc)
    s = jax.lax.dot_general(
        za_ref[:], zb_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale_ref[0, 0]
    p_row = _exp0(jnp.where(cid >= cols_actual, _NEG_INF, s)
                  - lse_a_ref[:])
    p_col = _exp0(jnp.where(rid >= rows_actual, _NEG_INF, s)
                  - lse_bt_ref[:])
    pos = (cid == rid).astype(jnp.float32)
    valid_row = (rid < rows_actual).astype(jnp.float32)
    valid_col = (cid < cols_actual).astype(jnp.float32)
    g = (p_row - pos) * valid_row + (p_col - pos) * valid_col

    rs = pl.ds(i * br, br)
    acc_a[rs] += jax.lax.dot_general(
        g, zb_ref[:].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    cs = pl.ds(j * bc, bc)
    acc_b[cs] += jax.lax.dot_general(
        g, za_ref[:].astype(jnp.float32),
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == nj - 1)
    def _():
        grad_a_ref[:] = acc_a[rs]

    @pl.when(i == ni - 1)
    def _():
        grad_b_ref[:] = acc_b[cs]


def _dual_bwd_call(zap, zbp, row_gid, scale, lse_a, lse_b, *, br, bc,
                   rows_actual, cols_actual, interpret):
    rp, d = zap.shape
    cp = zbp.shape[0]
    kernel = functools.partial(
        _dual_bwd_kernel, br=br, bc=bc,
        rows_actual=rows_actual, cols_actual=cols_actual,
    )
    grad_a, grad_b = pl.pallas_call(
        kernel,
        grid=(rp // br, cp // bc),
        in_specs=[
            pl.BlockSpec((br, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bc), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, d), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, d), jnp.float32),
            jax.ShapeDtypeStruct((cp, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((rp, d), jnp.float32),
            pltpu.VMEM((cp, d), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=6 * rp * cp * d,  # 3 matmuls/tile at 2 flops per MAC
            bytes_accessed=(2 * rp * d + 2 * cp * d) * 4,
            transcendentals=2 * rp * cp,
        ),
        interpret=interpret,
    )(zap, zbp, row_gid, jnp.asarray(scale, jnp.float32).reshape(1, 1),
      lse_a, lse_b.reshape(1, cp))
    return grad_a, grad_b


def _dual_bwd_fits(rp: int, cp: int, d: int, br: int, bc: int) -> bool:
    """Do both full-length fp32 accumulators plus the tile working set fit
    the VMEM budget?"""
    working = (rp + cp) * d * 4 + (2 * br + 2 * bc) * d * 4 + br * bc * 4
    return working <= VMEM_BUDGET_BYTES


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _infonce(za, zb, scale, br, bc, interpret):
    return _infonce_fwd(za, zb, scale, br, bc, interpret)[0]


def _infonce_prepare(za, zb, br, bc):
    n = za.shape[0]
    pad = math.lcm(br, bc)  # each side serves as both rows and columns
    zap = _pad_rows(za, pad)
    zbp = _pad_rows(zb, pad)
    gid = _gid_column(jnp.arange(zap.shape[0]), pad, sentinel=n)
    return zap, zbp, gid, n


def _infonce_fwd(za, zb, scale, br, bc, interpret):
    n = za.shape[0]
    zap = _pad_rows(za, br)
    zbp = _pad_rows(zb, bc)
    loss_sum, lse_a, lse_b = _dual_fwd_call(
        zap, zbp, scale, br=br, bc=bc,
        rows_actual=n, cols_actual=n, interpret=interpret)
    loss = loss_sum / (2 * n)
    # Residuals trimmed to n: each backward path re-pads for its own tiling
    # (zero lse on padded entries is safe — their g contributions are
    # masked by valid_row/valid_col either way).
    return loss, (za, zb, scale, lse_a[:n, 0], lse_b[:n, 0])


def _infonce_bwd(br, bc, interpret, res, g):
    from .blocks import round_up

    za, zb, scale, lse_a, lse_b = res
    n, d = za.shape
    rp, cp = round_up(n, br), round_up(n, bc)
    lse_a = lse_a.reshape(n, 1)
    lse_b = lse_b.reshape(n, 1)
    if _dual_bwd_fits(rp, cp, d, br, bc):
        # o_a[i] = sum_j G_ij zb_j with G = P_row + P_col - 2I (the total
        # dL/ds before scale/normalization); o_b[j] = sum_i G_ij za_i.
        # One s recompute + one shared G per tile drives both.
        o_a, o_b = _dual_bwd_call(
            _pad_rows(za, br), _pad_rows(zb, bc),
            _gid_column(jnp.arange(n), br, sentinel=n), scale,
            _pad_rows(lse_a, br), _pad_rows(lse_b, bc), br=br, bc=bc,
            rows_actual=n, cols_actual=n, interpret=interpret)
        o_a, o_b = o_a[:n], o_b[:n]
    else:
        # Accumulators don't fit VMEM at this (N, D): two one-direction
        # passes over the shared rectangular backward kernel instead.
        zap2, zbp2, gid, _ = _infonce_prepare(za, zb, br, bc)
        lse_ap = _pad_rows(lse_a, zap2.shape[0])
        lse_bp = _pad_rows(lse_b, zap2.shape[0])
        common = dict(br=br, bc=bc, inv_t=1.0, cols_actual=n, n_half=0,
                      interpret=interpret, diag_pos=True, scale=scale)
        o_a = _bwd_sym_call(zap2, gid, lse_ap, z_cols=zbp2, lse_cols=lse_bp,
                            **common)[:n]
        o_b = _bwd_sym_call(zbp2, gid, lse_bp, z_cols=zap2, lse_cols=lse_ap,
                            **common)[:n]
    coef = g / (2 * n)
    grad_za = (o_a * (coef * scale)).astype(za.dtype)
    grad_zb = (o_b * (coef * scale)).astype(zb.dtype)
    # dL/dscale = coef * sum_ij G_ij (za_i . zb_j) = coef * sum_i o_a[i].za[i]
    grad_scale = (coef * jnp.sum(o_a * za.astype(jnp.float32))).reshape(
        jnp.shape(scale)).astype(scale.dtype)
    return grad_za, grad_zb, grad_scale


_infonce.defvjp(_infonce_fwd, _infonce_bwd)


def info_nce_fused(
    za: jax.Array,
    zb: jax.Array,
    temperature: float = 0.07,
    *,
    scale: jax.Array | float | None = None,
    block_rows: int | None = None,
    block_cols: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused symmetric InfoNCE over paired embeddings za, zb: (N, D) each.

    Drop-in fused equivalent of ``ops.oracle.info_nce_loss`` — same
    semantics, O(N) memory, exact gradients for za, zb AND the logit scale.
    Pass ``scale`` (= 1/T, e.g. CLIP's learnable ``exp(logit_scale)``) as a
    traced array to train it; otherwise ``temperature`` is used.
    """
    if za.shape != zb.shape:
        raise ValueError(f"paired embeddings must match: {za.shape} vs {zb.shape}")
    scale = resolve_scale(temperature, scale)
    br, bc = choose_blocks(za.shape[0], za.shape[0], za.shape[1], za.dtype,
                           block_rows, block_cols)
    if interpret is None:
        interpret = _default_interpret()
    return _infonce(za, zb, scale, br, bc, interpret)


# ---------------------------------------------------------------------------
# Distributed dual-partial: one matmul pass per device, both directions
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _infonce_dual_local(za_local, zb_g, row_gid, scale, axis, br, bc,
                        interpret):
    """Per-device symmetric-InfoNCE partial SUM (call inside shard_map).

    ONE tile walk of this device's local-rows x global-cols block of
    ``s = scale * za @ zb.T`` yields the local row logsumexp AND this
    device's partial column statistics; the global column logsumexp is a
    cheap cross-device merge (pmax/psum over an (N,) vector) instead of a
    second all-gather + matmul pass. The two-pass path
    (``local_infonce_allgather``) gathers BOTH modalities and walks two
    blocks; this walks one and gathers one.

    Returns ``sum_local_i (lse_row_i - s_ii) + sum_local_i
    (lse_col_gid(i) - s_ii)`` — psum across devices and divide by 2N for
    the mean loss. Gradients are hand-derived (the combined
    ``G = P_row + P_col - 2I`` identity): za_local's flows directly,
    zb_g's partial flows back through the caller's all_gather as a
    reduce-scatter, and the scale's partial is psum'd by shard_map AD.
    """
    return _infonce_dual_local_fwd(za_local, zb_g, row_gid, scale, axis,
                                   br, bc, interpret)[0]


def _infonce_dual_local_fwd(za_local, zb_g, row_gid, scale, axis, br, bc,
                            interpret):
    n_local = za_local.shape[0]
    n = zb_g.shape[0]
    zap = _pad_rows(za_local, br)
    zbp = _pad_rows(zb_g, bc)
    # Stats-only dual forward: the kernel's in-kernel positives are
    # local-iota (meaningless here — positives are the global diagonal,
    # recovered below from a rowwise dot), so the flag strips their
    # accumulation and the loss folds from this hot path entirely.
    _, lse_a_p, lse_b_p = _dual_fwd_call(
        zap, zbp, scale, br=br, bc=bc,
        rows_actual=n_local, cols_actual=n, interpret=interpret,
        stats_only=True)
    lse_a = lse_a_p[:n_local, 0]
    lse_b_part = lse_b_p[:n, 0]
    # Global column logsumexp: logsumexp-merge of the per-device partial
    # stats — an (N,) collective, not a matmul. Routed through the mesh
    # shims so the comms accounting sees it (imported at call time:
    # trace-time only, and it keeps this ops module import-order-neutral
    # with the parallel package that imports it).
    from ..parallel.mesh import pmax as _pmax_acct
    from ..parallel.mesh import psum as _psum_acct

    m = _pmax_acct(lse_b_part, axis)
    lse_b = m + jnp.log(_psum_acct(jnp.exp(lse_b_part - m), axis))
    # Positive logits s_ii for the local pairs: zb row gid(i) gathered from
    # the already-present zb_g.
    pos = scale * jnp.sum(
        za_local.astype(jnp.float32)
        * jnp.take(zb_g, row_gid, axis=0).astype(jnp.float32), axis=1)
    loss_part = jnp.sum(lse_a - pos) + jnp.sum(
        jnp.take(lse_b, row_gid) - pos)
    return loss_part, (za_local, zb_g, row_gid, scale, lse_a, lse_b)


def _infonce_dual_local_bwd(axis, br, bc, interpret, res, g):
    from .ntxent_pallas import _bwd_sym_call, _bwd_sym_cols_call

    za_local, zb_g, row_gid, scale, lse_a, lse_b = res
    n_local, d = za_local.shape
    n = zb_g.shape[0]
    zap = _pad_rows(za_local, br)
    zbp = _pad_rows(zb_g, bc)
    gid_col = _gid_column(row_gid, br, sentinel=n)
    lse_ap = _pad_rows(lse_a.reshape(n_local, 1), br)
    lse_bp = _pad_rows(lse_b.reshape(n, 1), bc)
    # o_a = G @ zb over local rows; o_b_partial = G^T @ za over ALL columns
    # (this device's row contribution — shard_map AD of the caller's
    # all_gather psums it into the true zb gradient, i.e. reduce-scatter).
    if _dual_bwd_fits(zap.shape[0], zbp.shape[0], d, br, bc):
        # Shared-G kernel: one s recompute + two grad dots per tile.
        o_a, o_b = _dual_bwd_call(
            zap, zbp, gid_col, scale, lse_ap, lse_bp, br=br, bc=bc,
            rows_actual=n, cols_actual=n, interpret=interpret)
        o_a, o_b = o_a[:n_local], o_b[:n]
    else:
        # Accumulators exceed VMEM (large gathered N x D): two passes,
        # each rebuilding G for its own output side.
        common = dict(br=br, bc=bc, inv_t=1.0, cols_actual=n, n_half=0,
                      interpret=interpret, diag_pos=True, scale=scale)
        o_a = _bwd_sym_call(zap, gid_col, lse_ap, z_cols=zbp,
                            lse_cols=lse_bp, **common)[:n_local]
        o_b = _bwd_sym_cols_call(zap, zbp, gid_col, lse_ap, lse_bp,
                                 **common)[:n]
    grad_za = (o_a * (g * scale)).astype(za_local.dtype)
    grad_zb = (o_b * (g * scale)).astype(zb_g.dtype)
    grad_scale = (g * jnp.sum(o_a * za_local.astype(jnp.float32))).reshape(
        jnp.shape(scale)).astype(scale.dtype)
    return grad_za, grad_zb, None, grad_scale


_infonce_dual_local.defvjp(_infonce_dual_local_fwd, _infonce_dual_local_bwd)


def info_nce_dual_partial(
    za_local: jax.Array,
    zb_g: jax.Array,
    row_gid: jax.Array,
    axis: str,
    *,
    scale: jax.Array | float = 1.0,
    block_rows: int | None = None,
    block_cols: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Both-direction partial InfoNCE **sum** from ONE similarity walk.

    For use inside shard_map: ``za_local`` (local rows), ``zb_g`` (the
    all-gathered other modality), ``row_gid`` the local rows' global ids,
    ``axis`` the mesh axis for the column-stat merge collectives. See
    ``parallel.dist_loss.local_infonce_dual`` for the assembled loss.
    """
    br, bc = choose_blocks(za_local.shape[0], zb_g.shape[0],
                           za_local.shape[1], za_local.dtype,
                           block_rows, block_cols)
    if interpret is None:
        interpret = _default_interpret()
    return _infonce_dual_local(za_local, zb_g,
                               row_gid.astype(jnp.int32),
                               jnp.asarray(scale, jnp.float32), axis, br, bc,
                               interpret)


def info_nce_partial_fused(
    z_rows: jax.Array,
    z_cols: jax.Array,
    row_gid: jax.Array,
    *,
    scale: jax.Array | float = 1.0,
    block_rows: int | None = None,
    block_cols: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """One-direction partial InfoNCE **sum** over rows of the global matrix.

    Returns ``sum_i [logsumexp_j s_ij - s_i,gid(i)]`` where
    ``s = scale * z_rows @ z_cols.T`` and the positive of local row i is
    global column ``row_gid[i]`` — the diagonal of the global matrix.
    Differentiable w.r.t. both operands and ``scale``; powers the distributed
    CLIP path (all-gather columns, local rows, psum — see
    parallel/dist_loss.py) the way ``ntxent_partial_fused`` powers SimCLR.
    """
    br, bc = choose_blocks(z_rows.shape[0], z_cols.shape[0], z_rows.shape[1],
                           z_rows.dtype, block_rows, block_cols)
    if interpret is None:
        interpret = _default_interpret()
    return _ntxent_partial(z_rows, z_cols, row_gid.astype(jnp.int32),
                           jnp.asarray(scale, jnp.float32), 1.0, br, bc,
                           interpret, True)
