from ntxent_tpu.ops import oracle
from ntxent_tpu.ops.autotune import autotune_attention_blocks, autotune_blocks
from ntxent_tpu.ops.blocks import choose_blocks
from ntxent_tpu.ops.attention_pallas import flash_attention
from ntxent_tpu.ops.infonce_pallas import info_nce_fused, info_nce_partial_fused
from ntxent_tpu.ops.ntxent_pallas import (
    ntxent_loss_and_lse,
    ntxent_loss_fused,
    ntxent_partial_fused,
)

__all__ = [
    "oracle",
    "choose_blocks",
    "autotune_attention_blocks",
    "autotune_blocks",
    "ntxent_loss_fused",
    "ntxent_loss_and_lse",
    "ntxent_partial_fused",
    "info_nce_fused",
    "info_nce_partial_fused",
    "flash_attention",
]
