"""Long-context transformer tower: attention as a pluggable function.

The stock towers (models/vit.py) use flax's fused attention — right for
L ≤ a few hundred (ViT-B/16 at 224px has L = 197, where the (L, L)
matrix is trivia). For sequences where L or L² is the constraint, this
module factors the attention CALL out of the architecture so the same
parameters run under any of the framework's attention decompositions
(parallel/ring_attention.py):

* single chip, moderate L     -> ``attention_oracle`` (exact, simple)
* single chip, long L         -> ``blockwise_attention`` (flash-style
                                  lax.scan folds, no (L, L) materialized)
* mesh, sequence-sharded      -> ``make_ring_attention(mesh)`` or
                                  ``make_ulysses_attention(mesh)``

All four are the same mathematical function (tests pin model outputs AND
parameter gradients for every plan), so a checkpoint trained under one
runs under the others — the parallelism decision is a RUNTIME choice,
not an architecture fork.
shard_map attention composes inside jit: annotate the inputs sequence-
sharded and GSPMD partitions the pointwise/Dense ops around the explicit
ring/all-to-all collectives.

Follows the towers' conventions (vit.py): bf16 activations / fp32
params, fp32 LayerNorm, pre-norm blocks.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..parallel.ring_attention import attention_oracle
from .vit import MlpBlock

AttentionFn = Callable[..., jnp.ndarray]  # (q, k, v) -> out, all (B,L,H,D)


def default_attention() -> AttentionFn:
    """Backend auto-selection (same policy as the trainer's use_fused):
    the fused flash kernel where it compiles natively (TPU), the exact
    jnp oracle elsewhere (identical function; interpret-mode Pallas off
    TPU is ~100x slower and measures nothing).

    Measured basis for the unconditional-on-TPU choice (v5e A/B,
    benchmark_results/tpu/attention_ab.json): flash ties XLA's own
    fusion at L=1024 (0.96-1.13x), wins 1.5x at 4096 causal, and wins
    23-31x at 8192 where XLA spills the materialized score matrix — no
    length regime favors the oracle enough to warrant a crossover."""
    from ..utils.capability import is_tpu_backend

    if is_tpu_backend():
        from ..ops.attention_pallas import flash_attention

        return flash_attention
    return attention_oracle


class SeqParallelSelfAttention(nn.Module):
    """QKV projection + pluggable attention call + output projection.

    ``attention_fn`` consumes/produces (B, L, H, D); every projection here
    is pointwise over L, so under a sequence-sharded input GSPMD keeps
    them local and only ``attention_fn``'s own collectives move data.
    """

    num_heads: int
    dtype: jnp.dtype = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None  # None -> default_attention()

    @nn.compact
    def __call__(self, x):
        b, l, hidden = x.shape
        if hidden % self.num_heads:
            raise ValueError(
                f"hidden {hidden} not divisible by heads {self.num_heads}")
        head_dim = hidden // self.num_heads

        def proj(name):
            return nn.DenseGeneral(
                (self.num_heads, head_dim), axis=-1, dtype=self.dtype,
                param_dtype=jnp.float32, name=name)(x)

        attention_fn = self.attention_fn or default_attention()
        out = attention_fn(proj("query"), proj("key"), proj("value"))
        return nn.DenseGeneral(
            hidden, axis=(-2, -1), dtype=self.dtype,
            param_dtype=jnp.float32, name="out")(out)


class LongContextBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        y = SeqParallelSelfAttention(
            num_heads=self.num_heads, dtype=self.dtype,
            attention_fn=self.attention_fn)(y)
        x = x + y
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        return x + MlpBlock(self.mlp_dim, self.dtype)(y)


class LongContextTransformer(nn.Module):
    """Token-sequence tower for sequences beyond single-chip attention.

    Maps (B, L) int tokens -> (B, L, hidden) contextual features (mean-
    pool or slice downstream as the objective needs). Same parameter tree
    regardless of ``attention_fn`` — swap the decomposition at load time.
    """

    vocab_size: int
    hidden_dim: int = 512
    depth: int = 8
    num_heads: int = 8
    mlp_dim: int = 2048
    max_len: int = 32768
    dtype: jnp.dtype = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None  # None -> default_attention()

    def setup(self):
        # Explicit names reproduce the original nn.compact auto-names, so
        # the parameter tree (and every existing checkpoint/test) is
        # byte-identical to the pre-setup() module.
        self.token_embed = nn.Embed(self.vocab_size, self.hidden_dim,
                                    param_dtype=jnp.float32,
                                    dtype=self.dtype, name="Embed_0")
        self.pos_embedding = self.param(
            "pos_embedding", nn.initializers.normal(0.02),
            (1, self.max_len, self.hidden_dim), jnp.float32)
        self.blocks = [
            LongContextBlock(
                num_heads=self.num_heads, mlp_dim=self.mlp_dim,
                dtype=self.dtype, attention_fn=self.attention_fn,
                name=f"LongContextBlock_{i}")
            for i in range(self.depth)]
        self.out_ln = nn.LayerNorm(dtype=jnp.float32, name="LayerNorm_0")

    def embed(self, tokens):
        """(B, L) tokens -> (B, L, hidden) embedded + positioned acts
        (the pre-pipeline stage of the pipelined forward)."""
        _, l = tokens.shape
        if l > self.max_len:
            raise ValueError(
                f"sequence length {l} exceeds max_len {self.max_len} "
                f"(raise max_len — it sizes the position table)")
        x = self.token_embed(tokens)
        return x + self.pos_embedding[:, :l].astype(self.dtype)

    def head(self, x):
        """Final norm (the post-pipeline stage of the pipelined forward)."""
        return self.out_ln(x)

    def __call__(self, tokens):
        x = self.embed(tokens)
        for blk in self.blocks:
            x = blk(x)
        return self.head(x)


def make_pipelined_apply(model: LongContextTransformer, mesh, *,
                         num_microbatches: int, axis: str = "stage",
                         data_axis: str | None = None,
                         remat: bool = False):
    """Pipeline-parallel forward for the long-context tower.

    Returns ``fn(variables, tokens) -> (B, L, hidden)`` equal to
    ``model.apply`` but with the block stack executed as a GPipe pipeline
    over ``mesh[axis]`` (parallel/pp.py): each device holds
    ``depth / num_stages`` blocks' weights, activations hand off over
    ppermute, embedding and final norm run replicated outside the
    pipeline. Same parameter tree as the plain forward — pipelining is a
    RUNTIME choice, exactly like the attention decomposition above.

    ``model.attention_fn`` must be a plain function (oracle / blockwise /
    flash) — a shard_map-based plan (ring/Ulysses) cannot nest inside the
    pipeline's own shard_map body.
    """
    from ..parallel.pp import make_gpipe, pipeline_stage_params

    num_stages = mesh.shape[axis]
    if model.depth % num_stages:
        raise ValueError(f"depth {model.depth} does not split over "
                         f"{num_stages} stages")
    blk = LongContextBlock(num_heads=model.num_heads,
                           mlp_dim=model.mlp_dim, dtype=model.dtype,
                           attention_fn=model.attention_fn)

    def stage_fn(stage_params, acts):
        def one(a, p):
            return blk.apply({"params": p}, a), None
        out, _ = jax.lax.scan(one, acts, stage_params)
        return out

    pipe = make_gpipe(stage_fn, mesh, num_microbatches=num_microbatches,
                      axis=axis, data_axis=data_axis, remat=remat)

    def apply(variables, tokens):
        stacked, rest = pipeline_stage_params(
            variables["params"], num_stages,
            block_prefix="LongContextBlock_")
        x = model.apply({"params": rest}, tokens,
                        method=LongContextTransformer.embed)
        x = pipe(stacked, x)
        return model.apply({"params": rest}, x,
                           method=LongContextTransformer.head)

    return apply
