"""CLIP-style dual encoder for cross-modal InfoNCE.

Workload named by BASELINE.json configs[4] (CLIP text-image InfoNCE, global
batch 32768). Image tower: any encoder from models/ (ResNet or ViT); text
tower: a small causal-free transformer over token ids with EOT pooling.
The loss is ``ops.oracle.info_nce_loss`` (or its distributed/ring analogs)
on the two L2-normalized embeddings plus a learnable logit scale.
"""

from __future__ import annotations

from collections.abc import Callable

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..ops.oracle import cosine_normalize
from .vit import EncoderBlock

__all__ = ["TextTransformer", "CLIPModel"]


class TextTransformer(nn.Module):
    vocab_size: int = 49408
    max_len: int = 77
    hidden_dim: int = 512
    depth: int = 12
    num_heads: int = 8
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        b, t = tokens.shape
        x = nn.Embed(self.vocab_size, self.hidden_dim,
                     param_dtype=jnp.float32, dtype=self.dtype)(tokens)
        pos = self.param("pos_embed", nn.initializers.normal(stddev=0.01),
                         (1, self.max_len, self.hidden_dim), jnp.float32)
        x = x + pos[:, :t].astype(self.dtype)
        # Causal mask (CLIP-standard): keeps the EOT feature independent of
        # trailing pad tokens — position i attends only to positions <= i.
        causal = nn.make_causal_mask(tokens)
        for i in range(self.depth):
            x = EncoderBlock(self.num_heads, self.hidden_dim * 4, self.dtype,
                             name=f"block_{i}")(x, mask=causal)
        x = nn.LayerNorm(dtype=jnp.float32, name="final_ln")(x)
        # EOT pooling: feature at each sequence's last non-pad position
        # (pad id assumed 0; argmax of position*mask finds the last token).
        mask = (tokens != 0).astype(jnp.int32)
        last = jnp.maximum(jnp.sum(mask, axis=1) - 1, 0)
        return x[jnp.arange(b), last].astype(jnp.float32)


class CLIPModel(nn.Module):
    """Dual encoder -> (image_embeds, text_embeds, logit_scale)."""

    image_encoder: Callable[..., nn.Module]
    text_encoder: Callable[..., nn.Module] = TextTransformer
    embed_dim: int = 512

    def setup(self):
        self.image_tower = self.image_encoder()
        self.text_tower = self.text_encoder()
        self.image_proj = nn.Dense(self.embed_dim, use_bias=False,
                                   param_dtype=jnp.float32, name="image_proj")
        self.text_proj = nn.Dense(self.embed_dim, use_bias=False,
                                  param_dtype=jnp.float32, name="text_proj")
        # CLIP-standard init: temperature 0.07 as log scale, clamped in loss.
        self.logit_scale = self.param(
            "logit_scale",
            lambda key: jnp.asarray(np.log(1.0 / 0.07), jnp.float32),
        )

    def __call__(self, images, tokens, train: bool = True):
        zi = cosine_normalize(self.image_proj(self.image_tower(images, train=train)))
        zt = cosine_normalize(self.text_proj(self.text_tower(tokens, train=train)))
        scale = jnp.clip(jnp.exp(self.logit_scale), 0.0, 100.0)
        return zi, zt, scale

    def encode_image(self, images):
        return cosine_normalize(
            self.image_proj(self.image_tower(images, train=False)))

    def encode_text(self, tokens):
        return cosine_normalize(
            self.text_proj(self.text_tower(tokens, train=False)))
