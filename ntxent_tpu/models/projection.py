"""SimCLR projection head and full contrastive model wrappers.

SimCLR (Chen et al. 2020) applies a small MLP g(.) on encoder features and
computes NT-Xent on its L2-normalized output — the (2N, D) embeddings the
reference's kernel consumed as its input `z` (ntxent_kernel.cuh:31-35).
"""

from __future__ import annotations

from collections.abc import Callable

import flax.linen as nn
import jax.numpy as jnp

from ..ops.oracle import cosine_normalize

__all__ = ["ProjectionHead", "SimCLRModel"]


class ProjectionHead(nn.Module):
    """2-layer MLP (hidden -> BN+ReLU -> out), SimCLR-standard."""

    hidden_dim: int = 2048
    out_dim: int = 128
    dtype: jnp.dtype = jnp.bfloat16
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Dense(self.hidden_dim, dtype=self.dtype,
                     param_dtype=jnp.float32, name="fc1")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         use_fast_variance=False,
                         dtype=self.dtype, param_dtype=jnp.float32,
                         axis_name=self.axis_name if train else None,
                         name="bn1")(x)
        x = nn.relu(x)
        x = nn.Dense(self.out_dim, use_bias=False, dtype=self.dtype,
                     param_dtype=jnp.float32, name="fc2")(x)
        return x.astype(jnp.float32)


class SimCLRModel(nn.Module):
    """Encoder + projection head -> L2-normalized contrastive embeddings."""

    encoder: Callable[..., nn.Module]
    proj_hidden_dim: int = 2048
    proj_dim: int = 128
    axis_name: str | None = None
    dtype: jnp.dtype = jnp.bfloat16  # projection-head compute dtype

    def setup(self):
        self.backbone = self.encoder()
        self.projector = ProjectionHead(
            hidden_dim=self.proj_hidden_dim, out_dim=self.proj_dim,
            axis_name=self.axis_name, dtype=self.dtype,
        )

    def __call__(self, x, train: bool = True):
        h = self.backbone(x, train=train)
        z = self.projector(h, train=train)
        return cosine_normalize(z)

    def features(self, x, train: bool = False):
        """Encoder features for linear evaluation (no projection)."""
        return self.backbone(x, train=train)
