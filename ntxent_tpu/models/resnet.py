"""Flax ResNet (v1.5) encoders for SimCLR pretraining.

The reference framework names SimCLR but contains no model code (SURVEY.md
§0.2); BASELINE.json's north star specifies ResNet-50 SimCLR pretraining
(configs[1-2]). This is a TPU-first implementation:

* NHWC layout (TPU conv-native) with bf16 activations / fp32 params and
  fp32 batch-norm statistics.
* ``axis_name``-aware BatchNorm: pass the mesh data axis to get cross-replica
  (global) batch statistics — the distributed-BN SimCLR needs at large batch
  (hand-rolled as SyncBN/NCCL elsewhere; here it is one argument, lowered to
  an XLA psum over ICI).
* stride-2 3x3 in the bottleneck's middle conv (v1.5), SimCLR-standard.
"""

from __future__ import annotations

from collections.abc import Callable, Sequence
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101",
           "ResNet152", "ResNet50x2"]

ModuleDef = Callable


def _space_to_depth(x, block: int = 2):
    """(B, H, W, C) -> (B, H/b, W/b, b*b*C), channel-major in (a, b, c)."""
    B, H, W, C = x.shape
    x = x.reshape(B, H // block, block, W // block, block, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        B, H // block, W // block, block * block * C)


class SpaceToDepthStem(nn.Module):
    """The ImageNet 7x7/stride-2 stem conv, executed MXU-friendly.

    A 7x7/s2 conv on a 3-channel image uses 3 of the MXU's 128 input
    lanes per tap — the single most padding-wasteful op in ResNet. The
    MLPerf-TPU transform: space-to-depth the image by 2 (H/2, W/2, 12)
    and run the EXACT same linear map as a 4x4/stride-1 conv whose
    kernel is the 7x7 kernel zero-padded to 8x8 and phase-grouped.

    Weight-compatible by construction: the parameter stays the standard
    (7, 7, C, width) kernel (checkpoints interchange with the plain
    stem); the pad + phase-group runs per apply and costs O(64*49*C)
    elementwise work. Equivalence is pinned by
    tests/test_models.py::test_s2d_stem_equivalence.
    """

    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        B, H, W, C = x.shape
        if H % 2 or W % 2:
            raise ValueError(f"space-to-depth stem needs even H/W, got "
                             f"{(H, W)}")
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (7, 7, C, self.features),
            jnp.float32)
        # W'[ki, kj, (a, b, c), o] = W[2ki + a, 2kj + b, c, o] (zero at
        # the padded 8th row/col): same taps, phase-major channel order
        # matching _space_to_depth's (a, b, c) layout.
        w = jnp.pad(kernel, ((0, 1), (0, 1), (0, 0), (0, 0)))
        w = w.reshape(4, 2, 4, 2, C, self.features)
        w = w.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * C, self.features)
        xs = _space_to_depth(x.astype(self.dtype), 2)
        # SAME at k=4/s1 pads (1, 2) — exactly the s2d image of the
        # original SAME (2, 3) padding at k=7/s2.
        return jax.lax.conv_general_dilated(
            xs, w.astype(self.dtype), window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides,) * 2)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 strides=(self.strides,) * 2,
                                 name="proj_conv")(residual)
            residual = self.norm(name="proj_bn")(residual)
        return self.act(y + residual)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides,) * 2)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 strides=(self.strides,) * 2,
                                 name="proj_conv")(residual)
            residual = self.norm(name="proj_bn")(residual)
        return self.act(y + residual)


class ResNet(nn.Module):
    """Returns pooled (B, width*512*expansion-ish) features — no classifier.

    ``axis_name``: mesh axis for cross-replica BN statistics (None = local).
    ``small_images``: CIFAR stem (3x3/1 conv, no maxpool) vs ImageNet stem.
    """

    stage_sizes: Sequence[int]
    block_cls: type = BottleneckBlock
    width_multiplier: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    axis_name: str | None = None
    small_images: bool = False
    stem: str = "conv"  # "conv" | "space_to_depth" (ImageNet stem only)
    # False = two-pass variance (subtract mean, then square): the
    # conservative numerics default. True = flax/XLA's one-pass
    # E[x^2]-E[x]^2 — halves the BN reduction bandwidth across the
    # network's 53 norms (an RN50 MFU lever; A/B'd on-chip before any
    # default change, same policy as the kernel defaults).
    bn_fast_variance: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       use_fast_variance=self.bn_fast_variance,
                       param_dtype=jnp.float32,
                       axis_name=self.axis_name if train else None)
        act = nn.relu

        if self.stem not in ("conv", "space_to_depth"):
            raise ValueError(f"unknown stem {self.stem!r}: expected 'conv' "
                             "or 'space_to_depth'")
        if self.small_images and self.stem != "conv":
            # The CIFAR stem replaces the ImageNet stem entirely, so a
            # non-default stem choice would be silently ignored here —
            # fail loudly instead (same check the CLI makes; ADVICE r3 #3).
            raise ValueError(f"stem={self.stem!r} requires the ImageNet "
                             "stem; small_images=True uses the 3x3 CIFAR "
                             "stem and would silently ignore it")
        x = x.astype(self.dtype)
        width = 64 * self.width_multiplier
        if self.small_images:
            x = conv(width, (3, 3), name="stem_conv")(x)
        elif self.stem == "space_to_depth":
            x = SpaceToDepthStem(width, dtype=self.dtype,
                                 name="stem_conv")(x)
        else:
            x = conv(width, (7, 7), strides=(2, 2), name="stem_conv")(x)
        x = norm(name="stem_bn")(x)
        x = act(x)
        if not self.small_images:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=width * 2**i, strides=strides,
                    conv=conv, norm=norm, act=act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock)
ResNet50x2 = partial(ResNet, stage_sizes=(3, 4, 6, 3),
                     block_cls=BottleneckBlock, width_multiplier=2)
