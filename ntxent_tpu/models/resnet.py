"""Flax ResNet (v1.5) encoders for SimCLR pretraining.

The reference framework names SimCLR but contains no model code (SURVEY.md
§0.2); BASELINE.json's north star specifies ResNet-50 SimCLR pretraining
(configs[1-2]). This is a TPU-first implementation:

* NHWC layout (TPU conv-native) with bf16 activations / fp32 params and
  fp32 batch-norm statistics.
* ``axis_name``-aware BatchNorm: pass the mesh data axis to get cross-replica
  (global) batch statistics — the distributed-BN SimCLR needs at large batch
  (hand-rolled as SyncBN/NCCL elsewhere; here it is one argument, lowered to
  an XLA psum over ICI).
* stride-2 3x3 in the bottleneck's middle conv (v1.5), SimCLR-standard.
"""

from __future__ import annotations

from collections.abc import Callable, Sequence
from functools import partial

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101",
           "ResNet152", "ResNet50x2"]

ModuleDef = Callable


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides,) * 2)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 strides=(self.strides,) * 2,
                                 name="proj_conv")(residual)
            residual = self.norm(name="proj_bn")(residual)
        return self.act(y + residual)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides,) * 2)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 strides=(self.strides,) * 2,
                                 name="proj_conv")(residual)
            residual = self.norm(name="proj_bn")(residual)
        return self.act(y + residual)


class ResNet(nn.Module):
    """Returns pooled (B, width*512*expansion-ish) features — no classifier.

    ``axis_name``: mesh axis for cross-replica BN statistics (None = local).
    ``small_images``: CIFAR stem (3x3/1 conv, no maxpool) vs ImageNet stem.
    """

    stage_sizes: Sequence[int]
    block_cls: type = BottleneckBlock
    width_multiplier: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    axis_name: str | None = None
    small_images: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       use_fast_variance=False,
                       param_dtype=jnp.float32,
                       axis_name=self.axis_name if train else None)
        act = nn.relu

        x = x.astype(self.dtype)
        width = 64 * self.width_multiplier
        if self.small_images:
            x = conv(width, (3, 3), name="stem_conv")(x)
        else:
            x = conv(width, (7, 7), strides=(2, 2), name="stem_conv")(x)
        x = norm(name="stem_bn")(x)
        x = act(x)
        if not self.small_images:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=width * 2**i, strides=strides,
                    conv=conv, norm=norm, act=act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock)
ResNet50x2 = partial(ResNet, stage_sizes=(3, 4, 6, 3),
                     block_cls=BottleneckBlock, width_multiplier=2)
