from ntxent_tpu.models.clip import CLIPModel, TextTransformer
from ntxent_tpu.models.long_context import (
    LongContextBlock,
    LongContextTransformer,
    make_pipelined_apply,
    SeqParallelSelfAttention,
)
from ntxent_tpu.models.projection import ProjectionHead, SimCLRModel
from ntxent_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet50x2,
    ResNet101,
    ResNet152,
)
from ntxent_tpu.models.vit import (
    ViT_B16,
    ViT_L16,
    ViT_S16,
    ViT_Ti16,
    VisionTransformer,
)

__all__ = [
    "CLIPModel",
    "TextTransformer",
    "LongContextBlock",
    "LongContextTransformer",
    "make_pipelined_apply",
    "SeqParallelSelfAttention",
    "ProjectionHead",
    "SimCLRModel",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet50x2",
    "ResNet101",
    "ResNet152",
    "VisionTransformer",
    "ViT_Ti16",
    "ViT_S16",
    "ViT_B16",
    "ViT_L16",
]
