"""Flax Vision Transformer encoder (ViT-B/16 class) for SimCLR / CLIP.

Workload named by BASELINE.json configs[3] (ViT-B/16 SimCLR, global batch
8192 on v5p-64). TPU-first choices: bf16 activations with fp32 params and
fp32 LayerNorm/softmax, patchify as a strided conv (lowers to one MXU
matmul), sequence length 197 padded naturally by XLA, fused-friendly MLP.
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["VisionTransformer", "ViT_Ti16", "ViT_S16", "ViT_B16", "ViT_L16"]


class MlpBlock(nn.Module):
    mlp_dim: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        x = nn.Dense(self.mlp_dim, dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = nn.gelu(x)
        return nn.Dense(d, dtype=self.dtype, param_dtype=jnp.float32)(x)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: jnp.dtype
    moe_experts: int = 0  # >0 swaps the dense MLP for a switch-MoE MLP
    # "xla": nn.MultiHeadDotProductAttention (XLA fuses the (L, L) score
    # matrix; fine at ViT's L=197). "flash": the repo's fused blockwise
    # kernel via SeqParallelSelfAttention — an on-chip A/B lever for the
    # ViT MFU ladder (BASELINE.md: 49.0% at batch 64, just under the 50%
    # target). WEIGHT-COMPATIBLE: both paths project through DenseGeneral
    # submodules named query/key/value/out with identical kernel shapes,
    # and the flash module reuses the XLA path's auto-generated module
    # name, so one checkpoint serves either impl.
    attention_impl: str = "xla"

    @nn.compact
    def __call__(self, x, mask=None):
        if self.attention_impl not in ("xla", "flash"):
            raise ValueError(f"unknown attention_impl "
                             f"{self.attention_impl!r}: expected 'xla' "
                             "or 'flash'")
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        if self.attention_impl == "flash":
            if mask is not None:
                raise ValueError("attention_impl='flash' supports only "
                                 "the unmasked encoder case (ViT towers)")
            from .long_context import SeqParallelSelfAttention

            # Explicitly claim the name flax would auto-generate for the
            # nn.MultiHeadDotProductAttention below — this is what makes
            # the two impls load each other's checkpoints.
            y = SeqParallelSelfAttention(
                num_heads=self.num_heads, dtype=self.dtype,
                name="MultiHeadDotProductAttention_0")(y)
        else:
            y = nn.MultiHeadDotProductAttention(
                num_heads=self.num_heads, dtype=self.dtype,
                param_dtype=jnp.float32,
            )(y, y, mask=mask)
        x = x + y
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        if self.moe_experts > 0:
            from ntxent_tpu.parallel.moe import MoEMlp

            return x + MoEMlp(num_experts=self.moe_experts,
                              mlp_dim=self.mlp_dim, dtype=self.dtype)(y)
        return x + MlpBlock(self.mlp_dim, self.dtype)(y)


class VisionTransformer(nn.Module):
    """Returns (B, hidden) CLS-token features — no classifier head."""

    patch_size: int = 16
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dtype: jnp.dtype = jnp.bfloat16
    # Every-other-block switch-MoE (Switch Transformer layout) when > 0;
    # aux losses surface under intermediates/…/moe_aux_loss.
    moe_experts: int = 0
    # "xla" | "flash" — see EncoderBlock.attention_impl (weight-compatible
    # on-chip A/B lever for the ViT MFU ladder).
    attention_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = True):
        b, h, w, _ = x.shape
        x = x.astype(self.dtype)
        # Patchify = conv with kernel == stride == patch: one big MXU matmul.
        x = nn.Conv(self.hidden_dim, (self.patch_size,) * 2,
                    strides=(self.patch_size,) * 2, padding="VALID",
                    dtype=self.dtype, param_dtype=jnp.float32,
                    name="patch_embed")(x)
        x = x.reshape(b, -1, self.hidden_dim)

        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, self.hidden_dim), jnp.float32)
        x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, self.hidden_dim)
                                              ).astype(self.dtype), x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, x.shape[1], self.hidden_dim), jnp.float32)
        x = x + pos.astype(self.dtype)

        for i in range(self.depth):
            moe = self.moe_experts if i % 2 == 1 else 0
            x = EncoderBlock(self.num_heads, self.mlp_dim, self.dtype,
                             moe_experts=moe,
                             attention_impl=self.attention_impl,
                             name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="final_ln")(x)
        return x[:, 0].astype(jnp.float32)  # CLS token


ViT_Ti16 = partial(VisionTransformer, hidden_dim=192, depth=12, num_heads=3,
                   mlp_dim=768)
ViT_S16 = partial(VisionTransformer, hidden_dim=384, depth=12, num_heads=6,
                  mlp_dim=1536)
ViT_B16 = partial(VisionTransformer, hidden_dim=768, depth=12, num_heads=12,
                  mlp_dim=3072)
ViT_L16 = partial(VisionTransformer, hidden_dim=1024, depth=24, num_heads=16,
                  mlp_dim=4096)
