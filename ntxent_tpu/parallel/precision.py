"""Collective precision policy: what dtype rides the wire (ISSUE 12).

The NT-Xent distributed loss is communication-bound — every step
all-gathers full-precision embeddings and all-reduces full-precision
gradients through the mesh shims, and PR 7's comms accounting measured
exactly how many bytes that moves. EQuARX (PAPERS.md) shows quantized
AllReduce inside XLA at ~2x collective speedup with negligible quality
loss; this repo owns every hand-written collective call site, so the
same move lands HERE, one layer up from XLA: payloads are quantized
before the wire and dequantized after, inside the traced program.

This module is the pure half (no mesh state, no accounting): the
thread-local policy context and the int8 quantize/dequantize math.
``parallel/mesh.py`` owns the collective implementations that consume
it (the shims check :func:`collective_dtype` at trace time) and the
wire-byte accounting.

Policy semantics (``collective_precision(dtype)``):

* ``"float32"`` — the default: payloads ride as traced.
* ``"bf16"`` — float payloads are cast to bfloat16 before the
  collective and cast back after (2x fewer wire bytes; reductions
  accumulate in bf16 on the wire).
* ``"int8"`` — eligible payloads are quantized with a per-chunk
  symmetric scale computed in-graph (``quantize_int8``: the scale is
  ``amax(|x|)/127`` over each slice of the last axis, so one f32 scale
  rides per chunk), moved as int8 + scales, and dequantized after
  (~4x fewer wire bytes). Reductions use the two-phase
  quantize -> all_to_all -> local-sum -> re-quantize -> all_gather
  schedule (mesh.py), which keeps the ring-wire volume at exactly the
  int8 fraction of a float all-reduce at every mesh size.

Eligibility (``quantizable``): int8 applies only to float payloads with
at least :data:`MIN_QUANT_ELEMS` elements — scalars (the psum'd loss),
small vectors (logsumexp merges, biases) and integer payloads pass
through in full precision. That keeps the scalar loss psum exactly
differentiable and spends the compression where the bytes are.
"""

from __future__ import annotations

import os
import threading

import jax
import jax.numpy as jnp

__all__ = [
    "COLLECTIVE_DTYPES",
    "MIN_QUANT_ELEMS",
    "collective_precision",
    "collective_dtype",
    "quantizable",
    "quantize_int8",
    "dequantize_int8",
]

# The closed set of policy names (bounded label cardinality for the
# dtype-labeled collective counters rides on this).
COLLECTIVE_DTYPES = ("float32", "bf16", "int8")

# int8 floor: payloads below this many elements ride in full precision
# (scalars/small vectors cost more in scales + graph ops than they save
# in wire bytes, and the scalar loss psum must stay exactly
# differentiable). Env-overridable for tests that want tiny payloads
# quantized.
MIN_QUANT_ELEMS = int(os.environ.get("NTXENT_QUANT_MIN_ELEMS", "1024"))

_policy = threading.local()


def collective_dtype() -> str:
    """The wire dtype the ambient ``collective_precision`` context set
    (``"float32"`` outside any context)."""
    return getattr(_policy, "dtype", "float32")


class collective_precision:
    """Context manager: collectives traced inside quantize to ``dtype``.

    The policy is a TRACE-time, thread-local property — enter it around
    the code that builds the traced program (e.g. inside the shard_map
    body of a train step), not around the compiled call. Nests; the
    inner context wins. ``"bfloat16"`` is accepted as an alias for
    ``"bf16"``.
    """

    def __init__(self, dtype: str = "float32"):
        dtype = {"bfloat16": "bf16"}.get(str(dtype), str(dtype))
        if dtype not in COLLECTIVE_DTYPES:
            raise ValueError(
                f"collective dtype must be one of {COLLECTIVE_DTYPES}, "
                f"got {dtype!r}")
        self.dtype = dtype
        self._saved = "float32"

    def __enter__(self) -> "collective_precision":
        self._saved = collective_dtype()
        _policy.dtype = self.dtype
        return self

    def __exit__(self, *exc) -> None:
        _policy.dtype = self._saved
        return None


def quantizable(x, min_elems: int | None = None) -> bool:
    """Is this leaf worth putting on the wire as int8? Float payloads of
    at least ``min_elems`` elements (default :data:`MIN_QUANT_ELEMS`)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return False
    if not jnp.issubdtype(dtype, jnp.floating):
        return False
    size = 1
    for d in shape:
        size *= int(d)
    floor = MIN_QUANT_ELEMS if min_elems is None else int(min_elems)
    return size >= floor and len(shape) >= 1


def quantize_int8(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-chunk symmetric int8 quantization, computed in-graph.

    A chunk is one slice along the LAST axis: ``scale`` has shape
    ``x.shape[:-1] + (1,)`` with ``scale = amax(|chunk|) / 127``
    (clamped away from zero so all-zero chunks quantize to zeros, not
    NaNs). Returns ``(q, scale)`` with ``q`` int8 in [-127, 127] —
    symmetric, so -128 is never minted and dequantization is a pure
    multiply. The wire cost is 1 byte/element + 4 bytes/chunk.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127.0, 127.0).astype(jnp.int8)
    return q, scale


def dequantize_int8(q: jax.Array, scale: jax.Array,
                    dtype=jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_int8` (up to rounding): ``q * scale``
    in f32, cast to ``dtype``."""
    return (q.astype(jnp.float32) * scale).astype(dtype)
