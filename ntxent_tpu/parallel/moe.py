"""Expert parallelism: Switch-style MoE MLP with all-to-all dispatch.

SURVEY.md §2.2 marked EP/Ulysses N/A for the reference — this module goes
beyond parity and fills the ``ep`` slot of the dp/tp/pp/sp/ep matrix. The
design follows the standard TPU MoE recipe (Switch Transformer / GShard):
everything is a fixed-shape einsum so XLA can tile it onto the MXU, and the
only communication is a pair of ``lax.all_to_all`` exchanges over the
``expert`` mesh axis.

* **Routing** is top-1 ("switch") with a static capacity
  ``C = ceil(T / E * capacity_factor)``. A token's slot inside its expert
  is its rank among same-expert tokens (cumsum of the one-hot assignment);
  tokens past capacity are *dropped* — their combine weight is zero, so
  they pass through the residual stream untouched. Static shapes mean no
  data-dependent control flow inside jit.
* **Dispatch/combine** are the mesh-tensorflow einsum formulation: a
  ``(T, E, C)`` one-hot dispatch mask gathers token rows into an
  ``(E, C, d)`` expert batch; the transpose einsum with gate-weighted
  entries scatters expert outputs back. Both lower to MXU matmuls.
* **Expert parallelism**: under ``shard_map`` with ``axis="expert"``, each
  device routes its local tokens against all ``E`` experts, then one
  tiled ``all_to_all`` re-shards the ``(E, C, d)`` expert batch from
  token-sharded to expert-sharded — each device receives every device's
  rows for its own ``E/P`` experts — the local expert FFNs run, and the
  inverse ``all_to_all`` brings the rows home for the local combine. This
  is exactly the dispatch pattern the Ulysses path uses for heads
  (ring_attention.py), applied to experts.
* **Load-balancing aux loss** (Switch eq. 4): ``E * sum_e f_e * p_e`` with
  ``f_e`` the fraction of tokens routed to expert ``e`` and ``p_e`` the
  mean router probability — differentiable through ``p_e`` only.

``MoEMlp`` is the flax module (drop-in for the towers' dense ``MlpBlock``);
``switch_moe`` is the pure functional core shared by the local and
expert-parallel paths, so the EP test can assert shard == single-device.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from .mesh import all_to_all as _all_to_all_acct
from .mesh import axis_index as _axis_index_compat
from .mesh import axis_size as _axis_size_compat
from .mesh import pmean as _pmean_acct
from .mesh import shard_map as _shard_map_compat

__all__ = ["MoEParams", "init_moe_params", "switch_moe",
           "make_expert_parallel_moe", "MoEMlp", "moe_aux_from"]


def moe_aux_from(updates) -> jax.Array:
    """Summed MoE load-balance loss out of a mutated-variables dict.

    Lives next to the module that sows ``moe_aux_loss`` (``MoEMlp``) and
    selects ONLY those entries: other modules may sow unrelated
    intermediates (debug activations, attention maps) that must never
    leak into a training objective. Consumed by the trainers
    (training/trainer.py, parallel/tp.py).
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(
        updates.get("intermediates", {}))
    leaves = [v for path, v in flat
              if any(getattr(k, "key", None) == "moe_aux_loss"
                     for k in path)]
    return sum(jnp.sum(a) for a in leaves) if leaves else jnp.float32(0)


@dataclass(frozen=True)
class MoEParams:
    """Weights of one switch-MoE layer (E experts, width d, hidden f)."""

    router: jax.Array  # (d, E)
    w_up: jax.Array    # (E, d, f)
    b_up: jax.Array    # (E, f)
    w_down: jax.Array  # (E, f, d)
    b_down: jax.Array  # (E, d)


jax.tree_util.register_dataclass(
    MoEParams, data_fields=["router", "w_up", "b_up", "w_down", "b_down"],
    meta_fields=[])


def init_moe_params(key, num_experts: int, d: int, mlp_dim: int,
                    dtype=jnp.float32) -> MoEParams:
    kr, ku, kd = jax.random.split(key, 3)
    lecun = nn.initializers.lecun_normal()
    return MoEParams(
        router=lecun(kr, (d, num_experts), dtype),
        w_up=lecun(ku, (num_experts, d, mlp_dim), dtype),
        b_up=jnp.zeros((num_experts, mlp_dim), dtype),
        w_down=lecun(kd, (num_experts, mlp_dim, d), dtype),
        b_down=jnp.zeros((num_experts, d), dtype),
    )


def _route(x2d: jax.Array, router: jax.Array, capacity: int):
    """Top-1 routing → (dispatch (T,E,C) bool, combine (T,E,C), aux loss).

    Router math in fp32 regardless of activation dtype (softmax stability,
    same policy as the towers' norms).
    """
    t, _ = x2d.shape
    e = router.shape[1]
    logits = x2d.astype(jnp.float32) @ router.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                    # (T, E)
    expert = jnp.argmax(probs, axis=-1)                        # (T,)
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)      # (T, E)
    gate = jnp.sum(probs * onehot, axis=-1)                    # (T,)
    # Rank of each token within its expert (0-based), in token order.
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot         # (T, E)
    slot = jnp.sum(pos, axis=-1).astype(jnp.int32)             # (T,)
    kept = slot < capacity
    dispatch = (onehot * kept[:, None].astype(jnp.float32))[..., None] \
        * jax.nn.one_hot(slot, capacity, dtype=jnp.float32)[:, None, :]
    combine = dispatch * gate[:, None, None]
    # Per-expert token fraction and mean router prob (aux-loss inputs;
    # the caller pmean's them over the mesh so sharded aux == global aux).
    frac = jnp.mean(onehot, axis=0)
    mean_p = jnp.mean(probs, axis=0)
    return dispatch, combine, frac, mean_p


def switch_moe(params: MoEParams, x: jax.Array, *,
               capacity_factor: float = 1.25,
               axis: str | None = None):
    """Apply one switch-MoE layer; returns ``(y, aux_loss)``.

    ``x`` is ``(..., d)``; leading axes are flattened into a token axis for
    routing. With ``axis`` set, the call must be inside ``shard_map``:
    experts are sharded over that mesh axis (``E % axis_size == 0``) and
    the expert batch crosses the mesh via two tiled all-to-alls; capacity
    is computed from the *local* token count, so the routing decisions are
    identical to the unsharded layer whenever nothing overflows.
    """
    d = x.shape[-1]
    lead = x.shape[:-1]
    x2d = x.reshape(-1, d)
    t = x2d.shape[0]
    e = params.router.shape[1]
    capacity = max(1, math.ceil(t / e * capacity_factor))
    dispatch, combine, frac, mean_p = _route(x2d, params.router, capacity)
    if axis is not None:
        # Equal shard sizes → pmean of per-shard token means IS the global
        # mean, so the load-balance loss below matches the unsharded layer.
        frac = _pmean_acct(frac, axis)
        mean_p = _pmean_acct(mean_p, axis)
    # Switch load-balance loss (eq. 4): differentiable through probs only.
    aux = e * jnp.sum(frac * mean_p)

    xin = jnp.einsum("tec,td->ecd", dispatch,
                     x2d.astype(jnp.float32)).astype(x.dtype)  # (E, C, d)

    w_up, b_up, w_down, b_down = (params.w_up, params.b_up,
                                  params.w_down, params.b_down)
    if axis is not None:
        p = _axis_size_compat(axis)
        if e % p:
            raise ValueError(f"{e} experts not divisible over {p} devices")
        # Token-sharded (E, C, d) → expert-sharded (E/P, P*C, d): each
        # device keeps only its experts' rows, from every device.
        xin = _all_to_all_acct(xin, axis, split_axis=0, concat_axis=1,
                                 tiled=True)
        i = _axis_index_compat(axis)
        sl = e // p
        w_up = jax.lax.dynamic_slice_in_dim(w_up, i * sl, sl, 0)
        b_up = jax.lax.dynamic_slice_in_dim(b_up, i * sl, sl, 0)
        w_down = jax.lax.dynamic_slice_in_dim(w_down, i * sl, sl, 0)
        b_down = jax.lax.dynamic_slice_in_dim(b_down, i * sl, sl, 0)

    h = jnp.einsum("ecd,edf->ecf", xin, w_up.astype(x.dtype)) \
        + b_up[:, None, :].astype(x.dtype)
    h = nn.gelu(h)
    yout = jnp.einsum("ecf,efd->ecd", h, w_down.astype(x.dtype)) \
        + b_down[:, None, :].astype(x.dtype)

    if axis is not None:
        # Inverse exchange: expert-sharded rows come home token-sharded.
        yout = _all_to_all_acct(yout, axis, split_axis=1, concat_axis=0,
                                  tiled=True)

    y = jnp.einsum("tec,ecd->td", combine,
                   yout.astype(jnp.float32)).astype(x.dtype)
    return y.reshape(*lead, d), aux


def make_expert_parallel_moe(mesh: Mesh, *, axis: str = "expert",
                             capacity_factor: float = 1.25,
                             token_axis: str | None = None):
    """Build ``fn(params, x) -> (y, aux)`` sharded over ``mesh[axis]``.

    Tokens are sharded over ``token_axis`` (defaults to ``axis`` itself —
    the usual dp=ep layout where each device routes its own batch shard);
    expert weights enter replicated and each device slices its own
    experts. ``aux`` is psum-averaged so every device returns the global
    load-balance loss.
    """
    tok = token_axis or axis

    def body(params, x):
        # switch_moe already pmean's the aux-loss statistics over the mesh,
        # so aux comes back identical (and global) on every device.
        return switch_moe(params, x, capacity_factor=capacity_factor,
                          axis=axis)

    return _shard_map_compat(
        body, mesh=mesh, in_specs=(P(), P(tok)),
        out_specs=(P(tok), P()), check_vma=False)


class MoEMlp(nn.Module):
    """Flax switch-MoE MLP: drop-in for the towers' dense ``MlpBlock``.

    Sows the load-balance aux loss under ``intermediates/moe_aux_loss`` so
    trainers can collect it via ``mutable=["intermediates"]`` and add
    ``aux_weight * sum(aux)`` to the objective.
    """

    num_experts: int
    mlp_dim: int
    dtype: Any = jnp.float32
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        lecun = nn.initializers.lecun_normal()
        params = MoEParams(
            router=self.param("router", lecun, (d, self.num_experts),
                              jnp.float32),
            w_up=self.param("w_up", lecun,
                            (self.num_experts, d, self.mlp_dim),
                            jnp.float32),
            b_up=self.param("b_up", nn.initializers.zeros,
                            (self.num_experts, self.mlp_dim), jnp.float32),
            w_down=self.param("w_down", lecun,
                              (self.num_experts, self.mlp_dim, d),
                              jnp.float32),
            b_down=self.param("b_down", nn.initializers.zeros,
                              (self.num_experts, d), jnp.float32),
        )
        y, aux = switch_moe(params, x.astype(self.dtype),
                            capacity_factor=self.capacity_factor)
        self.sow("intermediates", "moe_aux_loss", aux)
        return y
