"""Sequence/context parallelism for long sequences: ring attention and
Ulysses-style all-to-all head parallelism.

The framework's contrastive losses already have their ring form
(parallel/ring.py — the quadratic object there is the similarity matrix).
This module gives the TOWERS the same treatment for sequences too long for
one chip's attention: the quadratic object is the (L, L) attention matrix,
and "long context" means L²  doesn't fit — or L itself doesn't — per chip.

Two standard decompositions, both over a 1-D mesh axis that shards the
sequence dimension:

* **Ring attention** (`make_ring_attention`): Q stays home; (K, V) blocks
  circulate around the ICI ring via ``lax.ppermute`` while each device
  folds every visiting block into flash-style online-softmax statistics
  (running max m, running sum l, running output O). After P hops every
  query row has seen all L keys: per-chip attention memory is
  O(L/P x L/P) per fold, activations O(L/P), and all communication rides
  neighbor ICI links. The backward is a custom VJP running a SECOND ring
  pass in which each (K, V) block circulates together with its (dK, dV)
  accumulators and arrives home carrying every device's contribution —
  the hand-written reverse-ring the pattern needs, derived once here
  (same structure as ring.py's fused-ring loss VJP).
* **Ulysses / all-to-all** (`make_ulysses_attention`): one
  ``lax.all_to_all`` re-shards from sequence-split to head-split (every
  device gets the FULL sequence for H/P heads), attention runs locally
  and exactly, and a second all-to-all re-shards back. Communication is
  two all-to-alls of the activations; attention math is untouched —
  gradients flow through the collectives by AD. Requires H % P == 0.

When to use which (the scaling-book recipe): Ulysses when heads divide
cleanly and the all-to-all fits ICI (cheapest — exact attention, two
collectives); the ring when L/P is the binding constraint or heads are
few — its communication overlaps with per-hop compute and nothing ever
holds the full (L, d) K/V on one chip.

Shapes follow the towers' convention: q, k, v are (B, L, H, D) with L
sharded over the mesh axis; outputs match. All softmax statistics are
fp32 regardless of input dtype (bf16-safe), with the same `_exp0`/`_log_l`
compiler-skew hardening the loss kernels use.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention_pallas import resolve_attention_scale as _resolve_scale
from ..ops.attention_pallas import _flat, _unflat
from ..ops.ntxent_pallas import _exp0, _log_l
from .mesh import all_to_all as _all_to_all_acct
from .mesh import axis_index as _axis_index_compat
from .mesh import chunk_bounds as _chunk_bounds
from .mesh import comms_scaled as _comms_scaled
from .mesh import pcast as _pcast_compat
from .mesh import ppermute as _ppermute_acct
from .mesh import shard_map as _shard_map_compat

__all__ = [
    "attention_oracle",
    "blockwise_attention",
    "make_ring_attention",
    "make_ulysses_attention",
]

_NEG_INF = -1e30


def _send_chunked(x, axis, perm, chunks):
    """One ring hop of a (B, L, ...) block split into ``chunks``
    independent ppermutes along the SEQUENCE dim (the ISSUE 19 overlap
    schedule, transplanted from ``mesh.ppermute_chunked`` — which slices
    dim 0 — to the attention layout where dim 1 is the long one). Total
    wire bytes are identical to the monolithic hop, so the declared byte
    model and the graph census agree either way; each slice rides the
    ambient ``collective_precision`` policy independently.
    ``chunks <= 1`` degrades to one plain hop."""
    c = max(int(chunks or 1), 1)
    if c <= 1 or getattr(x, "ndim", 0) < 2 or x.shape[1] <= 1:
        return _ppermute_acct(x, axis, perm)
    parts = [_ppermute_acct(x[:, lo:hi], axis, perm)
             for lo, hi in _chunk_bounds(x.shape[1], c)]
    return jnp.concatenate(parts, axis=1)


def _varying(x, axis):
    """Mark a device-invariant init as ring-varying (scan carries must
    agree in varying-ness with the values ppermute makes device-local).
    Routed through the mesh.pcast version shim: on jax without the
    varying type system the annotation is unnecessary and this is
    identity."""
    return _pcast_compat(x, (axis,), to="varying")


def attention_oracle(q, k, v, *, causal: bool = False, scale=None,
                     q_offset: int = 0, k_offset: int = 0):
    """Reference full-softmax attention (jnp, fp32 softmax) — the oracle
    the parallel forms are tested against. q, k, v: (B, L, H, D)."""
    sc = _resolve_scale(scale, q.shape[-1])
    s = jnp.einsum("blhd,bmhd->bhlm", q, k,
                   preferred_element_type=jnp.float32) * sc
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        s = jnp.where(kpos[None, None, None, :] > qpos[None, None, :, None],
                      _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhlm,bmhd->blhd", p.astype(v.dtype), v)
    return out.astype(q.dtype)


def _fold(q_bhld, kb, vb, qpos, kpos, m, l, o, sc, causal):
    """Fold one (K, V) block into the online-softmax statistics.

    q_bhld: (B, H, Lq, D); kb, vb: (B, Lk, H, D); m, l: (B, H, Lq);
    o: (B, H, Lq, D) fp32 accumulators; qpos/kpos: global row positions.
    """
    s = jnp.einsum("bhld,bmhd->bhlm", q_bhld, kb,
                   preferred_element_type=jnp.float32) * sc
    if causal:
        s = jnp.where(kpos[None, None, None, :] > qpos[None, None, :, None],
                      _NEG_INF, s)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # A fold whose every entry is causal-masked leaves m_new at -inf and
    # s - m_new == 0 — the raw exp would count masked entries as weight 1.
    # (Happens on real rings: an early hop can be entirely in a query
    # row's future.) Zero them explicitly.
    p = jnp.where(s <= _NEG_INF * 0.5, 0.0, _exp0(s - m_new[..., None]))
    alpha = _exp0(m - m_new)
    l = l * alpha + jnp.sum(p, axis=-1)
    o = o * alpha[..., None] + jnp.einsum(
        "bhlm,bmhd->bhld", p, vb.astype(jnp.float32))
    return m_new, l, o


def blockwise_attention(q, k, v, *, block_kv: int | None = None,
                        causal: bool = False, scale=None):
    """Single-device flash-style attention: a ``lax.scan`` over K/V blocks
    with online-softmax folds — never materializes the (L, L) matrix.
    Exact (same math as ``attention_oracle``, fold order aside). The
    per-hop building block of the ring form, usable standalone for long
    single-chip sequences. L must divide by ``block_kv`` (default: one
    block — plain attention memory, kept simple for callers that only
    want the interface)."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    block = block_kv or lk
    if lk % block:
        raise ValueError(f"sequence {lk} not divisible by block {block}")
    sc = _resolve_scale(scale, d)
    q_ = q.astype(jnp.float32).transpose(0, 2, 1, 3)  # (B, H, Lq, D)
    pos = jnp.arange(lq)
    m = jnp.full((b, h, lq), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, lq), jnp.float32)
    o = jnp.zeros((b, h, lq, d), jnp.float32)

    kb = k.reshape(b, lk // block, block, h, d).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(b, lk // block, block, h, d).transpose(1, 0, 2, 3, 4)

    def step(carry, blk):
        m, l, o = carry
        kj, vj, j = blk
        kpos = j * block + jnp.arange(block)
        m, l, o = _fold(q_, kj, vj, pos, kpos, m, l, o, sc, causal)
        return (m, l, o), None

    (m, l, o), _ = jax.lax.scan(
        step, (m, l, o), (kb, vb, jnp.arange(lk // block)))
    out = o / l[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# ---------------------------------------------------------------------------
# Ring attention
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_attention(q, k, v, axis, num_devices, causal, sc, chunks):
    """Per-device ring attention body (call inside shard_map).

    q, k, v: (B, L/P, H, D) local sequence shards. Returns the local
    (B, L/P, H, D) output block after all P hops. ``chunks`` splits each
    K/V hop into that many sequence-dim ppermutes (ISSUE 19 overlap).
    """
    return _ring_fwd(q, k, v, axis, num_devices, causal, sc, chunks)[0]


def _hop_perm(axis, num_devices):
    return [(i, (i + 1) % num_devices) for i in range(num_devices)]


def _positions(axis, l_loc):
    # mesh.axis_index, not the raw lax op: these custom-VJP ring bodies
    # are exactly the old-jax partition-id-under-GSPMD lowering seam the
    # shim exists for (see parallel/mesh.py).
    d = _axis_index_compat(axis)
    return d * l_loc + jnp.arange(l_loc)


def _ring_fwd(q, k, v, axis, num_devices, causal, sc, chunks=1):
    b, l_loc, h, d = q.shape
    perm = _hop_perm(axis, num_devices)
    qpos = _positions(axis, l_loc)
    q_ = q.astype(jnp.float32).transpose(0, 2, 1, 3)  # (B, H, Lq, D)

    init = (
        k, v, qpos,
        _varying(jnp.full((b, h, l_loc), _NEG_INF, jnp.float32), axis),
        _varying(jnp.zeros((b, h, l_loc), jnp.float32), axis),
        _varying(jnp.zeros((b, h, l_loc, d), jnp.float32), axis),
    )

    def step(carry, _):
        kb, vb, kpos, m, l, o = carry
        # Sends issued before the fold consumes the block: the chunked
        # slices and the fold are independent, so chunk transfers overlap
        # the similarity/output compute of the current hop.
        kb_n = _send_chunked(kb, axis, perm, chunks)
        vb_n = _send_chunked(vb, axis, perm, chunks)
        kpos_n = _ppermute_acct(kpos, axis, perm)
        m, l, o = _fold(q_, kb, vb, qpos, kpos, m, l, o, sc, causal)
        return (kb_n, vb_n, kpos_n, m, l, o), None

    # comms_scaled on every scanned ring below: the body's ppermutes
    # trace once but run `length` times.
    with _comms_scaled(num_devices):
        (_, _, _, m, l, o), _ = jax.lax.scan(step, init, None,
                                             length=num_devices)
    lse = m + _log_l(l)                      # (B, H, Lq)
    out = (o / l[..., None]).transpose(0, 2, 1, 3).astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis, num_devices, causal, sc, chunks, res, g):
    """Second ring pass: each (K, V) block circulates WITH its (dK, dV)
    accumulators and arrives home carrying every device's contribution.
    Reuses the forward's chunked schedule: the (K, V, dK, dV) sends are
    sequence-dim chunked so the gradient exchange overlaps the hop's
    einsum work the same way."""
    q, k, v, out, lse = res
    b, l_loc, h, d = q.shape
    perm = _hop_perm(axis, num_devices)
    qpos = _positions(axis, l_loc)

    q_ = q.astype(jnp.float32).transpose(0, 2, 1, 3)     # (B, H, Lq, D)
    do = g.astype(jnp.float32).transpose(0, 2, 1, 3)     # (B, H, Lq, D)
    # D_i = sum_d do_i * o_i — the softmax-backward row correction.
    drow = jnp.sum(do * out.astype(jnp.float32).transpose(0, 2, 1, 3),
                   axis=-1)                               # (B, H, Lq)

    init = (
        k, v, qpos,
        _varying(jnp.zeros((b, l_loc, h, d), jnp.float32), axis),  # dk
        _varying(jnp.zeros((b, l_loc, h, d), jnp.float32), axis),  # dv
        _varying(jnp.zeros((b, h, l_loc, d), jnp.float32), axis),  # dq home
    )

    def step(carry, _):
        kb, vb, kpos, dkb, dvb, dq = carry
        s = jnp.einsum("bhld,bmhd->bhlm", q_, kb,
                       preferred_element_type=jnp.float32) * sc
        if causal:
            s = jnp.where(
                kpos[None, None, None, :] > qpos[None, None, :, None],
                _NEG_INF, s)
        p = _exp0(s - lse[..., None])                     # true softmax rows
        dvb = dvb + jnp.einsum("bhlm,bhld->bmhd", p, do)
        dp = jnp.einsum("bhld,bmhd->bhlm", do, vb.astype(jnp.float32))
        ds = p * (dp - drow[..., None]) * sc
        dq = dq + jnp.einsum("bhlm,bmhd->bhld", ds, kb.astype(jnp.float32))
        dkb = dkb + jnp.einsum("bhlm,bhld->bmhd", ds, q_)
        kb = _send_chunked(kb, axis, perm, chunks)
        vb = _send_chunked(vb, axis, perm, chunks)
        kpos = _ppermute_acct(kpos, axis, perm)
        dkb = _send_chunked(dkb, axis, perm, chunks)
        dvb = _send_chunked(dvb, axis, perm, chunks)
        return (kb, vb, kpos, dkb, dvb, dq), None

    with _comms_scaled(num_devices):
        (_, _, _, dk, dv, dq), _ = jax.lax.scan(step, init, None,
                                                length=num_devices)
    dq = dq.transpose(0, 2, 1, 3)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_attention.defvjp(_ring_fwd, _ring_bwd)


# --- Fused (Pallas) ring: flash folds per hop, kernel-grade hot path ---


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _ring_attention_flash(q, k, v, axis, num_devices, causal, sc,
                          bq=None, bk=None, chunks=1):
    """Ring attention whose per-hop fold runs the fused flash kernel
    (ops/attention_pallas.py:flash_fold) — carried (m, l, acc) statistics
    thread through the hops, so the across-hop softmax is exact and the
    (L_loc, L_loc) tile work happens on the MXU with VMEM statistics.
    The backward is the same second ring pass as the jnp form, but each
    hop's contribution comes from the flash dQ / dK-dV kernels."""
    return _ring_flash_fwd(q, k, v, axis, num_devices, causal, sc,
                           bq, bk, chunks)[0]


def _ring_flash_fwd(q, k, v, axis, num_devices, causal, sc,
                    bq=None, bk=None, chunks=1):
    from ..ops.attention_pallas import flash_fold

    b, l_loc, h, d = q.shape
    bh = b * h
    perm = _hop_perm(axis, num_devices)
    q_off = _axis_index_compat(axis) * l_loc
    qf = _flat(q)

    init = (
        _flat(k), _flat(v),
        (_axis_index_compat(axis) * l_loc).reshape(1),
        _varying(jnp.full((bh, l_loc), _NEG_INF, jnp.float32), axis),
        _varying(jnp.zeros((bh, l_loc), jnp.float32), axis),
        _varying(jnp.zeros((bh, l_loc, d), jnp.float32), axis),
    )

    def step(carry, _):
        kf, vf, k_off, m, l, acc = carry
        # Chunked sends issued before the kernel folds the block (same
        # overlap structure as the jnp ring).
        kf_n = _send_chunked(kf, axis, perm, chunks)
        vf_n = _send_chunked(vf, axis, perm, chunks)
        k_off_n = _ppermute_acct(k_off, axis, perm)
        m, l, acc = flash_fold(qf, kf, vf, m, l, acc,
                               q_offset=q_off, k_offset=k_off[0],
                               scale=sc, causal=causal,
                               block_q=bq, block_kv=bk)
        return (kf_n, vf_n, k_off_n, m, l, acc), None

    with _comms_scaled(num_devices):
        (_, _, _, m, l, acc), _ = jax.lax.scan(step, init, None,
                                               length=num_devices)
    lse = m + _log_l(l)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = _unflat((acc / l_safe[..., None]).astype(q.dtype), b, h)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis, num_devices, causal, sc, bq, bk, chunks, res, g):
    from ..ops.attention_pallas import flash_dkv_hop, flash_dq_hop

    q, k, v, out, lse = res
    b, l_loc, h, d = q.shape
    bh = b * h
    perm = _hop_perm(axis, num_devices)
    q_off = _axis_index_compat(axis) * l_loc
    qf, dof, outf = _flat(q), _flat(g), _flat(out)
    delta = jnp.sum(dof.astype(jnp.float32) * outf.astype(jnp.float32),
                    axis=-1)

    init = (
        _flat(k), _flat(v),
        (_axis_index_compat(axis) * l_loc).reshape(1),
        _varying(jnp.zeros((bh, l_loc, d), jnp.float32), axis),  # dk
        _varying(jnp.zeros((bh, l_loc, d), jnp.float32), axis),  # dv
        _varying(jnp.zeros((bh, l_loc, d), jnp.float32), axis),  # dq home
    )

    def step(carry, _):
        kf, vf, k_off, dkf, dvf, dqf = carry
        kwargs = dict(q_offset=q_off, k_offset=k_off[0], scale=sc,
                      causal=causal, block_q=bq, block_kv=bk)
        dqf = dqf + flash_dq_hop(qf, kf, vf, dof, lse, delta, **kwargs)
        dkc, dvc = flash_dkv_hop(qf, kf, vf, dof, lse, delta, **kwargs)
        dkf, dvf = dkf + dkc, dvf + dvc
        kf = _send_chunked(kf, axis, perm, chunks)
        vf = _send_chunked(vf, axis, perm, chunks)
        k_off = _ppermute_acct(k_off, axis, perm)
        dkf = _send_chunked(dkf, axis, perm, chunks)
        dvf = _send_chunked(dvf, axis, perm, chunks)
        return (kf, vf, k_off, dkf, dvf, dqf), None

    with _comms_scaled(num_devices):
        (_, _, _, dkf, dvf, dqf), _ = jax.lax.scan(step, init, None,
                                                   length=num_devices)
    return (_unflat(dqf, b, h).astype(q.dtype),
            _unflat(dkf, b, h).astype(k.dtype),
            _unflat(dvf, b, h).astype(v.dtype))


_ring_attention_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def make_ring_attention(mesh: Mesh, axis: str = "data", *,
                        causal: bool = False, scale=None,
                        impl: str = "jnp",
                        block_q: int | None = None,
                        block_kv: int | None = None,
                        transfer_chunks: int | None = None):
    """Build a jit-able sequence-parallel ring attention over ``mesh``.

    Returns ``fn(q, k, v) -> out`` with all four (B, L, H, D) and L
    sharded over ``axis`` (L % P == 0). ``causal`` masks with GLOBAL
    positions, so the sharded form equals the oracle on the full
    sequence. Exact gradients for q, k, v via the second-ring-pass VJP.

    ``impl="jnp"`` folds hops with XLA ops (runs everywhere);
    ``impl="flash"`` runs the fused Pallas flash kernels per hop
    (carried-statistics folds forward, flash dQ/dK-dV kernels in the
    backward ring) — the TPU hot path; interpret-mode (exact, slow)
    off-TPU. The two are the same function; on-chip A/B decides the
    production default.

    ``block_q``/``block_kv`` (flash only) pin the per-hop kernel tiles —
    feed them from ``ops.autotune.autotune_attention_blocks(l_local,
    l_local, head_dim, causal=causal)`` to run each hop at the
    measured-winner tile instead of the static heuristic (the tuned
    tile was worth up to 1.3x on the single-chip A/B ladder).

    ``transfer_chunks`` (ISSUE 19) splits each K/V ring hop — forward
    AND the backward gradient exchange — into that many sequence-dim
    ppermutes issued before the hop's fold, so chunk k+1's transfer
    overlaps chunk k's compute. Total wire bytes are unchanged (the
    census pins this). Default ``None`` keeps the monolithic hop;
    feed ``ops.autotune.resolve_ring_chunks`` for the tuned count.
    """
    if impl not in ("jnp", "flash"):
        raise ValueError(f"unknown ring attention impl {impl!r}")
    if impl != "flash" and (block_q is not None or block_kv is not None):
        raise ValueError("block_q/block_kv tune the flash kernels; the "
                         "jnp fold has no tiles — they would be silently "
                         "ignored")
    num_devices = mesh.shape[axis]
    chunks = max(int(transfer_chunks or 1), 1)

    def body(q, k, v):
        sc = _resolve_scale(scale, q.shape[-1])
        if impl == "flash":
            return _ring_attention_flash(q, k, v, axis, num_devices,
                                         causal, sc, block_q, block_kv,
                                         chunks)
        return _ring_attention(q, k, v, axis, num_devices, causal, sc,
                               chunks)

    return _shard_map_compat(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
    )


# ---------------------------------------------------------------------------
# Ulysses (all-to-all head parallelism)
# ---------------------------------------------------------------------------


def make_ulysses_attention(mesh: Mesh, axis: str = "data", *,
                           causal: bool = False, scale=None,
                           block_kv: int | None = None):
    """Build a jit-able all-to-all sequence-parallel attention.

    Input/output (B, L, H, D) with L sharded over ``axis``; internally one
    ``all_to_all`` re-shards to (B, L, H/P, D) per device (full sequence,
    a slice of heads), attention runs locally — blockwise when
    ``block_kv`` is set — and a second all-to-all restores the sequence
    sharding. H % P == 0 required. Gradients through the collectives are
    AD-derived (the transpose of an all-to-all is the reverse
    all-to-all).
    """
    num_devices = mesh.shape[axis]

    def body(q, k, v):
        h = q.shape[2]
        if h % num_devices:
            raise ValueError(
                f"Ulysses needs heads ({h}) divisible by mesh axis "
                f"({num_devices}); use make_ring_attention instead")

        def to_heads(x):   # (B, L/P, H, D) -> (B, L, H/P, D)
            return _all_to_all_acct(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
        if block_kv:
            oh = blockwise_attention(qh, kh, vh, block_kv=block_kv,
                                     causal=causal, scale=scale)
        else:
            oh = attention_oracle(qh, kh, vh, causal=causal, scale=scale)
        # (B, L, H/P, D) -> (B, L/P, H, D)
        return _all_to_all_acct(oh, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    return _shard_map_compat(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
    )
