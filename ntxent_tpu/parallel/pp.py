"""Pipeline parallelism: GPipe microbatch schedule over a mesh ``stage`` axis.

SURVEY.md §2.2 marked pipeline parallelism N/A for the reference (its NCCL
path is pure data parallel) — this module goes beyond parity and fills the
``pp`` slot of the framework's dp/tp/pp/sp/ep matrix. The design is the
idiomatic JAX/TPU recipe rather than a hand-scheduled runtime:

* Stage weights live stacked along a leading axis sharded over the mesh's
  ``stage`` axis — each device holds exactly one stage's parameters and
  never sees the others (weights are *partitioned*, the point of PP).
* The GPipe schedule is one ``lax.scan`` over ``M + S - 1`` ticks. Each
  tick every device applies its stage to its current activation and hands
  the result to its successor via ``lax.ppermute`` — a nearest-neighbour
  hop that rides a single ICI link, the cheapest collective on a TPU torus.
* The backward pipeline is **derived, not written**: ``jax.grad`` through
  the scan reverses the schedule, and ppermute's transpose is the inverted
  permutation, so cotangents flow stage S-1 → 0 with the same
  nearest-neighbour traffic. (The reference would have had to hand-code
  this with NCCL send/recv; here AD + XLA emit it.)

The pipeline body is *homogeneous*: every stage maps activations of one
fixed shape to the same shape (the transformer-stack case — embedding and
head run outside the pipeline, unsharded or under dp/tp). Bubble fraction
is the textbook ``(S-1)/(M+S-1)``; raise ``num_microbatches`` to amortize.

Composes with data parallelism by construction: pass ``data_axis`` and the
batch stays sharded over that axis while the schedule runs per data-row of
the mesh — a 2-D (data, stage) mesh gives dp×pp with no extra code.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from .mesh import axis_index as _axis_index_compat
from .mesh import comms_scaled as _comms_scaled
from .mesh import ppermute as _ppermute_acct
from .mesh import psum as _psum_acct
from .mesh import shard_map as _shard_map_compat

__all__ = [
    "stack_stage_params",
    "make_gpipe",
    "pipeline_stage_params",
]


def stack_stage_params(params_list: Sequence[Any]):
    """Stack S per-stage pytrees into one tree with a leading stage axis.

    All stages must share a tree structure and per-leaf shapes (homogeneous
    pipeline). The result is what ``make_gpipe`` expects: leaves of shape
    ``(S, ...)``, sharded ``P(stage_axis)`` on entry to the shard_map.
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *params_list)


def pipeline_stage_params(params: Any, num_stages: int,
                          block_prefix: str = "block_"):
    """Split a flax transformer param dict into stacked GPipe stage params.

    ``params`` holds ``{block_prefix}{i}`` sub-trees (flax auto-names, e.g.
    ``VisionTransformer``'s ``block_0..block_{depth-1}``) with identical
    structure. Returns ``(stacked, rest)``: ``stacked`` has leaves
    ``(num_stages, blocks_per_stage, ...)`` — stage-major so a ``P(stage)``
    prefix spec shards it — and ``rest`` is everything else (embeddings,
    final norm), to be applied outside the pipeline.
    """
    blocks = sorted(
        (int(k[len(block_prefix):]), k) for k in params
        if k.startswith(block_prefix))
    if not blocks:
        raise ValueError(f"no '{block_prefix}*' entries in params")
    n = len(blocks)
    if n % num_stages:
        raise ValueError(f"{n} blocks do not split into {num_stages} stages")
    per = n // num_stages
    stages = []
    for s in range(num_stages):
        chunk = [params[blocks[s * per + j][1]] for j in range(per)]
        stages.append(jax.tree.map(lambda *xs: jnp.stack(xs, 0), *chunk))
    rest = {k: v for k, v in params.items()
            if not k.startswith(block_prefix)}
    return stack_stage_params(stages), rest


def make_gpipe(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    *,
    num_microbatches: int,
    axis: str = "stage",
    data_axis: str | None = None,
    remat: bool = False,
) -> Callable[[Any, jax.Array], jax.Array]:
    """Build ``fn(stage_params, x) -> y`` running the GPipe schedule.

    ``stage_fn(one_stage_params, acts) -> acts`` must preserve the
    activation shape (homogeneous stages). ``stage_params`` leaves carry a
    leading ``S`` axis (see ``stack_stage_params``); ``x`` is the full
    (local) batch, split internally into ``num_microbatches`` equal
    microbatches. Differentiable in both arguments; ``remat=True`` wraps
    the stage in ``jax.checkpoint`` so the backward pipeline recomputes
    activations instead of holding all ``M + S - 1`` ticks' residuals.
    """
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no axis {axis!r}: {dict(mesh.shape)}")
    num_stages = mesh.shape[axis]
    m = num_microbatches
    if m < 1:
        raise ValueError("num_microbatches must be >= 1")
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(stage_params, x):
        # Inside shard_map: params leaves are (1, ...) — this device's stage.
        local = jax.tree.map(lambda a: a[0], stage_params)
        s = _axis_index_compat(axis)
        batch = x.shape[0]
        if batch % m:
            raise ValueError(
                f"batch {batch} not divisible into {m} microbatches")
        xs = x.reshape(m, batch // m, *x.shape[1:])
        shift = [(i, i + 1) for i in range(num_stages - 1)]

        def tick(carry, t):
            state, outs = carry
            # Stage 0 ingests microbatch t while t < M; later ticks replay
            # the last microbatch into the where()'s dead branch.
            x_t = jax.lax.dynamic_index_in_dim(
                xs, jnp.minimum(t, m - 1), 0, keepdims=False)
            inp = jnp.where(s == 0, x_t, state)
            out = fn(local, inp)
            # The last stage finishes microbatch t - (S-1) at tick t.
            idx = jnp.clip(t - (num_stages - 1), 0, m - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, idx, 0, keepdims=False)
            done = jnp.logical_and(s == num_stages - 1,
                                   t >= num_stages - 1)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(done, out, cur), idx, 0)
            # Hand activations to the successor; stage 0 ignores arrivals
            # (devices with no inbound edge receive zeros).
            state = _ppermute_acct(out, axis, shift) \
                if num_stages > 1 else state
            return (state, outs), None

        outs0 = jnp.zeros_like(xs)
        state0 = jnp.zeros_like(xs[0])
        # comms_scaled: the tick's ppermute traces once, runs per tick.
        with _comms_scaled(m + num_stages - 1):
            (_, outs), _ = jax.lax.scan(
                tick, (state0, outs0), jnp.arange(m + num_stages - 1))
        # Only the last stage holds real outputs; psum replicates them so
        # the out_spec can be P() (or P(data_axis)) without lying.
        outs = _psum_acct(
            jnp.where(s == num_stages - 1, outs, jnp.zeros_like(outs)),
            axis)
        return outs.reshape(batch, *x.shape[1:])

    xspec = P(data_axis) if data_axis else P()
    return _shard_map_compat(
        body, mesh=mesh, in_specs=(P(axis), xspec), out_specs=xspec,
        check_vma=False)
