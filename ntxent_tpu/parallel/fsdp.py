"""Fully-sharded data parallelism (ZeRO-3 style) via GSPMD.

Beyond the reference (SURVEY.md §2.2 lists only declared DP): the memory
side of data parallelism. The shard_map DP path (trainer.py) and the TP
path (tp.py) both keep a FULL parameter + optimizer-state replica per
device; for encoders at ResNet-152/ViT-L scale on small-HBM chips the
replica, its Adam/LARS moments, and the gradients are the footprint that
caps batch size. FSDP shards all three over the ``data`` axis and pays
for it with weight all-gathers at use time.

TPU-idiomatic recipe (same shape as tp.py — annotate, don't hand-roll):

* ``fsdp_param_spec`` maps each array leaf to a ``PartitionSpec`` that
  shards its LARGEST ``data``-divisible dimension; small leaves (norm
  scales, biases — below ``min_shard_elems``) stay replicated, where
  sharding would buy nothing and cost a collective each.
* Optimizer state needs no separate rules: optax states mirror the param
  tree, so placing every array leaf of the TrainState through the same
  shape-driven rule shards Adam moments / LARS traces exactly like their
  parameters (ZeRO's optimizer-state partitioning for free).
* ``make_fsdp_train_step`` jits the ordinary global-batch train step over
  the committed placements. GSPMD inserts the all-gather of each weight
  shard at use and — because the gradient of all-gather is
  reduce-scatter — emits reduce-scattered gradients that land directly
  on the optimizer's shards. No hand-written collectives anywhere; this
  is the ICI-bandwidth-for-HBM-capacity trade compiled from annotations.

Composes with the fused-kernel DP loss story the same way tp.py does:
the loss here is the jnp oracle (GSPMD shards the similarity matmul);
the explicit shard_map + fused Pallas partials path stays the
latency-optimal route when params fit.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.oracle import ntxent_loss
from .mesh import data_sharding

__all__ = [
    "fsdp_param_spec",
    "fsdp_spec_tree",
    "shard_train_state_fsdp",
    "make_fsdp_train_step",
    "param_bytes_per_device",
]

# Leaves smaller than this many elements are replicated: a (64,) BN scale
# sharded 8 ways saves 56 floats and costs an all-gather per use.
MIN_SHARD_ELEMS = 2 ** 14


def fsdp_param_spec(leaf, *, axis: str = "data", axis_size: int,
                    min_shard_elems: int = MIN_SHARD_ELEMS) -> P:
    """PartitionSpec sharding the largest ``axis_size``-divisible dim.

    Ties break toward the TRAILING dimension (weights are (in, out) /
    (H, W, Cin, Cout): the output-feature axis is both the usually-larger
    and the contraction-friendly choice). Replicates when the leaf is
    small or nothing divides.
    """
    if not hasattr(leaf, "ndim") or leaf.ndim == 0 \
            or leaf.size < min_shard_elems:
        return P()
    best = None  # (dim_size, index) — max size, later index wins ties
    for i, d in enumerate(leaf.shape):
        if d % axis_size == 0 and (best is None or d >= best[0]):
            best = (d, i)
    if best is None:
        return P()
    spec = [None] * leaf.ndim
    spec[best[1]] = axis
    return P(*spec)


def fsdp_spec_tree(tree, *, axis: str = "data", axis_size: int):
    """Spec pytree for params or any mirrored optimizer-state tree."""
    return jax.tree_util.tree_map(
        functools.partial(fsdp_param_spec, axis=axis, axis_size=axis_size),
        tree)


def shard_train_state_fsdp(state, mesh: Mesh, *, axis: str = "data"):
    """Place a TrainState on the mesh with FSDP sharding on every array
    leaf (params, Adam/LARS moments, and batch_stats alike — the rule is
    shape-driven, so the mirrored optimizer trees shard with their
    parameters). jit infers program shardings from these placements.

    Aliasing caveat: ``jax.device_put`` onto the mesh reuses the source
    buffer on its home device rather than copying. The returned state is
    therefore NOT independent of ``state`` — donating the original to a
    jitted step afterwards deletes shards out from under the placed copy
    ("Array has been deleted"). Treat the original as consumed, as with
    tp.shard_train_state."""
    axis_size = mesh.shape[axis]

    def place(leaf):
        if not hasattr(leaf, "ndim"):  # static fields (apply_fn, tx, step)
            return leaf
        spec = fsdp_param_spec(leaf, axis=axis, axis_size=axis_size)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, state)


def param_bytes_per_device(state) -> int:
    """Actually-addressable bytes of the first device's param shards —
    the memory claim FSDP exists for (== total/P + replicated smalls)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(state.params):
        if hasattr(leaf, "addressable_shards"):
            s = leaf.addressable_shards[0]
            total += s.data.size * s.data.dtype.itemsize
    return total


def _constrain_batch(x, mesh: Mesh, axis: str):
    return jax.lax.with_sharding_constraint(x, data_sharding(mesh, axis))


def _constrain_state(state, mesh: Mesh, axis: str):
    """Pin every array leaf of the OUTPUT state to its FSDP spec.

    Without this, GSPMD freely picks output shardings (e.g. splitting a
    replicated (64,) BN bias over ``data``), and feeding the returned
    state back into the compiled step then fails with a passed-vs-required
    sharding mismatch on the second call.
    """
    axis_size = mesh.shape[axis]

    def pin(leaf):
        if not hasattr(leaf, "ndim"):
            return leaf
        spec = fsdp_param_spec(leaf, axis=axis, axis_size=axis_size)
        return jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(pin, state)


def make_fsdp_train_step(
    mesh: Mesh,
    temperature: float = 0.1,
    *,
    axis: str = "data",
    has_batch_stats: bool = True,
    remat: bool = False,
) -> Callable:
    """Fully-sharded SimCLR train step: batch sharded over ``axis``,
    weights/optimizer sharded per ``fsdp_param_spec``; GSPMD derives the
    gather-on-use / reduce-scatter schedule. ``has_batch_stats`` default
    True (the flagship FSDP target is the ResNet family, which carries
    BatchNorm; the global-batch program gives cross-replica statistics by
    construction). ``remat=True`` rematerializes the encoder forward —
    the usual FSDP companion, since both trade compute/comm for HBM.
    """

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state, v1, v2):
        v1c = _constrain_batch(v1, mesh, axis)
        v2c = _constrain_batch(v2, mesh, axis)

        def encode(params, both):
            if has_batch_stats:
                variables = {"params": params,
                             "batch_stats": state.batch_stats}
                return state.apply_fn(variables, both, train=True,
                                      mutable=["batch_stats"])
            return state.apply_fn({"params": params}, both, train=True), None

        if remat:
            encode = jax.checkpoint(encode, static_argnums=())

        def loss_fn(params):
            both = jnp.concatenate([v1c, v2c], axis=0)
            z, updates = encode(params, both)
            new_stats = updates["batch_stats"] if has_batch_stats else None
            z = _constrain_batch(z, mesh, axis)
            return ntxent_loss(z, temperature), new_stats

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        state2 = state.apply_gradients(grads=grads)
        if new_stats is not None:
            state2 = state2.replace(batch_stats=new_stats)
        return _constrain_state(state2, mesh, axis), {"loss": loss}

    return train_step
