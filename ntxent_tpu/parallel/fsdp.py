"""Fully-sharded data parallelism (ZeRO-3 style) via GSPMD.

Beyond the reference (SURVEY.md §2.2 lists only declared DP): the memory
side of data parallelism. The shard_map DP path (trainer.py) and the TP
path (tp.py) both keep a FULL parameter + optimizer-state replica per
device; for encoders at ResNet-152/ViT-L scale on small-HBM chips the
replica, its Adam/LARS moments, and the gradients are the footprint that
caps batch size. FSDP shards all three over the ``data`` axis and pays
for it with weight all-gathers at use time.

TPU-idiomatic recipe (same shape as tp.py — annotate, don't hand-roll):

* ``fsdp_param_spec`` maps each array leaf to a ``PartitionSpec`` that
  shards its LARGEST ``data``-divisible dimension; small leaves (norm
  scales, biases — below ``min_shard_elems``) stay replicated, where
  sharding would buy nothing and cost a collective each.
* Optimizer state needs no separate rules: optax states mirror the param
  tree, so placing every array leaf of the TrainState through the same
  shape-driven rule shards Adam moments / LARS traces exactly like their
  parameters (ZeRO's optimizer-state partitioning for free).
* ``make_fsdp_train_step`` jits the ordinary global-batch train step over
  the committed placements. GSPMD inserts the all-gather of each weight
  shard at use and — because the gradient of all-gather is
  reduce-scatter — emits reduce-scattered gradients that land directly
  on the optimizer's shards. No hand-written collectives anywhere; this
  is the ICI-bandwidth-for-HBM-capacity trade compiled from annotations.

Composes with the fused-kernel DP loss: the default train step embeds
the shard_map fused-partial NT-Xent (``dist_loss.resolve_local_ntxent``
— the same strip/pair bodies the explicit DP trainer uses) inside the
GSPMD-sharded program, so ZeRO-3 parameter sharding and the Pallas
fused loss run together in one jitted step (``loss_impl="oracle"``
keeps the all-jnp GSPMD-sharded similarity matmul for A/B).

Hybrid ZeRO on multi-slice pods: pass a 2-axis ``('dcn', 'data')``
hybrid mesh with ``batch_axes=('dcn', 'data')`` and the default
``axis='data'`` — the batch (and the loss all-gather's bulky, once-per-
step traffic) spans slices over DCN while the per-layer weight
all-gathers GSPMD inserts at use stay on intra-slice ICI, because the
parameter shards never cross slices (ADVICE r3 #1).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.oracle import ntxent_loss

__all__ = [
    "fsdp_param_spec",
    "fsdp_spec_tree",
    "shard_train_state_fsdp",
    "make_fsdp_train_step",
    "make_fsdp_clip_train_step",
    "param_bytes_per_device",
]

# Leaves smaller than this many elements are replicated: a (64,) BN scale
# sharded 8 ways saves 56 floats and costs an all-gather per use.
MIN_SHARD_ELEMS = 2 ** 14


def largest_divisible_dim(shape, axis_size: int, taken=()) -> int | None:
    """Index of the largest ``axis_size``-divisible dim not in ``taken``.

    Ties break toward the TRAILING dimension (weights are (in, out) /
    (H, W, Cin, Cout): the output-feature axis is both the usually-larger
    and the contraction-friendly choice). None when nothing divides. The
    ONE copy of the FSDP dim-selection policy — tp.tp_fsdp_param_spec
    composes it with the Megatron rule via ``taken``.
    """
    best = None  # (dim_size, index) — max size, later index wins ties
    for i, d in enumerate(shape):
        if i in taken or d % axis_size:
            continue
        if best is None or d >= best[0]:
            best = (d, i)
    return None if best is None else best[1]


def fsdp_param_spec(leaf, *, axis: str = "data", axis_size: int,
                    min_shard_elems: int = MIN_SHARD_ELEMS) -> P:
    """PartitionSpec sharding the largest ``axis_size``-divisible dim
    (``largest_divisible_dim``). Replicates when the leaf is small or
    nothing divides."""
    if not hasattr(leaf, "ndim") or leaf.ndim == 0 \
            or leaf.size < min_shard_elems:
        return P()
    i = largest_divisible_dim(leaf.shape, axis_size)
    if i is None:
        return P()
    spec = [None] * leaf.ndim
    spec[i] = axis
    return P(*spec)


def fsdp_spec_tree(tree, *, axis: str = "data", axis_size: int):
    """Spec pytree for params or any mirrored optimizer-state tree."""
    return jax.tree_util.tree_map(
        functools.partial(fsdp_param_spec, axis=axis, axis_size=axis_size),
        tree)


def shard_train_state_fsdp(state, mesh: Mesh, *, axis: str = "data"):
    """Place a TrainState on the mesh with FSDP sharding on every array
    leaf (params, Adam/LARS moments, and batch_stats alike — the rule is
    shape-driven, so the mirrored optimizer trees shard with their
    parameters). jit infers program shardings from these placements.

    Aliasing caveat: ``jax.device_put`` onto the mesh reuses the source
    buffer on its home device rather than copying. The returned state is
    therefore NOT independent of ``state`` — donating the original to a
    jitted step afterwards deletes shards out from under the placed copy
    ("Array has been deleted"). Treat the original as consumed, as with
    tp.shard_train_state."""
    axis_size = mesh.shape[axis]

    def place(leaf):
        if not hasattr(leaf, "ndim"):  # static fields (apply_fn, tx, step)
            return leaf
        spec = fsdp_param_spec(leaf, axis=axis, axis_size=axis_size)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, state)


def param_bytes_per_device(state) -> int:
    """Actually-addressable bytes of the first device's param shards —
    the memory claim FSDP exists for (== total/P + replicated smalls)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(state.params):
        if hasattr(leaf, "addressable_shards"):
            s = leaf.addressable_shards[0]
            total += s.data.size * s.data.dtype.itemsize
    return total


def _constrain_state(state, mesh: Mesh, axis: str):
    """Pin every array leaf of the OUTPUT state to its FSDP spec.

    Without this, GSPMD freely picks output shardings (e.g. splitting a
    replicated (64,) BN bias over ``data``), and feeding the returned
    state back into the compiled step then fails with a passed-vs-required
    sharding mismatch on the second call.
    """
    axis_size = mesh.shape[axis]

    def pin(leaf):
        if not hasattr(leaf, "ndim"):
            return leaf
        spec = fsdp_param_spec(leaf, axis=axis, axis_size=axis_size)
        return jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(pin, state)


def _resolve_batch_axes(mesh: Mesh, axis: str, batch_axes):
    """(batch_axes tuple, shard_map collective axis arg, device count).

    ``batch_axes`` defaults to every mesh axis; the parameter axis must be
    among them (its gradient reduce-scatter rides the batch program).
    Normalization and the single-vs-tuple collective-axis convention live
    in ``dist_loss._resolve_loss_axes`` (one copy); this adds only the
    membership validation.
    """
    from .dist_loss import _resolve_loss_axes

    if batch_axes is None:
        batch_axes = tuple(mesh.axis_names)
    axes, loss_axis, n = _resolve_loss_axes(mesh, batch_axes)
    if axis not in axes:
        raise ValueError(f"param axis {axis!r} must be one of the batch "
                         f"axes {axes} (its gradient reduce-scatter "
                         "rides the batch program)")
    return axes, loss_axis, n


def _row_constrainer(mesh: Mesh, batch_axes: tuple):
    """Closure pinning an array's leading dim over ``batch_axes``."""
    sharding = NamedSharding(mesh, P(batch_axes))

    def constrain(x):
        return jax.lax.with_sharding_constraint(x, sharding)

    return constrain


def make_fsdp_train_step(
    mesh: Mesh,
    temperature: float = 0.1,
    *,
    axis: str = "data",
    batch_axes: str | tuple | None = None,
    has_batch_stats: bool = True,
    remat: bool = False,
    loss_impl: str = "strip",
    moe_aux_weight: float = 0.0,
    interpret: bool | None = None,
) -> Callable:
    """Fully-sharded SimCLR train step: batch sharded over ``batch_axes``
    (default: every mesh axis), weights/optimizer sharded over ``axis``
    per ``fsdp_param_spec``; GSPMD derives the gather-on-use /
    reduce-scatter schedule for the weights while the loss runs as the
    shard_map fused-partial NT-Xent over the batch axes.

    ``loss_impl``: ``"strip"`` (default) / ``"pair"`` — the fused Pallas
    per-device bodies shared with the explicit DP trainer
    (``dist_loss.resolve_local_ntxent``); ``"oracle"`` — the all-jnp
    global loss whose similarity matmul GSPMD shards (the pre-round-4
    behavior, kept for A/B).

    On a 1-axis mesh ``batch_axes == (axis,)`` and this is flat ZeRO-3.
    On a hybrid ``('dcn', 'data')`` mesh the defaults give hybrid ZeRO:
    batch over all devices, parameter shards confined to the intra-slice
    ``data`` (ICI) axis and replicated across slices, so per-layer weight
    all-gathers never touch DCN.

    ``has_batch_stats`` default True (the flagship FSDP target is the
    ResNet family, which carries BatchNorm; the global-batch program
    gives cross-replica statistics by construction). ``remat=True``
    rematerializes the encoder forward — the usual FSDP companion, since
    both trade compute/comm for HBM.

    ``moe_aux_weight > 0`` adds the MoE towers' load-balance aux loss,
    computed once over the global batch by the GSPMD program (no
    per-shard pmean estimator needed, unlike the shard_map DP step) and
    reported under ``metrics["moe_aux"]``. Expert weights shard by the
    same shape-driven rule as every other leaf (ZeRO-3 memory scaling);
    expert COMPUTE stays data-parallel here — the all-to-all
    expert-parallel schedule remains the shard_map EP path's
    (``parallel/moe.py``).
    """
    batch_axes, loss_axis, _ = _resolve_batch_axes(mesh, axis, batch_axes)

    if loss_impl == "oracle":
        sharded_loss = None
    else:
        # The ONE dispatch point for fused NT-Xent bodies — same factory
        # the explicit shard_map DP trainer uses, tuple-axis form.
        from .dist_loss import make_sharded_ntxent

        sharded_loss = make_sharded_ntxent(
            mesh, temperature, axis=loss_axis, interpret=interpret,
            impl=loss_impl)

    constrain_rows = _row_constrainer(mesh, batch_axes)
    collect = moe_aux_weight > 0.0

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state, v1, v2):
        v1c = constrain_rows(v1)
        v2c = constrain_rows(v2)

        def encode(params, both):
            variables = {"params": params}
            mutable = []
            if has_batch_stats:
                variables["batch_stats"] = state.batch_stats
                mutable.append("batch_stats")
            if collect:
                mutable.append("intermediates")
            if not mutable:
                return state.apply_fn(variables, both, train=True), {}
            return state.apply_fn(variables, both, train=True,
                                  mutable=mutable)

        if remat:
            encode = jax.checkpoint(encode, static_argnums=())

        def loss_fn(params):
            both = jnp.concatenate([v1c, v2c], axis=0)
            z, updates = encode(params, both)
            new_stats = updates["batch_stats"] if has_batch_stats else None
            if collect:
                from .moe import moe_aux_from

                aux = moe_aux_from(updates)
            else:
                aux = 0.0
            if sharded_loss is None:
                z = constrain_rows(z)
                loss = ntxent_loss(z, temperature)
            else:
                n = v1c.shape[0]
                # Split the stacked (2N, D) embeddings back into views:
                # the fused bodies take (z1, z2) row-sharded over the
                # batch axes and rebuild the [view1; view2] global layout
                # internally (mesh.local_row_gids).
                loss = sharded_loss(constrain_rows(z[:n]),
                                    constrain_rows(z[n:]))
            return loss + moe_aux_weight * aux, (new_stats, aux)

        (loss, (new_stats, aux)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        state2 = state.apply_gradients(grads=grads)
        if new_stats is not None:
            state2 = state2.replace(batch_stats=new_stats)
        metrics = {"loss": loss}
        if collect:
            metrics["moe_aux"] = aux
        return _constrain_state(state2, mesh, axis), metrics

    return train_step


def make_fsdp_clip_train_step(
    mesh: Mesh,
    *,
    axis: str = "data",
    batch_axes: str | tuple | None = None,
    remat: bool = False,
    loss_impl: str = "dual",
    moe_aux_weight: float = 0.0,
    interpret: bool | None = None,
) -> Callable:
    """Fully-sharded CLIP train step: the dual-tower analog of
    ``make_fsdp_train_step`` (round 4 — the CLI previously refused
    ``--fsdp`` for the CLIP objective outright).

    ViT-L/H-scale dual towers with AdamW moments are exactly where ZeRO-3
    pays: params + both optimizer moments shard over ``axis`` per
    ``fsdp_param_spec`` while the (images, tokens) batch shards over
    ``batch_axes`` (default: every mesh axis — hybrid ZeRO on a
    ``('dcn', 'data')`` mesh, like the SimCLR step).

    ``loss_impl``: ``"dual"`` (default) / ``"twopass"`` — the fused
    partial InfoNCE bodies shared with the shard_map DP trainer
    (``dist_loss.resolve_local_infonce``), run as a shard_map inside the
    GSPMD program; ``"oracle"`` — the all-jnp global InfoNCE whose
    similarity matmul GSPMD shards.

    ``state.apply_fn(variables, images, tokens)`` must return
    ``(image_embeds, text_embeds, scale)`` (models/clip.py); the
    learnable logit scale's gradient flows through either loss path.
    ``moe_aux_weight > 0`` adds the MoE towers' load-balance aux loss —
    computed once over the global batch by the GSPMD program (no
    per-shard pmean estimator needed, unlike the shard_map DP step).
    """
    batch_axes, loss_axis, _ = _resolve_batch_axes(mesh, axis, batch_axes)
    collect = moe_aux_weight > 0.0

    if loss_impl == "oracle":
        sharded_loss = None
    else:
        # The ONE dispatch point for fused InfoNCE bodies — same factory
        # the shard_map DP CLIP trainer uses, tuple-axis form.
        from .dist_loss import make_sharded_infonce

        sharded_loss = make_sharded_infonce(
            mesh, axis=loss_axis, interpret=interpret, impl=loss_impl)

    constrain_rows = _row_constrainer(mesh, batch_axes)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state, images, tokens):
        imc = constrain_rows(images)
        tkc = constrain_rows(tokens)

        def fwd(params, im, tk):
            if not collect:
                zi, zt, scale = state.apply_fn(
                    {"params": params}, im, tk, train=True)
                return zi, zt, scale, 0.0
            from .moe import moe_aux_from

            (zi, zt, scale), updates = state.apply_fn(
                {"params": params}, im, tk, train=True,
                mutable=["intermediates"])
            return zi, zt, scale, moe_aux_from(updates)

        if remat:
            fwd = jax.checkpoint(fwd)

        def loss_fn(params):
            zi, zt, scale, aux = fwd(params, imc, tkc)
            if sharded_loss is None:
                from ..ops.oracle import info_nce_loss

                zi_c = constrain_rows(zi)
                zt_c = constrain_rows(zt)
                loss = info_nce_loss(zi_c, zt_c, temperature=1.0 / scale)
            else:
                loss = sharded_loss(constrain_rows(zi), constrain_rows(zt),
                                    scale)
            return loss + moe_aux_weight * aux, aux

        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        state2 = state.apply_gradients(grads=grads)
        metrics = {"loss": loss}
        if collect:
            metrics["moe_aux"] = aux
        return _constrain_state(state2, mesh, axis), metrics

    return train_step
