from ntxent_tpu.parallel.dist_loss import (
    info_nce_loss_distributed,
    make_sharded_infonce,
    make_sharded_ntxent,
    ntxent_loss_distributed,
)
from ntxent_tpu.parallel.mesh import (
    create_hybrid_mesh,
    create_mesh,
    data_sharding,
    global_batch,
    init_distributed,
    local_row_gids,
    process_info,
    replicate_state,
    replicated_sharding,
    sharded_prefetch,
)
from ntxent_tpu.parallel.pair import (
    make_pair_ntxent,
    ntxent_loss_pair,
)
from ntxent_tpu.parallel.moe import (
    MoEMlp,
    init_moe_params,
    make_expert_parallel_moe,
    switch_moe,
)
from ntxent_tpu.parallel.pp import (
    make_gpipe,
    pipeline_stage_params,
    stack_stage_params,
)
from ntxent_tpu.parallel.ring_attention import (
    attention_oracle,
    blockwise_attention,
    make_ring_attention,
    make_ulysses_attention,
)
from ntxent_tpu.parallel.ring import (
    info_nce_loss_ring,
    make_ring_infonce,
    make_ring_ntxent,
    ntxent_loss_ring,
)
from ntxent_tpu.parallel.fsdp import (
    fsdp_param_spec,
    make_fsdp_clip_train_step,
    make_fsdp_train_step,
    param_bytes_per_device,
    shard_train_state_fsdp,
)
from ntxent_tpu.parallel.tp import (
    make_tp_clip_train_step,
    make_tp_simclr_train_step,
    param_spec_tree,
    shard_train_state,
    shard_train_state_tp_fsdp,
    tp_fsdp_param_spec,
    tp_fsdp_spec_fn,
    tp_param_spec,
)

__all__ = [
    "create_mesh",
    "create_hybrid_mesh",
    "data_sharding",
    "global_batch",
    "init_distributed",
    "local_row_gids",
    "process_info",
    "make_pair_ntxent",
    "ntxent_loss_pair",
    "make_gpipe",
    "pipeline_stage_params",
    "stack_stage_params",
    "MoEMlp",
    "init_moe_params",
    "make_expert_parallel_moe",
    "switch_moe",
    "replicate_state",
    "replicated_sharding",
    "sharded_prefetch",
    "make_sharded_ntxent",
    "ntxent_loss_distributed",
    "make_ring_ntxent",
    "ntxent_loss_ring",
    "info_nce_loss_distributed",
    "make_sharded_infonce",
    "info_nce_loss_ring",
    "make_ring_infonce",
    "attention_oracle",
    "blockwise_attention",
    "make_ring_attention",
    "make_ulysses_attention",
    "tp_param_spec",
    "param_spec_tree",
    "shard_train_state",
    "shard_train_state_tp_fsdp",
    "tp_fsdp_param_spec",
    "tp_fsdp_spec_fn",
    "make_tp_simclr_train_step",
    "make_tp_clip_train_step",
    "fsdp_param_spec",
    "make_fsdp_clip_train_step",
    "make_fsdp_train_step",
    "param_bytes_per_device",
    "shard_train_state_fsdp",
]
