"""Distributed data-parallel NT-Xent: the NCCL-all-gather role, TPU-native.

The classic distributed-SimCLR recipe the reference's repo name promised but
never implemented (SURVEY.md §0.1, §2.2: MPI/NCCL are link-only CMake
options with zero call sites) is: every rank runs the encoder on its local
batch shard, all-gathers the embeddings, computes the global-batch loss, and
all-reduces gradients. Here that becomes:

* ``lax.all_gather(z_local, 'data')`` over the mesh — XLA lowers it onto ICI
  (intra-slice) / DCN (cross-slice); no hand-written communicator.
* each device computes only its **local rows x global columns** block of the
  similarity matrix via the fused Pallas kernel (``ntxent_partial_fused``)
  — compute is sharded 1/P per device, unlike naive replicated-loss setups.
* ``lax.psum`` of the partial loss — and, through AD, of the gradients: the
  backward of all_gather is the reduce-scatter hand-written NCCL SimCLR
  implementations must code manually; ``shard_map`` + ``jax.grad`` derive it
  (a correctness obligation verified in tests/test_distributed.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.infonce_pallas import (
    info_nce_dual_partial,
    info_nce_partial_fused,
    resolve_scale,
)
from ..ops.ntxent_pallas import _exp0, _log_l, ntxent_partial_fused
from .mesh import all_gather as _all_gather_acct
from .mesh import axis_index as _axis_index_compat
from .mesh import axis_index_plain as _axis_index_plain
from .mesh import chunk_bounds
from .mesh import comms_scaled as _comms_scaled
from .mesh import local_row_gids
from .mesh import pcast as _pcast_compat
from .mesh import ppermute as _ppermute_acct
from .mesh import psum as _psum_acct
from .mesh import shard_map as _shard_map_compat

__all__ = ["ntxent_loss_distributed", "make_sharded_ntxent",
           "local_ntxent_allgather", "local_ntxent_chunked",
           "resolve_local_ntxent",
           "info_nce_loss_distributed",
           "make_sharded_infonce", "local_infonce_allgather",
           "local_infonce_dual", "resolve_local_infonce"]

_NEG_INF = -1e30


def _resolve_loss_axes(mesh: Mesh, axis):
    """(axes tuple, collective axis arg, device count) for a loss that may
    span one mesh axis (the plain DP case) or several (hybrid meshes —
    the FSDP step's batch axes). A single axis keeps the string form for
    collectives (identical semantics, simpler HLO names); multiple axes
    pass as the tuple ``lax`` collectives accept directly."""
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return axes, axes[0] if len(axes) == 1 else axes, n


def local_ntxent_allgather(z1_local, z2_local, temperature, axis, num_devices,
                           interpret=None):
    """Per-device global-batch NT-Xent body (call inside shard_map/psum
    context): all-gather both views, fused local-rows x global-cols partial
    loss, psum to the global mean. Shared by the standalone distributed loss
    below and the trainer's sharded train step."""
    n_local = z1_local.shape[0]
    # tiled=True concatenates shards along axis 0: (n_local, D) -> (N, D).
    z1_g = _all_gather_acct(z1_local, axis, tiled=True)
    z2_g = _all_gather_acct(z2_local, axis, tiled=True)
    z_global = jnp.concatenate([z1_g, z2_g], axis=0)          # (2N, D)
    z_local = jnp.concatenate([z1_local, z2_local], axis=0)   # (2n, D)
    gid = local_row_gids(axis, n_local, num_devices)
    loss_sum = ntxent_partial_fused(
        z_local, z_global, gid, temperature, interpret=interpret
    )
    return _psum_acct(loss_sum, axis) / z_global.shape[0]


def local_ntxent_chunked(z1_local, z2_local, temperature, axis, num_devices,
                         interpret=None, chunks=None):
    """Per-device global-batch NT-Xent body with the chunked ring-overlap
    schedule (ISSUE 19 — arxiv 2305.06942's fused computation-collective
    decomposition applied to the embedding all-gather).

    Numerically the same loss as ``local_ntxent_allgather``, but the
    dense all-gather never happens: the local stacked block circulates
    around the ring in ``chunks`` independent ``ppermute`` pieces, and
    each arriving chunk is folded into flash-style online-softmax
    statistics (running max m, running sum l) against the local rows.
    Because chunk k's fold and chunk k+1's send are independent ops in
    the traced graph, the async scheduler overlaps the transfer with the
    similarity compute — and total ring bytes are EXACTLY the dense
    path's two all-gathers ((P-1) * 2*n_local*D payload per device;
    test-pinned via the graph census). Visiting-row gids are derived
    arithmetically from the hop index (never circulated), which is what
    makes the byte parity exact. Each chunk rides the ambient
    ``collective_precision`` policy independently (int8 per-row scales
    quantize per chunk; the STE custom_vjp backward reuses the reverse
    ring at full precision), so the PR 11 byte cut survives chunking.

    The backward pass needs no hand schedule: AD through the scan
    transposes every chunk ppermute into the reverse-direction hop, so
    the gradient exchange is the same chunked ring run backwards.

    ``chunks=None`` resolves via ``ops.autotune.resolve_ring_chunks``
    (explicit override -> cached measured vote -> CPU-safe static
    heuristic — pure given (batch, dim, mesh), never re-measured
    per step).
    """
    from ..ops.autotune import resolve_ring_chunks

    n_local, dim = z1_local.shape
    rows = 2 * n_local
    n_total = n_local * num_devices
    two_n = 2 * n_total
    inv_t = 1.0 / temperature

    z_local = jnp.concatenate([z1_local, z2_local], axis=0)   # (2n, D)
    my_gid = local_row_gids(axis, n_local, num_devices)
    # Plain-spelled axis_index, NOT the compat shim: this is a plain
    # shard_map body (no custom_vjp), and the shim's old-jax psum_scatter
    # fallback would put an undeclared 4-byte collective in the scan
    # body, breaking the census == declared exactness the fwd audit pins.
    d = _axis_index_plain(axis)

    # Positives are device-local in the stacked-view layout (view-1 row i
    # pairs with view-2 row i of the same device) — same as ring.py.
    pos = jnp.sum(z1_local * z2_local, axis=-1, dtype=jnp.float32) * inv_t
    pos = jnp.concatenate([pos, pos])

    n_chunks = resolve_ring_chunks(rows, dim, num_devices,
                                   z_local.dtype, chunks=chunks)
    bounds = chunk_bounds(rows, n_chunks)
    perm = [(i, (i + 1) % num_devices) for i in range(num_devices)]

    def fold_chunk(blk, src, lo, hi, m, l):
        """Fold one arriving chunk (local rows [lo, hi) of the block
        that started on device ``src``) into the running stats. The
        chunk's gids follow arithmetically from (src, row index) in the
        stacked layout — no gid payload rides the ring."""
        idx = jnp.arange(lo, hi, dtype=jnp.int32)
        bgid = jnp.where(idx < n_local,
                         src * n_local + idx,
                         n_total + src * n_local + (idx - n_local))
        s = jnp.dot(z_local, blk.T, preferred_element_type=jnp.float32)
        s = s * inv_t
        s = jnp.where(my_gid[:, None] == bgid[None, :], _NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        l = l * jnp.exp(m - m_new) \
            + jnp.sum(_exp0(s - m_new[:, None]), axis=1)
        return m_new, l

    def step(carry, t):
        blocks, m, l = carry
        # After t hops this device holds the block that started t seats
        # upstream on the ring.
        src = (d - t) % num_devices
        nxt = []
        for c, (lo, hi) in enumerate(bounds):
            # The onward send is issued BEFORE the fold consumes the
            # chunk: the two are independent, so chunk c+1's transfer
            # overlaps chunk c's similarity block.
            nxt.append(_ppermute_acct(blocks[c], axis, perm))
            m, l = fold_chunk(blocks[c], src, lo, hi, m, l)
        return (tuple(nxt), m, l), None

    init_blocks = tuple(z_local[lo:hi] for lo, hi in bounds)
    # pcast to 'varying': the m/l statistics start device-invariant but
    # become varying across the ring axis inside the scan.
    init = (
        init_blocks,
        _pcast_compat(jnp.full((rows,), _NEG_INF, jnp.float32),
                      (axis,), to="varying"),
        _pcast_compat(jnp.zeros((rows,), jnp.float32),
                      (axis,), to="varying"),
    )
    # P-1 exchanges; the final visiting chunks fold outside the scan
    # (no wasted hop home). comms_scaled: the body's chunk sends trace
    # once but run P-1 times.
    with _comms_scaled(num_devices - 1):
        (blocks, m, l), _ = jax.lax.scan(
            step, init, jnp.arange(num_devices - 1, dtype=jnp.int32))
    src = (d - (num_devices - 1)) % num_devices
    for c, (lo, hi) in enumerate(bounds):
        m, l = fold_chunk(blocks[c], src, lo, hi, m, l)
    lse = m + _log_l(l)
    loss_sum = jnp.sum(lse - pos)
    return _psum_acct(loss_sum, axis) / two_n


def resolve_local_ntxent(impl: str):
    """The per-device NT-Xent body for an impl name — the ONE dispatch
    point shared by make_sharded_ntxent and the sharded train-step
    factory. Bodies share the signature
    ``(z1_local, z2_local, temperature, axis, num_devices, interpret)``
    (``"chunked"`` additionally accepts a trailing ``chunks`` kwarg)."""
    if impl == "pair":
        from .pair import pair_body

        return pair_body
    if impl == "strip":
        return local_ntxent_allgather
    if impl == "chunked":
        return local_ntxent_chunked
    raise ValueError(f"unknown NT-Xent impl {impl!r}")


def make_sharded_ntxent(
    mesh: Mesh,
    temperature: float = 0.07,
    axis: str = "data",
    interpret: bool | None = None,
    impl: str = "strip",
    ring_chunks: int | None = None,
):
    """Build a jit-able global-batch NT-Xent over ``mesh``.

    Returns ``loss_fn(z1, z2) -> scalar`` where z1, z2 are the two augmented
    views, (N, D) each, sharded (or shardable) along ``axis``. The scalar is
    replicated; gradients through it are correct per-shard gradients.

    ``impl="strip"`` (default): every device walks its local-rows x
    global-cols strip. ``impl="pair"``: balanced symmetric shard-pair
    schedule — each global tile walked once across the mesh, ~2.2x fewer
    loss matmuls at P=8 (see parallel/pair.py for the trade-offs).
    ``impl="chunked"``: the ring-overlap schedule (ISSUE 19) — same
    bytes as "strip", decomposed into per-chunk neighbor hops that
    overlap transfer with the similarity compute; ``ring_chunks``
    overrides the autotuned/heuristic chunk count (ignored by the
    other impls).

    ``axis`` may be a tuple of mesh axes (e.g. ``('dcn', 'data')`` on a
    hybrid mesh): the batch then shards over their product and the
    bodies' collectives run over the combined axes — how the FSDP step
    embeds this loss inside a GSPMD program (fsdp.py).
    """
    axes, body_axis, num_devices = _resolve_loss_axes(mesh, axis)

    extra = {"chunks": ring_chunks} if impl == "chunked" else {}
    body = functools.partial(
        resolve_local_ntxent(impl),
        temperature=float(temperature),
        axis=body_axis,
        num_devices=num_devices,
        interpret=interpret,
        **extra,
    )
    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # annotation, so JAX's vma checker cannot see through the kernel.
    return _shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(P(axes), P(axes)),
        out_specs=P(),
        check_vma=False,
    )


def ntxent_loss_distributed(
    z1: jax.Array,
    z2: jax.Array,
    mesh: Mesh,
    temperature: float = 0.07,
    axis: str = "data",
    interpret: bool | None = None,
) -> jax.Array:
    """Global-batch canonical NT-Xent over a device mesh (one-shot form)."""
    return make_sharded_ntxent(mesh, temperature, axis, interpret)(z1, z2)


def local_infonce_allgather(za_local, zb_local, scale, axis,
                            interpret=None):
    """Per-device global-batch InfoNCE body (call inside shard_map).

    The CLIP analog of ``local_ntxent_allgather``: all-gather both modality
    shards, then compute this device's local-rows x global-cols block of
    each direction of the cross-modal matrix with the fused partial kernel.
    Row direction: local za rows vs gathered zb; column direction: local zb
    rows vs gathered za (the transpose's rows). ``scale`` (CLIP's learnable
    ``exp(logit_scale)``) is traced and differentiable; its gradient — and
    the reduce-scatter gradient of both all-gathers — falls out of AD.
    """
    n_local = za_local.shape[0]
    za_g = _all_gather_acct(za_local, axis, tiled=True)    # (N, D)
    zb_g = _all_gather_acct(zb_local, axis, tiled=True)
    n = za_g.shape[0]
    d = _axis_index_compat(axis)
    gid = d * n_local + jnp.arange(n_local, dtype=jnp.int32)
    loss_a = info_nce_partial_fused(za_local, zb_g, gid, scale=scale,
                                    interpret=interpret)
    loss_b = info_nce_partial_fused(zb_local, za_g, gid, scale=scale,
                                    interpret=interpret)
    return _psum_acct(loss_a + loss_b, axis) / (2 * n)


def local_infonce_dual(za_local, zb_local, scale, axis, interpret=None):
    """Per-device global-batch InfoNCE body — dual-direction variant.

    Half the communication and half the forward matmuls of
    ``local_infonce_allgather``: only ``zb`` is gathered, and ONE walk of
    the local-rows x global-cols block feeds both softmax directions (the
    column statistics are completed by an (N,)-vector logsumexp merge
    across devices — a cheap collective instead of a second gathered
    matmul pass). Gradients: za's flow directly from the combined-G
    kernels, zb's return through the all_gather as a reduce-scatter, and
    the learnable scale's psum through shard_map AD.
    """
    n_local = za_local.shape[0]
    zb_g = _all_gather_acct(zb_local, axis, tiled=True)     # (N, D)
    n = zb_g.shape[0]
    d = _axis_index_compat(axis)
    gid = d * n_local + jnp.arange(n_local, dtype=jnp.int32)
    part = info_nce_dual_partial(za_local, zb_g, gid, axis, scale=scale,
                                 interpret=interpret)
    return _psum_acct(part, axis) / (2 * n)


def resolve_local_infonce(impl: str):
    """The per-device InfoNCE body for an impl name — the ONE dispatch
    point shared by make_sharded_infonce and the CLIP train-step factory."""
    impls = {"dual": local_infonce_dual,
             "twopass": local_infonce_allgather}
    try:
        return impls[impl]
    except KeyError:
        raise ValueError(
            f"unknown InfoNCE impl {impl!r}; choose from {sorted(impls)}")


def make_sharded_infonce(
    mesh: Mesh,
    axis: str = "data",
    interpret: bool | None = None,
    impl: str = "dual",
):
    """Build a jit-able global-batch InfoNCE over ``mesh``.

    Returns ``loss_fn(za, zb, scale) -> scalar`` with za, zb (N, D) paired
    modality embeddings sharded along ``axis`` and ``scale`` replicated
    (differentiable — psum of its per-shard gradients is AD-derived).

    ``impl="dual"`` (default) gathers one modality and walks the
    similarity block once for both directions; ``impl="twopass"`` is the
    gather-both/walk-twice form (kept for A/B comparison).

    ``axis`` may be a tuple of mesh axes, as in ``make_sharded_ntxent``.
    """
    local = resolve_local_infonce(impl)
    axes, body_axis, _ = _resolve_loss_axes(mesh, axis)

    def body(za_local, zb_local, scale):
        return local(za_local, zb_local, scale, body_axis, interpret)

    return _shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(P(axes), P(axes), P()),
        out_specs=P(),
        check_vma=False,
    )


def info_nce_loss_distributed(
    za: jax.Array,
    zb: jax.Array,
    mesh: Mesh,
    temperature: float = 0.07,
    *,
    scale: jax.Array | float | None = None,
    axis: str = "data",
    interpret: bool | None = None,
) -> jax.Array:
    """Global-batch symmetric InfoNCE over a device mesh (one-shot form)."""
    return make_sharded_infonce(mesh, axis, interpret)(
        za, zb, resolve_scale(temperature, scale))
