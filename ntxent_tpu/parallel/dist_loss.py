"""Distributed data-parallel NT-Xent: the NCCL-all-gather role, TPU-native.

The classic distributed-SimCLR recipe the reference's repo name promised but
never implemented (SURVEY.md §0.1, §2.2: MPI/NCCL are link-only CMake
options with zero call sites) is: every rank runs the encoder on its local
batch shard, all-gathers the embeddings, computes the global-batch loss, and
all-reduces gradients. Here that becomes:

* ``lax.all_gather(z_local, 'data')`` over the mesh — XLA lowers it onto ICI
  (intra-slice) / DCN (cross-slice); no hand-written communicator.
* each device computes only its **local rows x global columns** block of the
  similarity matrix via the fused Pallas kernel (``ntxent_partial_fused``)
  — compute is sharded 1/P per device, unlike naive replicated-loss setups.
* ``lax.psum`` of the partial loss — and, through AD, of the gradients: the
  backward of all_gather is the reduce-scatter hand-written NCCL SimCLR
  implementations must code manually; ``shard_map`` + ``jax.grad`` derive it
  (a correctness obligation verified in tests/test_distributed.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.infonce_pallas import (
    info_nce_dual_partial,
    info_nce_partial_fused,
    resolve_scale,
)
from ..ops.ntxent_pallas import ntxent_partial_fused
from .mesh import all_gather as _all_gather_acct
from .mesh import axis_index as _axis_index_compat
from .mesh import local_row_gids
from .mesh import psum as _psum_acct
from .mesh import shard_map as _shard_map_compat

__all__ = ["ntxent_loss_distributed", "make_sharded_ntxent",
           "local_ntxent_allgather", "resolve_local_ntxent",
           "info_nce_loss_distributed",
           "make_sharded_infonce", "local_infonce_allgather",
           "local_infonce_dual", "resolve_local_infonce"]


def _resolve_loss_axes(mesh: Mesh, axis):
    """(axes tuple, collective axis arg, device count) for a loss that may
    span one mesh axis (the plain DP case) or several (hybrid meshes —
    the FSDP step's batch axes). A single axis keeps the string form for
    collectives (identical semantics, simpler HLO names); multiple axes
    pass as the tuple ``lax`` collectives accept directly."""
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return axes, axes[0] if len(axes) == 1 else axes, n


def local_ntxent_allgather(z1_local, z2_local, temperature, axis, num_devices,
                           interpret=None):
    """Per-device global-batch NT-Xent body (call inside shard_map/psum
    context): all-gather both views, fused local-rows x global-cols partial
    loss, psum to the global mean. Shared by the standalone distributed loss
    below and the trainer's sharded train step."""
    n_local = z1_local.shape[0]
    # tiled=True concatenates shards along axis 0: (n_local, D) -> (N, D).
    z1_g = _all_gather_acct(z1_local, axis, tiled=True)
    z2_g = _all_gather_acct(z2_local, axis, tiled=True)
    z_global = jnp.concatenate([z1_g, z2_g], axis=0)          # (2N, D)
    z_local = jnp.concatenate([z1_local, z2_local], axis=0)   # (2n, D)
    gid = local_row_gids(axis, n_local, num_devices)
    loss_sum = ntxent_partial_fused(
        z_local, z_global, gid, temperature, interpret=interpret
    )
    return _psum_acct(loss_sum, axis) / z_global.shape[0]


def resolve_local_ntxent(impl: str):
    """The per-device NT-Xent body for an impl name — the ONE dispatch
    point shared by make_sharded_ntxent and the sharded train-step
    factory. Bodies share the signature
    ``(z1_local, z2_local, temperature, axis, num_devices, interpret)``."""
    if impl == "pair":
        from .pair import pair_body

        return pair_body
    if impl == "strip":
        return local_ntxent_allgather
    raise ValueError(f"unknown NT-Xent impl {impl!r}")


def make_sharded_ntxent(
    mesh: Mesh,
    temperature: float = 0.07,
    axis: str = "data",
    interpret: bool | None = None,
    impl: str = "strip",
):
    """Build a jit-able global-batch NT-Xent over ``mesh``.

    Returns ``loss_fn(z1, z2) -> scalar`` where z1, z2 are the two augmented
    views, (N, D) each, sharded (or shardable) along ``axis``. The scalar is
    replicated; gradients through it are correct per-shard gradients.

    ``impl="strip"`` (default): every device walks its local-rows x
    global-cols strip. ``impl="pair"``: balanced symmetric shard-pair
    schedule — each global tile walked once across the mesh, ~2.2x fewer
    loss matmuls at P=8 (see parallel/pair.py for the trade-offs).

    ``axis`` may be a tuple of mesh axes (e.g. ``('dcn', 'data')`` on a
    hybrid mesh): the batch then shards over their product and the
    bodies' collectives run over the combined axes — how the FSDP step
    embeds this loss inside a GSPMD program (fsdp.py).
    """
    axes, body_axis, num_devices = _resolve_loss_axes(mesh, axis)

    body = functools.partial(
        resolve_local_ntxent(impl),
        temperature=float(temperature),
        axis=body_axis,
        num_devices=num_devices,
        interpret=interpret,
    )
    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # annotation, so JAX's vma checker cannot see through the kernel.
    return _shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(P(axes), P(axes)),
        out_specs=P(),
        check_vma=False,
    )


def ntxent_loss_distributed(
    z1: jax.Array,
    z2: jax.Array,
    mesh: Mesh,
    temperature: float = 0.07,
    axis: str = "data",
    interpret: bool | None = None,
) -> jax.Array:
    """Global-batch canonical NT-Xent over a device mesh (one-shot form)."""
    return make_sharded_ntxent(mesh, temperature, axis, interpret)(z1, z2)


def local_infonce_allgather(za_local, zb_local, scale, axis,
                            interpret=None):
    """Per-device global-batch InfoNCE body (call inside shard_map).

    The CLIP analog of ``local_ntxent_allgather``: all-gather both modality
    shards, then compute this device's local-rows x global-cols block of
    each direction of the cross-modal matrix with the fused partial kernel.
    Row direction: local za rows vs gathered zb; column direction: local zb
    rows vs gathered za (the transpose's rows). ``scale`` (CLIP's learnable
    ``exp(logit_scale)``) is traced and differentiable; its gradient — and
    the reduce-scatter gradient of both all-gathers — falls out of AD.
    """
    n_local = za_local.shape[0]
    za_g = _all_gather_acct(za_local, axis, tiled=True)    # (N, D)
    zb_g = _all_gather_acct(zb_local, axis, tiled=True)
    n = za_g.shape[0]
    d = _axis_index_compat(axis)
    gid = d * n_local + jnp.arange(n_local, dtype=jnp.int32)
    loss_a = info_nce_partial_fused(za_local, zb_g, gid, scale=scale,
                                    interpret=interpret)
    loss_b = info_nce_partial_fused(zb_local, za_g, gid, scale=scale,
                                    interpret=interpret)
    return _psum_acct(loss_a + loss_b, axis) / (2 * n)


def local_infonce_dual(za_local, zb_local, scale, axis, interpret=None):
    """Per-device global-batch InfoNCE body — dual-direction variant.

    Half the communication and half the forward matmuls of
    ``local_infonce_allgather``: only ``zb`` is gathered, and ONE walk of
    the local-rows x global-cols block feeds both softmax directions (the
    column statistics are completed by an (N,)-vector logsumexp merge
    across devices — a cheap collective instead of a second gathered
    matmul pass). Gradients: za's flow directly from the combined-G
    kernels, zb's return through the all_gather as a reduce-scatter, and
    the learnable scale's psum through shard_map AD.
    """
    n_local = za_local.shape[0]
    zb_g = _all_gather_acct(zb_local, axis, tiled=True)     # (N, D)
    n = zb_g.shape[0]
    d = _axis_index_compat(axis)
    gid = d * n_local + jnp.arange(n_local, dtype=jnp.int32)
    part = info_nce_dual_partial(za_local, zb_g, gid, axis, scale=scale,
                                 interpret=interpret)
    return _psum_acct(part, axis) / (2 * n)


def resolve_local_infonce(impl: str):
    """The per-device InfoNCE body for an impl name — the ONE dispatch
    point shared by make_sharded_infonce and the CLIP train-step factory."""
    impls = {"dual": local_infonce_dual,
             "twopass": local_infonce_allgather}
    try:
        return impls[impl]
    except KeyError:
        raise ValueError(
            f"unknown InfoNCE impl {impl!r}; choose from {sorted(impls)}")


def make_sharded_infonce(
    mesh: Mesh,
    axis: str = "data",
    interpret: bool | None = None,
    impl: str = "dual",
):
    """Build a jit-able global-batch InfoNCE over ``mesh``.

    Returns ``loss_fn(za, zb, scale) -> scalar`` with za, zb (N, D) paired
    modality embeddings sharded along ``axis`` and ``scale`` replicated
    (differentiable — psum of its per-shard gradients is AD-derived).

    ``impl="dual"`` (default) gathers one modality and walks the
    similarity block once for both directions; ``impl="twopass"`` is the
    gather-both/walk-twice form (kept for A/B comparison).

    ``axis`` may be a tuple of mesh axes, as in ``make_sharded_ntxent``.
    """
    local = resolve_local_infonce(impl)
    axes, body_axis, _ = _resolve_loss_axes(mesh, axis)

    def body(za_local, zb_local, scale):
        return local(za_local, zb_local, scale, body_axis, interpret)

    return _shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(P(axes), P(axes), P()),
        out_specs=P(),
        check_vma=False,
    )


def info_nce_loss_distributed(
    za: jax.Array,
    zb: jax.Array,
    mesh: Mesh,
    temperature: float = 0.07,
    *,
    scale: jax.Array | float | None = None,
    axis: str = "data",
    interpret: bool | None = None,
) -> jax.Array:
    """Global-batch symmetric InfoNCE over a device mesh (one-shot form)."""
    return make_sharded_infonce(mesh, axis, interpret)(
        za, zb, resolve_scale(temperature, scale))
