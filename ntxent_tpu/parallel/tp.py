"""Tensor parallelism: 2-D (data, model) mesh for the ViT/CLIP towers.

SURVEY.md §2.2 (TP row): the reference has no tensor parallelism; the
obligation for the ViT-B / CLIP configs (BASELINE.json configs[3-4]) is an
optional ``model`` mesh axis realized "via pjit sharding annotations, not
custom code". That is exactly what this module does — the idiomatic XLA/GSPMD
recipe (pick a mesh, annotate shardings, let the compiler insert the
collectives):

* ``tp_param_spec`` maps each parameter path to a ``PartitionSpec``. The
  Megatron-style layout for transformer blocks: attention Q/K/V project onto
  head-sharded activations (heads split over ``model``), the attention output
  projection contracts the sharded head axis (XLA inserts the psum); the MLP
  up-projection is column-sharded, the down-projection row-sharded. Norms,
  embeddings, and small projections stay replicated.
* ``shard_train_state`` places a TrainState on the mesh: every leaf whose
  trailing path matches a parameter rule (this covers the optimizer's
  momentum/trace pytrees too, since optax states mirror the param tree)
  gets its spec; everything else is replicated.
* ``make_tp_simclr_train_step`` / ``make_tp_clip_train_step`` jit the
  ordinary single-program train step over committed sharded inputs —
  activations are constrained to stay batch-sharded over ``data``, weights
  stay sharded over ``model``, and GSPMD derives every all-gather /
  reduce-scatter / psum. The contrastive loss defaults to the shard_map
  fused-Pallas partial bodies over ``data`` embedded inside the GSPMD
  program (the same compose fsdp.py uses; ``loss_impl="oracle"`` keeps
  the all-jnp GSPMD-sharded similarity matmul for A/B).

The explicit shard_map data-parallel path (trainer.py + parallel/dist_loss.py)
remains the hand-scheduled route; this module is the compiler-partitioned
route for models big enough to need their weights split.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.oracle import info_nce_loss, ntxent_loss
from .moe import moe_aux_from

__all__ = [
    "tp_param_spec",
    "tp_fsdp_param_spec",
    "param_spec_tree",
    "shard_train_state",
    "shard_train_state_tp_fsdp",
    "make_tp_simclr_train_step",
    "make_tp_clip_train_step",
]


def _path_names(path) -> list[str]:
    out = []
    for k in path:
        if hasattr(k, "key"):
            out.append(str(k.key))
        elif hasattr(k, "name"):
            out.append(str(k.name))
        elif hasattr(k, "idx"):
            out.append(str(k.idx))
        else:  # pragma: no cover - future jax key types
            out.append(str(k))
    return out


def tp_param_spec(path, leaf, *, model_axis: str = "model") -> P:
    """Megatron-style PartitionSpec for one (path, leaf) of a transformer.

    Matches on the *trailing* module names (flax linen auto-names), so the
    same rule applies to ``params`` and to optimizer-state pytrees that
    mirror the param tree. Leaves whose rank doesn't match the rule (or that
    no rule covers) are replicated.
    """
    names = _path_names(path)
    if not names:
        return P()
    leaf_name = names[-1]
    in_attn = any("Attention" in n for n in names)
    in_mlp = any("MlpBlock" in n for n in names)

    if in_attn and len(names) >= 2:
        proj = names[-2]
        if proj in ("query", "key", "value"):
            # kernel: (embed, heads, head_dim) — shard heads.
            if leaf_name == "kernel" and leaf.ndim == 3:
                return P(None, model_axis, None)
            if leaf_name == "bias" and leaf.ndim == 2:
                return P(model_axis, None)
        elif proj == "out":
            # kernel: (heads, head_dim, embed) — contract sharded heads;
            # the bias is added after the psum, replicated.
            if leaf_name == "kernel" and leaf.ndim == 3:
                return P(model_axis, None, None)
    if in_mlp:
        dense = next((n for n in names if n.startswith("Dense_")), None)
        if dense == "Dense_0":  # up-projection: column-sharded
            if leaf_name == "kernel" and leaf.ndim == 2:
                return P(None, model_axis)
            if leaf_name == "bias" and leaf.ndim == 1:
                return P(model_axis)
        elif dense == "Dense_1":  # down-projection: row-sharded (psum after)
            if leaf_name == "kernel" and leaf.ndim == 2:
                return P(model_axis, None)
    if any("MoEMlp" in n for n in names):
        # MoE weights under TP: Megatron WITHIN each expert — the hidden
        # (f) axis shards over `model` (up-projection column-parallel,
        # down-projection row-parallel; XLA inserts the psum after the f
        # contraction). The expert axis deliberately stays unsharded:
        # expert-dim sharding makes the partitioner emit the
        # scatter/all-to-all path, which XLA:CPU's threaded runtime
        # executes with a nondeterministic abort (~40% of runs on the
        # 8-device host mesh) — psum-only programs are stable everywhere.
        # True expert-dim EP is the explicit shard_map path
        # (parallel/moe.py:make_expert_parallel_moe). Router replicated.
        if leaf_name == "w_up" and leaf.ndim == 3:    # (E, d, f)
            return P(None, None, model_axis)
        if leaf_name == "b_up" and leaf.ndim == 2:    # (E, f)
            return P(None, model_axis)
        if leaf_name == "w_down" and leaf.ndim == 3:  # (E, f, d)
            return P(None, model_axis, None)
    return P()


def tp_fsdp_param_spec(path, leaf, *, model_axis: str = "model",
                       data_axis: str = "data", data_size: int,
                       model_size: int | None = None,
                       min_shard_elems: int | None = None) -> P:
    """Megatron + ZeRO-3 spec for one (path, leaf): the TP rule claims its
    dimension first, then the FSDP shape rule shards the largest REMAINING
    ``data_size``-divisible dimension over ``data_axis``.

    The composition large transformer stacks actually deploy: weights that
    TP splits over ``model`` still carry a full copy per data-replica —
    ZeRO-3 shards that copy (and the mirrored optimizer moments, since the
    rule is path+shape-driven) over ``data`` too, so per-device parameter
    bytes scale 1/(|model|*|data|) for doubly-sharded leaves. Small leaves
    keep FSDP's replicate-below-threshold policy.

    ``model_size`` (the ``model`` mesh-axis size, when known): a TP claim
    the axis cannot divide is dropped HERE, before ``taken`` is computed —
    placement would replicate that dim anyway (``_drop_indivisible``), so
    the freed dim stays available to the data-axis rule instead of the
    leaf ending fully replicated (ADVICE r4 #1).
    """
    from .fsdp import MIN_SHARD_ELEMS, largest_divisible_dim

    if min_shard_elems is None:
        min_shard_elems = MIN_SHARD_ELEMS
    spec = tp_param_spec(path, leaf, model_axis=model_axis)
    if not hasattr(leaf, "ndim") or leaf.ndim == 0 \
            or leaf.size < min_shard_elems:
        return spec
    entries = list(spec) + [None] * (leaf.ndim - len(spec))
    changed = False
    if model_size is not None:
        for i, a in enumerate(entries):
            if a is not None and leaf.shape[i] % model_size:
                entries[i] = None
                changed = True
    taken = tuple(i for i, s in enumerate(entries) if s is not None)
    i = largest_divisible_dim(leaf.shape, data_size, taken=taken)
    if i is not None:
        entries[i] = data_axis
        changed = True
    # `changed` also covers the no-data-dim case: a dropped model claim
    # must not resurface in the returned spec (the rule's output is
    # always directly placeable when model_size is known).
    return P(*entries) if changed else spec


def tp_fsdp_spec_fn(mesh: Mesh, *, model_axis: str = "model",
                    data_axis: str = "data",
                    min_shard_elems: int | None = None):
    """(path, leaf) -> PartitionSpec closure for the Megatron + ZeRO-3
    layout on ``mesh``. ONE rule object shared by state placement
    (``shard_train_state_tp_fsdp``) and the train step's output pinning
    (``param_spec_fn``) — built twice with different thresholds, the two
    would disagree and every step would end in a resharding."""
    data_size = mesh.shape[data_axis]
    model_size = mesh.shape[model_axis]

    def spec_fn(path, leaf):
        return tp_fsdp_param_spec(path, leaf, model_axis=model_axis,
                                  data_axis=data_axis,
                                  data_size=data_size,
                                  model_size=model_size,
                                  min_shard_elems=min_shard_elems)

    return spec_fn


def shard_train_state_tp_fsdp(state, mesh: Mesh, *,
                              model_axis: str = "model",
                              data_axis: str = "data",
                              min_shard_elems: int | None = None):
    """Place a TrainState with the combined Megatron + ZeRO-3 sharding
    (``tp_fsdp_param_spec`` on every array leaf). Same aliasing caveat as
    ``shard_train_state``: treat the source state as consumed. Pass the
    matching ``tp_fsdp_spec_fn(mesh, ...)`` as the train step's
    ``param_spec_fn`` so output states round-trip."""
    spec_fn = tp_fsdp_spec_fn(mesh, model_axis=model_axis,
                              data_axis=data_axis,
                              min_shard_elems=min_shard_elems)

    def place(path, leaf):
        if not hasattr(leaf, "ndim"):
            return leaf
        spec = _drop_indivisible(spec_fn(path, leaf), leaf, mesh)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, state)


def _pin_state(state, mesh: Mesh, spec_fn):
    """Constrain every array leaf of an output state to ``spec_fn``'s
    layout (same role as fsdp._constrain_state: without it GSPMD freely
    picks output shardings — e.g. splitting a replicated LayerNorm bias
    over 'data' — and the returned state no longer matches the compiled
    step's input layout on the next call)."""

    def pin(path, leaf):
        if not hasattr(leaf, "ndim"):
            return leaf
        spec = _drop_indivisible(spec_fn(path, leaf), leaf, mesh)
        return jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(pin, state)


def param_spec_tree(params, *, model_axis: str = "model"):
    """PartitionSpec pytree for a param (or mirrored optimizer-state) tree."""
    return jax.tree_util.tree_map_with_path(
        functools.partial(tp_param_spec, model_axis=model_axis), params)


def _drop_indivisible(spec: P, leaf, mesh: Mesh) -> P:
    """Replicate any spec dimension the mesh axis doesn't divide.

    Megatron's head sharding assumes heads % |model| == 0; a tower whose
    head count doesn't divide (e.g. 3-head ViT-Ti on a 2-wide model
    axis) must fall back to replication for that leaf rather than fail
    placement — the rule is a layout preference, not a shape contract.
    """
    entries = list(spec) + [None] * (leaf.ndim - len(spec))
    changed = False
    for i, a in enumerate(entries):
        if a is not None and leaf.shape[i] % mesh.shape[a]:
            entries[i] = None
            changed = True
    if not changed:
        return spec
    return P(*entries)


def shard_train_state(state, mesh: Mesh, *, model_axis: str = "model"):
    """Place a TrainState on the mesh with TP param/optimizer sharding.

    Returns the state with every array leaf committed to a NamedSharding —
    jit then infers program shardings from these placements (no in_shardings
    needed).

    Aliasing caveat: ``jax.device_put`` onto the mesh reuses the source
    buffer on its home device rather than copying, so the returned state
    is NOT independent of ``state`` — donating the original to a jitted
    step afterwards deletes shards out from under the placed copy. Treat
    the original as consumed (see fsdp.shard_train_state_fsdp).
    """

    def place(path, leaf):
        if not hasattr(leaf, "ndim"):  # static fields (apply_fn, tx)
            return leaf
        spec = _drop_indivisible(
            tp_param_spec(path, leaf, model_axis=model_axis), leaf, mesh)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, state)


def _constrain_batch(x, mesh: Mesh, data_axis: str):
    spec = P(data_axis, *([None] * (x.ndim - 1)))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def make_tp_simclr_train_step(
    mesh: Mesh,
    temperature: float = 0.1,
    *,
    data_axis: str = "data",
    has_batch_stats: bool = False,
    remat: bool = False,
    loss_impl: str = "strip",
    loss_axes: str | tuple | None = None,
    interpret: bool | None = None,
    param_spec_fn=None,
) -> Callable:
    """Compiler-partitioned SimCLR train step on a (data, model) mesh.

    The batch stays sharded over ``data``; weights matching ``tp_param_spec``
    stay sharded over ``model``; the NT-Xent loss runs as the shard_map
    fused-partial bodies over ``data_axis`` inside the GSPMD program —
    the same compose fsdp.make_fsdp_train_step uses, so Megatron weight
    sharding and the Pallas fused loss run in one jitted step.

    ``loss_impl``: ``"strip"`` (default) / ``"pair"`` — the fused Pallas
    per-device bodies shared with the explicit DP trainer
    (``dist_loss.resolve_local_ntxent``); ``"oracle"`` — the all-jnp
    global loss whose (2B, 2B) similarity matmul GSPMD shards across the
    mesh (rows with the batch sharding, columns via its own all-gather;
    the pre-round-5 behavior, kept for A/B).

    ``loss_axes`` (default ``(data_axis,)``): mesh axes the fused loss
    shards over. The default replicates the loss compute across
    ``model`` — negligible next to the tower matmuls at small B. Pass
    ``(data_axis, model_axis)`` to spread the loss rows over EVERY
    device (the (2B, 2B) similarity work drops by |model|x at the cost
    of one embedding reshard into the shard_map) — worthwhile when B is
    large enough that the loss matmul shows up next to the towers.

    Divisibility contract (fused impls only): the per-step batch B (rows
    of ``v1``/``v2``) must divide by the product of the ``loss_axes``
    sizes — the shard_map's in_specs reject ragged shards at trace
    time. ``loss_impl="oracle"`` carries no such constraint (GSPMD
    pads).

    ``has_batch_stats=True`` is for encoders with BatchNorm (ResNet +
    trainer.TrainState); the default fits the primary TP targets (ViT/CLIP,
    no BatchNorm, plain flax TrainState).

    ``remat=True`` rematerializes the encoder forward in the backward
    pass (the same HBM-for-FLOPs trade as every other step factory).

    ``param_spec_fn`` (default: the plain Megatron ``tp_param_spec``
    rule) pins the OUTPUT state's leaves so they round-trip into the
    next call — pass ``tp_fsdp_spec_fn(mesh, ...)`` when the state was
    placed with the composed Megatron + ZeRO-3 layout.
    """
    if param_spec_fn is None:
        param_spec_fn = tp_param_spec
    if loss_impl == "oracle":
        if loss_axes is not None:
            # Silently dropping the requested sharding would let an A/B
            # pass on one arm and trace-fail on the other with no hint.
            raise ValueError("loss_axes applies only to the fused "
                             "shard_map impls; the oracle loss is "
                             "GSPMD-partitioned over the whole mesh")
        sharded_loss = None
    else:
        # The ONE dispatch point for fused NT-Xent bodies — same factory
        # the shard_map DP trainer and the FSDP step use; its
        # _resolve_loss_axes owns the str-vs-tuple normalization.
        from .dist_loss import make_sharded_ntxent

        sharded_loss = make_sharded_ntxent(
            mesh, temperature,
            axis=data_axis if loss_axes is None else loss_axes,
            interpret=interpret, impl=loss_impl)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state, v1, v2):
        v1c = _constrain_batch(v1, mesh, data_axis)
        v2c = _constrain_batch(v2, mesh, data_axis)

        def encode(params, both):
            if has_batch_stats:
                variables = {"params": params,
                             "batch_stats": state.batch_stats}
                return state.apply_fn(variables, both, train=True,
                                      mutable=["batch_stats"])
            return state.apply_fn({"params": params}, both,
                                  train=True), None

        if remat:
            encode = jax.checkpoint(encode)

        def loss_fn(params):
            both = jnp.concatenate([v1c, v2c], axis=0)
            z, updates = encode(params, both)
            new_stats = updates["batch_stats"] if has_batch_stats else None
            z = _constrain_batch(z, mesh, data_axis)
            if sharded_loss is None:
                return ntxent_loss(z, temperature), new_stats
            n = v1c.shape[0]
            # Split the stacked (2B, D) embeddings back into views: the
            # fused bodies take (z1, z2) row-sharded over `data` and
            # rebuild the [view1; view2] global layout internally.
            return sharded_loss(z[:n], z[n:]), new_stats

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        state2 = state.apply_gradients(grads=grads)
        if new_stats is not None:
            state2 = state2.replace(batch_stats=new_stats)
        return _pin_state(state2, mesh, param_spec_fn), {"loss": loss}

    return train_step


def make_tp_clip_train_step(
    mesh: Mesh,
    *,
    data_axis: str = "data",
    remat: bool = False,
    loss_impl: str = "dual",
    loss_axes: str | tuple | None = None,
    interpret: bool | None = None,
    moe_aux_weight: float = 0.0,
    param_spec_fn=None,
) -> Callable:
    """Compiler-partitioned CLIP train step: dual towers, learnable scale.

    ``state.apply_fn(variables, images, tokens)`` must return
    ``(image_embeds, text_embeds, scale)`` (models/clip.py). The symmetric
    InfoNCE runs at temperature ``1/scale`` so the logit scale's gradient
    flows; GSPMD shards both towers over ``model``.

    ``loss_impl``: ``"dual"`` (default) / ``"twopass"`` — the fused
    partial InfoNCE bodies shared with the shard_map DP trainer and the
    FSDP CLIP step (``dist_loss.resolve_local_infonce``), run as a
    shard_map over ``data_axis`` inside the GSPMD program; ``"oracle"``
    — the all-jnp global InfoNCE whose (N, N) logit matmul GSPMD shards
    over the mesh (the pre-round-5 behavior, kept for A/B). The fused
    impls require batch N to divide by the product of the ``loss_axes``
    sizes (the shard_map rejects ragged shards at trace time);
    ``"oracle"`` doesn't. ``loss_axes``: see
    ``make_tp_simclr_train_step`` — pass ``(data_axis, model_axis)`` to
    spread the loss rows over every device instead of replicating the
    loss compute across ``model``.

    ``remat`` rematerializes the tower forwards in the backward pass.
    ``moe_aux_weight > 0`` adds the MoE towers' load-balance aux loss (a
    single global program — no pmean needed). ``param_spec_fn``: see
    ``make_tp_simclr_train_step``.
    """
    collect = moe_aux_weight > 0.0
    if param_spec_fn is None:
        param_spec_fn = tp_param_spec
    if loss_impl == "oracle":
        if loss_axes is not None:
            raise ValueError("loss_axes applies only to the fused "
                             "shard_map impls; the oracle loss is "
                             "GSPMD-partitioned over the whole mesh")
        sharded_loss = None
    else:
        # The ONE dispatch point for fused InfoNCE bodies — same factory
        # the shard_map DP CLIP trainer and the FSDP CLIP step use; its
        # _resolve_loss_axes owns the str-vs-tuple normalization.
        from .dist_loss import make_sharded_infonce

        sharded_loss = make_sharded_infonce(
            mesh, axis=data_axis if loss_axes is None else loss_axes,
            interpret=interpret, impl=loss_impl)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state, images, tokens):
        imc = _constrain_batch(images, mesh, data_axis)
        tkc = _constrain_batch(tokens, mesh, data_axis)

        def fwd(params, imc, tkc):
            if not collect:
                out = state.apply_fn({"params": params}, imc, tkc,
                                     train=True)
                return (*out, 0.0)
            out, updates = state.apply_fn(
                {"params": params}, imc, tkc, train=True,
                mutable=["intermediates"])
            return (*out, moe_aux_from(updates))

        towers = jax.checkpoint(fwd) if remat else fwd

        def loss_fn(params):
            zi, zt, scale, aux = towers(params, imc, tkc)
            zi = _constrain_batch(zi, mesh, data_axis)
            zt = _constrain_batch(zt, mesh, data_axis)
            if sharded_loss is None:
                loss = info_nce_loss(zi, zt, temperature=1.0 / scale)
            else:
                loss = sharded_loss(zi, zt, scale)
            return loss + moe_aux_weight * aux, aux

        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        metrics = {"loss": loss}
        if collect:
            metrics["moe_aux"] = aux
        return _pin_state(state.apply_gradients(grads=grads), mesh,
                          param_spec_fn), metrics

    return train_step
