"""Device-mesh and multi-host initialization: the MPI-launcher role.

The reference declared MPI (process launch/rendezvous) and NCCL (collectives)
support as link-only CMake options with zero call sites
(/root/reference/CMakeLists.txt:13-14,41-47,115-121; SURVEY.md §0.1, §2.2).
This module realizes that declared capability TPU-natively:

* ``init_distributed`` replaces ``mpirun`` + ``MPI_Init``:
  ``jax.distributed.initialize`` performs rendezvous (auto-detecting
  coordinator/process count on Cloud TPU; explicit args elsewhere).
* ``create_mesh`` builds the ``jax.sharding.Mesh`` whose axes XLA lowers
  collectives onto — ICI links intra-slice, DCN across slices — replacing
  NCCL communicator construction.
"""

from __future__ import annotations

import functools
import logging
import re
import threading
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .precision import (
    collective_dtype,
    collective_precision,
    quantizable,
    quantize_int8,
)

logger = logging.getLogger(__name__)

__all__ = [
    "init_distributed",
    "create_mesh",
    "create_hybrid_mesh",
    "data_sharding",
    "replicated_sharding",
    "sharded_prefetch",
    "global_batch",
    "local_row_gids",
    "process_info",
    "shard_map",
    "pcast",
    "axis_size",
    "axis_index",
    "psum",
    "pmean",
    "pmax",
    "all_gather",
    "all_to_all",
    "ppermute",
    "ppermute_chunked",
    "chunk_bounds",
    "psum_scatter",
    "collective_precision",
    "collective_dtype",
    "quantized_grad_reduce",
    "CommsAccounting",
    "comms_accounting",
    "comms_scaled",
    "mesh_topology",
    "tree_partition_specs",
    "match_partition_rules",
    "resolve_restore_specs",
    "place_with_specs",
]

# Feature gate shared by every shim below: recent jax promoted shard_map
# to the top level; installs without it need the experimental spelling
# AND carry the two lowering/transpose bugs the shims own.
_OLD_JAX = not hasattr(jax, "shard_map")


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions (robustness shim).

    jax promoted shard_map to the top level (with ``check_rep`` renamed
    ``check_vma``) only recently; on older installs the same transform
    lives at ``jax.experimental.shard_map.shard_map``. Every shard_map in
    this package routes through here so the whole distributed layer —
    losses, TP/FSDP/PP steps, ring attention, MoE — degrades to the
    experimental spelling instead of dying with an AttributeError on the
    jax the host happens to ship.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def pcast(x, axes, to: str = "varying"):
    """``jax.lax.pcast`` across jax versions (robustness shim).

    Mirrors the ``shard_map`` shim above: the varying/invariant type
    system behind ``pcast`` is recent. Ring collectives (parallel/ring.py,
    ring_attention.py) use it only to mark device-invariant scan inits as
    ring-varying so carry types agree with what ``ppermute`` produces —
    a TYPE annotation, not a computation. Fallback ladder:

    * ``jax.lax.pcast`` exists: use it;
    * only ``jax.lax.pvary`` exists (the earlier spelling of the
      invariant→varying direction): use that for ``to="varying"``;
    * neither exists: identity — jax versions without the varying type
      system don't check carry varying-ness, so the annotation is
      simply unnecessary there (the seed-era distributed failures were
      exactly this AttributeError, not a semantic gap).
    """
    _account("pcast", axes, x, lambda b, p: 0.0)  # annotation: 0 bytes
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to=to)
    if to == "varying" and hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axes)
    return x


def axis_size(axis: str) -> int:
    """``jax.lax.axis_size`` across jax versions (robustness shim).

    Older jax has no ``axis_size``; ``psum(1, axis)`` is the classic
    spelling there — psum of a non-traced constant over a named axis is
    evaluated eagerly to the static size, so reshape dims built from it
    stay static.
    """
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    return jax.lax.psum(1, axis)


def axis_index(axis: str):
    """``jax.lax.axis_index`` spelled to survive old-jax lowering
    (robustness shim).

    On old jax, an ``axis_index`` inside a ``jax.custom_vjp`` body that is
    itself inside a jit-compiled ``shard_map`` lowers to a bare GSPMD
    ``partition-id`` that XLA's SPMD partitioner rejects as UNIMPLEMENTED
    (the seed-era ring-attention-under-jit failure). Collectives lower
    correctly in exactly that position, so the fallback derives the index
    from one: every device contributes ``arange(P)`` to a psum-scatter,
    so device d receives ``P * d`` — a reduce-scatter the partitioner
    understands anywhere a ppermute works. Use this (not the raw lax op)
    inside custom_vjp bodies that run under ``shard_map``; on new jax it
    is the native op.
    """
    if not _OLD_JAX:
        return jax.lax.axis_index(axis)
    n = int(axis_size(axis))  # static: psum of a non-traced constant
    if n == 1:
        return jnp.int32(0)
    scattered = jax.lax.psum_scatter(
        jnp.arange(n, dtype=jnp.int32), axis, scatter_dimension=0,
        tiled=True)
    return jnp.squeeze(scattered, 0) // n


def axis_index_plain(axis: str):
    """The native ``axis_index`` with no old-jax fallback.

    For plain ``shard_map`` bodies (no ``custom_vjp`` in the way), where
    the native op lowers fine everywhere and :func:`axis_index`'s old-jax
    ``psum_scatter`` fallback would be worse than useless: it is a real
    4-byte collective, and inside a censused region (e.g. the chunked
    dist_loss ring scan) it would break the graph-census == declared
    exactness the audits pin. ``axis_index`` is communication-free; only
    the fallback spelling isn't.
    """
    return jax.lax.axis_index(axis)


# ---------------------------------------------------------------------------
# Comms accounting: per-collective op/byte counters, recorded at trace time
# ---------------------------------------------------------------------------
#
# ROADMAP items 2 (quantized collectives) and 3 (computation-collective
# overlap) both claim byte/time wins that cannot be judged without a
# baseline: how many collective ops does one compiled step issue, and how
# many bytes do they move? This package owns every hand-written collective
# call site through the shims below, and shapes/dtypes are STATIC at trace
# time — so the accounting is host-side Python that runs exactly once per
# trace (zero device cost, zero HLO change): each shim reads the traced
# operand's aval, applies the textbook ring-algorithm byte model, and bumps
# `collective_calls_total{op,axis}` / `collective_bytes_total{op,axis}`
# in the process-wide metrics registry. `comms_accounting()` additionally
# keeps per-(op, axis) running totals whose deltas bracket a compile —
# trainer.train_loop captures the step's static profile that way and the
# StepTimeline publishes it as the per-step comms series.
#
# Scope: these shims record the forward-traced call sites — the traffic
# the quantization/overlap PRs rewrite. The AD-derived duals (the
# reduce-scatter behind an all_gather's gradient, the psum transpose)
# and GSPMD-inserted collectives (FSDP parameter gathers) are inserted
# by JAX's transpose rules / the XLA partitioner, never by these shims —
# since ISSUE 14 they are counted by the GRAPH census
# (analysis/graph/census.py: the jaxpr walk + compiled-HLO walk behind
# `ntxent-audit`), published as
# `collective_graph_bytes_total{source=ad|gspmd}` next to the declared
# series here, and cross-checked against these shims' byte model —
# census == declared, exactly, for every forward graph (test-pinned).
#
# Byte model (per device, ring algorithms — the TPU lowering): for payload
# bytes B over an axis group of size P:
#   all_gather     (P-1) * B      (B = the local shard being gathered)
#   psum / pmean / pmax   2 * (P-1)/P * B  (reduce-scatter + all-gather)
#   psum_scatter   (P-1)/P * B
#   ppermute       B              (one neighbor send)
#   all_to_all     (P-1)/P * B    (each device keeps its own 1/P slice)
#   pcast          0              (a type annotation, no data motion)
#
# Collectives inside a ``lax.scan`` body are TRACED once but EXECUTE once
# per iteration; call sites wrap the scan in ``comms_scaled(length)`` so
# the recorded counts/bytes reflect execution (ring.py / ring_attention.py
# / pp.py do). Without the wrapper a scanned collective is undercounted by
# the scan length — scaling is the call site's declaration, since the scan
# length is not visible from inside the body.


class CommsAccounting:
    """Host-side totals of traced collective traffic.

    Thread-safe; one process-wide instance (``comms_accounting()``).
    ``totals()`` snapshots ``{(op, axis): (calls, bytes)}``; ``delta``
    subtracts an earlier snapshot — bracket a step compile with the two
    to get the static per-compiled-step profile. Registry counters are
    bumped on every record, so a mid-run Prometheus scrape carries the
    cumulative trace-time traffic even if nobody brackets anything.
    """

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._registry = registry
        self._totals: dict[tuple[str, str], list[float]] = {}

    def _counters(self, op: str, axis_label: str,
                  dtype: str | None = None):
        if self._registry is None:
            from ..obs.registry import default_registry

            self._registry = default_registry()
        labels = {"op": op, "axis": axis_label}
        if dtype is not None:
            # The dtype-itemized view (ISSUE 12). Cardinality is bounded
            # by construction: values are canonical numpy dtype names of
            # payloads that actually ride the wire (float32/bfloat16/
            # int8/... — a closed, single-digit set), never request- or
            # data-derived strings, so the pow2-bounding rule the
            # request-size export needed does not apply here.
            # AGGREGATION CAVEAT: the itemized series share the metric
            # name with the unlabeled totals (the ISSUE 12 contract:
            # existing dashboards keep scraping unchanged), so a
            # sum() over the whole family counts everything twice —
            # the unlabeled series IS the total; the dtype series are
            # its breakdown.
            labels["dtype"] = dtype
        return (
            self._registry.counter(
                "collective_calls_total",
                "collective ops issued per compiled computation "
                "(recorded at trace time)", labels=labels),
            self._registry.counter(
                "collective_bytes_total",
                "bytes moved per device by traced collectives "
                "(ring-algorithm model, trace-time static)",
                labels=labels),
        )

    def record(self, op: str, axis_label: str, nbytes: float,
               calls: int = 1, dtype: str | None = None) -> None:
        # The unlabeled-by-dtype totals are the pre-quantization series
        # existing dashboards and obs_smoke scrape — always bumped, with
        # the SAME values, so mixed-precision runs change only what the
        # extra dtype-labeled series itemize on top.
        calls_c, bytes_c = self._counters(op, axis_label)
        calls_c.inc(calls)
        bytes_c.inc(nbytes)
        if dtype is not None:
            dcalls, dbytes = self._counters(op, axis_label, dtype)
            dcalls.inc(calls)
            dbytes.inc(nbytes)
        with self._lock:
            entry = self._totals.setdefault((op, axis_label), [0, 0.0])
            entry[0] += calls
            entry[1] += nbytes

    def totals(self) -> dict[tuple[str, str], tuple[int, float]]:
        with self._lock:
            return {k: (int(v[0]), float(v[1]))
                    for k, v in self._totals.items()}

    def delta(self, mark: dict) -> dict[tuple[str, str], tuple[int, float]]:
        """Traffic recorded since ``mark`` (an earlier ``totals()``),
        zero-entries dropped."""
        out = {}
        for key, (calls, nbytes) in self.totals().items():
            c0, b0 = mark.get(key, (0, 0.0))
            if calls - c0 or nbytes - b0:
                out[key] = (calls - c0, nbytes - b0)
        return out


_comms = CommsAccounting()
_comms_scale = threading.local()


def comms_accounting() -> CommsAccounting:
    """The process-wide collective-traffic registry."""
    return _comms


class comms_scaled:
    """Multiply collective accounting by ``n`` inside the block.

    Wrap a ``lax.scan`` whose BODY issues collectives: the body traces
    once but runs ``length`` times, so the call site declares the
    repetition (``with comms_scaled(num_devices - 1): lax.scan(...)``).
    Nesting multiplies. Thread-local, so concurrent traces don't leak
    scales into each other.
    """

    def __init__(self, n: int):
        self.n = max(int(n), 0)
        self._saved = 1

    def __enter__(self) -> "comms_scaled":
        self._saved = getattr(_comms_scale, "value", 1)
        _comms_scale.value = self._saved * self.n
        return self

    def __exit__(self, *exc) -> None:
        _comms_scale.value = self._saved
        return None


def _leaf_wire_dtype(leaf) -> np.dtype | None:
    """The dtype a leaf actually occupies ON THE WIRE.

    Traced/concrete arrays carry it directly (including the quantized
    int8 payloads and bf16 casts the precision policy puts on the wire
    — the itemsize read here is the on-wire one, not the caller's input
    dtype). Python scalars trace at jax's default widths (f32/i32 with
    x64 disabled), NOT numpy's 64-bit asarray default — previously they
    were silently skipped (0 bytes). None = not a payload.
    """
    dtype = getattr(leaf, "dtype", None)
    if dtype is not None:
        try:
            return np.dtype(dtype)
        except TypeError:
            return None
    if isinstance(leaf, bool):
        return np.dtype(np.bool_)
    if isinstance(leaf, int):
        return np.dtype(np.int32)
    if isinstance(leaf, float):
        return np.dtype(np.float32)
    if isinstance(leaf, complex):
        return np.dtype(np.complex64)
    return None


def _tree_payload_bytes(x) -> float:
    """Per-device payload bytes of a (pytree of) traced array(s), at
    the actual on-wire dtype of each leaf (see _leaf_wire_dtype)."""
    total = 0.0
    for leaf in jax.tree_util.tree_leaves(x):
        dtype = _leaf_wire_dtype(leaf)
        if dtype is None:
            continue
        total += float(np.prod(getattr(leaf, "shape", ()))) \
            * dtype.itemsize
    return total


def _wire_dtype_label(x) -> str:
    """Canonical dtype label of a wire payload: one dtype's numpy name,
    or "mixed" when leaves disagree (bounded cardinality either way)."""
    names = set()
    for leaf in jax.tree_util.tree_leaves(x):
        dtype = _leaf_wire_dtype(leaf)
        if dtype is not None:
            names.add(dtype.name)
    if not names:
        return "none"
    return names.pop() if len(names) == 1 else "mixed"


def _account(op: str, axis, x, factor) -> None:
    """Record one traced collective; NEVER raises (telemetry must not
    break tracing — e.g. a collective spelled over an axis the ambient
    mesh lacks will fail in jax with its own, better error)."""
    try:
        axes = (axis,) if isinstance(axis, str) else tuple(axis)
        p = 1
        for a in axes:
            p *= int(axis_size(a))
        scale = getattr(_comms_scale, "value", 1)
        nbytes = factor(_tree_payload_bytes(x), p) * scale
        _comms.record(op, "|".join(str(a) for a in axes), nbytes,
                      calls=scale, dtype=_wire_dtype_label(x))
    except Exception:  # noqa: BLE001 — accounting is strictly best-effort
        logger.debug("comms accounting skipped for %s over %r", op, axis,
                     exc_info=True)


# ---------------------------------------------------------------------------
# Precision policy: quantized wire payloads (ISSUE 12)
# ---------------------------------------------------------------------------
#
# Under ``collective_precision("bf16"|"int8")`` (parallel/precision.py,
# a TRACE-time thread-local), ``all_gather``/``psum``/``pmean``/
# ``psum_scatter`` compress their payloads before the wire and restore
# them after. The accounting records the WIRE payloads — quantized
# arrays + their scales, at their actual on-wire dtypes — under the
# LOGICAL op name (a quantized psum records as op="psum" so per-op
# dashboards keep their continuity), itemized by the new ``dtype``
# label. The int8 all-reduce is the two-phase schedule:
#
#   quantize (per-chunk symmetric scale, in-graph)
#     -> all_to_all of the chunks        (p-1)/p * B/4 wire
#     -> local dequant + sum (exact f32 accumulate of the segment)
#     -> re-quantize the reduced segment
#     -> all_gather of the segment       (p-1)/p * B/4 wire
#
# i.e. exactly the int8 fraction of a float ring all-reduce at EVERY
# mesh size (a naive quantize->all_gather->sum degrades to 1x at p=8).
# Each phase is a single existing lax collective — no hand ring.
#
# AD: quantization is not differentiable (round has zero gradient), so
# each quantized collective is a ``custom_vjp`` whose backward is the
# exact transpose of the UNQUANTIZED collective — a straight-through
# estimator for the compression, the identity the f32 path's AD derives
# (backward duals are not declared by these shims — the ISSUE 14 graph
# census counts them under
# ``collective_graph_bytes_total{source="ad"}``). Gradient reductions
# should prefer
# ``quantized_grad_reduce`` (error feedback: the compression residual
# carries into the next step's payload, so the noise is absorbed
# instead of biasing SGD).
#
# Eligibility: int8 applies per leaf to float payloads of >=
# precision.MIN_QUANT_ELEMS elements; scalars (the psum'd loss),
# small vectors and integer payloads ride in full precision. pmax
# never quantizes (a max over quantized values loses the very extremes
# it exists to find). ppermute rides the policy too (ISSUE 19): the
# ring-schedule paths (ring.py, the chunked dist_loss, ring attention)
# spell every hop through the shim, so int8/bf16 reach the circulating
# blocks — gid vectors (int32) and small stat vectors stay exempt via
# the same per-leaf eligibility floor.


def _tree_to_bf16(x):
    return jax.tree.map(
        lambda leaf: leaf.astype(jnp.bfloat16)
        if getattr(leaf, "dtype", None) is not None
        and jnp.issubdtype(leaf.dtype, jnp.floating) else leaf, x)


def _tree_cast_like(out, ref):
    return jax.tree.map(
        lambda o, r: o.astype(r.dtype)
        if getattr(r, "dtype", None) is not None
        and jnp.issubdtype(r.dtype, jnp.floating) else o, out, ref)


def _single_array(x) -> bool:
    return not isinstance(x, (tuple, list, dict)) \
        and getattr(x, "dtype", None) is not None


def _axis_group_size(axis) -> int:
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    p = 1
    for a in axes:
        p *= int(axis_size(a))
    return p


@functools.lru_cache(maxsize=None)
def _int8_gather(axis):
    """custom_vjp int8 all_gather over ``axis`` (tiled semantics):
    quantize the local shard per row, gather payload + scales,
    dequantize; backward is the exact tiled-gather transpose (a
    reduce-scatter of the cotangent)."""

    @jax.custom_vjp
    def gather_q(x):
        return _fwd(x)[0]

    def _fwd(x):
        q, s = quantize_int8(x)
        _account("all_gather", axis, q, lambda b, p: (p - 1) * b)
        _account("all_gather", axis, s, lambda b, p: (p - 1) * b)
        qg = jax.lax.all_gather(q, axis)       # (p, *shard)
        sg = jax.lax.all_gather(s, axis)
        deq = (qg.astype(jnp.float32) * sg).astype(x.dtype)
        return deq.reshape((-1,) + x.shape[1:]), None

    def _bwd(_, ct):
        return (jax.lax.psum_scatter(ct, axis, scatter_dimension=0,
                                     tiled=True),)

    gather_q.defvjp(_fwd, _bwd)
    return gather_q


@functools.lru_cache(maxsize=None)
def _int8_permute(axis, perm):
    """custom_vjp int8 neighbor send over ``axis`` along ``perm``:
    quantize the payload per row, permute payload + scales, dequantize
    on arrival. Backward is the exact ppermute transpose (the
    reverse-direction permute) at full precision — the same
    straight-through estimator as ``_int8_gather``, so quantization
    noise never compounds around a ring's gradient pass.

    No accounting in here: the ``ppermute`` wrapper declares the wire
    payloads BEFORE entering the custom_vjp. Inside a ``lax.scan`` body
    (the chunked ring schedule's home) the primal fn is staged when the
    scan is built and the ``fwd`` thunk is traced AGAIN by the scan's
    JVP rule — accounting placed inside either would double-declare
    every hop under ``grad`` and break the census byte parity the fwd
    audit pins. The wrapper's Python runs exactly once per body
    staging, same as the f32 path's accounting."""
    inverse = tuple((dst, src) for src, dst in perm)

    @jax.custom_vjp
    def permute_q(x):
        return _fwd(x)[0]

    def _fwd(x):
        q, s = quantize_int8(x)
        qp = jax.lax.ppermute(q, axis, perm)
        sp = jax.lax.ppermute(s, axis, perm)
        return (qp.astype(jnp.float32) * sp).astype(x.dtype), None

    def _bwd(_, ct):
        # Full-precision reverse hop; an AD dual, so (like every shim
        # backward) it is NOT declared here — the graph census counts it.
        return (jax.lax.ppermute(ct, axis, inverse),)

    permute_q.defvjp(_fwd, _bwd)
    return permute_q


def _qallreduce_leaves(leaves, axis, op: str):
    """(summed leaves, local compression errors) for a LIST of leaves,
    int8 on the wire via ONE two-phase schedule — 4 wire collectives
    TOTAL however many leaves ride it (per-leaf collectives would scale
    the per-step op count with model depth and lose the bandwidth win
    to latency on a real interconnect). Scale granularity is preserved:
    each leaf is chunked and scaled independently (one f32 scale per
    (device chunk, leaf)); only the wire transfers are shared, with the
    per-leaf scale columns re-expanded after each hop.

    The error is each leaf's phase-1 residual
    ``v - dequant(quantize(v))`` — the per-device term error feedback
    carries; the phase-2 re-quantization error belongs to the shared
    sum and is not attributable to one device (accepted noise, ~0.4%
    relative)."""
    p = _axis_group_size(axis)
    shapes = [x.shape for x in leaves]
    dtypes = [x.dtype for x in leaves]
    cs, qs, ss, errs = [], [], [], []
    for x in leaves:
        flat = x.astype(jnp.float32).reshape(-1)
        n = flat.size
        c = -(-n // p)
        pad = p * c - n
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), jnp.float32)])
        chunks = flat.reshape(p, c)
        q, s = quantize_int8(chunks)                  # (p, c), (p, 1)
        errs.append(chunks - q.astype(jnp.float32) * s)
        cs.append(c)
        qs.append(q)
        ss.append(s)
    q_all = jnp.concatenate(qs, axis=1)               # (p, Ctot) int8
    s_all = jnp.concatenate(ss, axis=1)               # (p, L) f32
    _account(op, axis, q_all, lambda b, _p: (_p - 1) / _p * b)
    _account(op, axis, s_all, lambda b, _p: (_p - 1) / _p * b)
    qx = jax.lax.all_to_all(q_all, axis, split_axis=0, concat_axis=0,
                            tiled=True)               # row d = device
    sx = jax.lax.all_to_all(s_all, axis, split_axis=0,  # d's chunk for
                            concat_axis=0, tiled=True)  # ME
    reps = np.asarray(cs)
    ctot = int(reps.sum())
    sx_full = jnp.repeat(sx, reps, axis=1, total_repeat_length=ctot)
    seg = jnp.sum(qx.astype(jnp.float32) * sx_full, axis=0)  # exact f32
    offs = np.concatenate([[0], np.cumsum(reps)])
    q2s, s2s = [], []
    for i in range(len(cs)):
        q2, s2 = quantize_int8(seg[offs[i]:offs[i + 1]][None, :])
        q2s.append(q2[0])
        s2s.append(s2[0])
    q2_all = jnp.concatenate(q2s)                     # (Ctot,)
    s2_all = jnp.concatenate(s2s)                     # (L,)
    _account(op, axis, q2_all, lambda b, _p: (_p - 1) * b)
    _account(op, axis, s2_all, lambda b, _p: (_p - 1) * b)
    qg = jax.lax.all_gather(q2_all, axis)             # (p, Ctot)
    sg = jax.lax.all_gather(s2_all, axis)             # (p, L)
    sg_full = jnp.repeat(sg, reps, axis=1, total_repeat_length=ctot)
    full = qg.astype(jnp.float32) * sg_full           # (p, Ctot)
    outs, errs_out = [], []
    for i, (shape, dtype, err) in enumerate(zip(shapes, dtypes, errs)):
        # leaf i flattened = [device 0's chunk; device 1's; ...] — the
        # column block's rows, in order.
        blk = full[:, offs[i]:offs[i + 1]].reshape(-1)
        n = 1
        for d in shape:
            n *= int(d)
        outs.append(blk[:n].reshape(shape).astype(dtype))
        errs_out.append(err.reshape(-1)[:n].reshape(shape))
    return outs, errs_out


@functools.lru_cache(maxsize=None)
def _int8_reduce(axis, mean: bool):
    """custom_vjp int8 all-reduce of a TUPLE of leaves (one shared
    two-phase schedule; errors discarded — the context path; gradients
    should use quantized_grad_reduce)."""
    op = "pmean" if mean else "psum"

    @jax.custom_vjp
    def reduce_q(leaves):
        return _fwd(leaves)[0]

    def _fwd(leaves):
        outs, _ = _qallreduce_leaves(list(leaves), axis, op)
        if mean:
            p = _axis_group_size(axis)
            outs = [o / p for o in outs]
        return tuple(outs), None

    def _bwd(_, cts):
        # psum's transpose passes the (replicated) cotangents through;
        # pmean's divides by the group size.
        if mean:
            p = _axis_group_size(axis)
            cts = tuple(ct / p for ct in cts)
        return (tuple(cts),)

    reduce_q.defvjp(_fwd, _bwd)
    return reduce_q


def _tree_quantized_reduce(x, axis, mean: bool):
    """int8 all-reduce over a pytree: every eligible leaf rides ONE
    shared quantized two-phase schedule, the rest share ONE plain
    full-precision reduce."""
    op = "pmean" if mean else "psum"
    axis_key = axis if isinstance(axis, str) else tuple(axis)
    leaves, treedef = jax.tree_util.tree_flatten(x)
    flags = [quantizable(leaf) for leaf in leaves]
    rest = [leaf for leaf, f in zip(leaves, flags) if not f]
    if rest:
        _account(op, axis, rest, lambda b, p: 2.0 * (p - 1) / p * b)
        fn = jax.lax.pmean if mean else jax.lax.psum
        rest = list(fn(tuple(rest), axis))
    elig = tuple(leaf for leaf, f in zip(leaves, flags) if f)
    elig_out = iter(_int8_reduce(axis_key, mean)(elig) if elig else ())
    rest_iter = iter(rest)
    out = [next(elig_out) if f else next(rest_iter) for f in flags]
    return jax.tree_util.tree_unflatten(treedef, out)


@functools.lru_cache(maxsize=None)
def _int8_scatter(axis):
    """custom_vjp int8 psum_scatter (tiled, scatter dim 0): phase 1 of
    the quantized all-reduce — quantize per destination chunk,
    all_to_all, dequantize + sum the received chunks. Backward is the
    tiled reduce-scatter transpose (an all_gather of the cotangent)."""

    @jax.custom_vjp
    def scatter_q(x):
        return _fwd(x)[0]

    def _fwd(x):
        p = _axis_group_size(axis)
        rows = x.shape[0] // p
        chunks = x.astype(jnp.float32).reshape(p, -1)
        q, s = quantize_int8(chunks)
        _account("psum_scatter", axis, q, lambda b, _p: (_p - 1) / _p * b)
        _account("psum_scatter", axis, s, lambda b, _p: (_p - 1) / _p * b)
        qx = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                                tiled=True)
        sx = jax.lax.all_to_all(s, axis, split_axis=0, concat_axis=0,
                                tiled=True)
        seg = jnp.sum(qx.astype(jnp.float32) * sx, axis=0)
        return seg.reshape((rows,) + x.shape[1:]).astype(x.dtype), None

    def _bwd(_, ct):
        return (jax.lax.all_gather(ct, axis, tiled=True),)

    scatter_q.defvjp(_fwd, _bwd)
    return scatter_q


def quantized_grad_reduce(tree, residual, axis, mean: bool = True):
    """Quantized gradient all-reduce WITH error feedback (ISSUE 12).

    ``tree`` is the local gradient pytree, ``residual`` a float32
    pytree of the same structure holding each leaf's carried
    compression error (zeros on step one —
    ``trainer.init_error_feedback`` builds and places it). Per eligible
    leaf the transmitted value is ``v = g + e``; the new residual is
    the local quantization error ``v - dequant(quantize(v))``, so what
    compression dropped this step rides into the next step's payload
    instead of biasing SGD (the classic EF-SGD identity). Every
    eligible leaf rides ONE shared two-phase schedule (4 wire
    collectives per step, not per leaf); ineligible leaves
    (small/integer) take one shared full-precision reduce and keep
    their (zero) residuals. Returns ``(reduced, new_residual)``;
    ``mean=True`` divides by the axis group size (the pmean spelling
    the data-parallel steps use).

    Not differentiable (it is the post-AD gradient reduction); call it
    outside ``jax.grad``.
    """
    op = "pmean" if mean else "psum"
    axis_key = axis if isinstance(axis, str) else tuple(axis)
    p = _axis_group_size(axis_key)
    g_leaves, treedef = jax.tree_util.tree_flatten(tree)
    e_leaves = treedef.flatten_up_to(residual)
    flags = [quantizable(g) for g in g_leaves]
    rest = [g for g, f in zip(g_leaves, flags) if not f]
    if rest:
        _account(op, axis, rest, lambda b, _p: 2.0 * (_p - 1) / _p * b)
        fn = jax.lax.pmean if mean else jax.lax.psum
        rest = list(fn(tuple(rest), axis))
    vs = [g.astype(jnp.float32) + e
          for g, e, f in zip(g_leaves, e_leaves, flags) if f]
    reduced, errs = _qallreduce_leaves(vs, axis_key, op) if vs \
        else ([], [])
    reduced_iter, err_iter, rest_iter = iter(reduced), iter(errs), \
        iter(rest)
    out, new_e = [], []
    for g, e, f in zip(g_leaves, e_leaves, flags):
        if not f:
            out.append(next(rest_iter))
            new_e.append(e)
            continue
        r = next(reduced_iter)
        out.append((r / p if mean else r).astype(g.dtype))
        new_e.append(next(err_iter))
    return (jax.tree_util.tree_unflatten(treedef, out),
            jax.tree_util.tree_unflatten(treedef, new_e))


def psum(x, axis):
    """``jax.lax.psum`` with trace-time comms accounting and the
    ambient ``collective_precision`` wire policy. Accepts the same
    (pytree, axis-or-axes) arguments; full-precision semantics
    identical, quantized semantics per the policy comment above."""
    dt = collective_dtype()
    if dt == "int8":
        return _tree_quantized_reduce(x, axis, mean=False)
    if dt == "bf16":
        xw = _tree_to_bf16(x)
        _account("psum", axis, xw, lambda b, p: 2.0 * (p - 1) / p * b)
        return _tree_cast_like(jax.lax.psum(xw, axis), x)
    _account("psum", axis, x, lambda b, p: 2.0 * (p - 1) / p * b)
    return jax.lax.psum(x, axis)


def pmean(x, axis):
    """``jax.lax.pmean`` with trace-time comms accounting and the
    ambient ``collective_precision`` wire policy (an all-reduce: same
    wire traffic as psum)."""
    dt = collective_dtype()
    if dt == "int8":
        return _tree_quantized_reduce(x, axis, mean=True)
    if dt == "bf16":
        xw = _tree_to_bf16(x)
        _account("pmean", axis, xw, lambda b, p: 2.0 * (p - 1) / p * b)
        return _tree_cast_like(jax.lax.pmean(xw, axis), x)
    _account("pmean", axis, x, lambda b, p: 2.0 * (p - 1) / p * b)
    return jax.lax.pmean(x, axis)


def all_gather(x, axis, **kwargs):
    """``jax.lax.all_gather`` with trace-time comms accounting and the
    ambient ``collective_precision`` wire policy (payload = the local
    shard; each device receives P-1 remote shards). The int8 path
    covers the package's own call shape — a single float array gathered
    tiled along dim 0; other shapes (axis_index_groups, non-tiled
    pytrees) ride the bf16/f32 paths."""
    dt = collective_dtype()
    if dt == "int8" and _single_array(x) and quantizable(x) \
            and set(kwargs) <= {"tiled"} and kwargs.get("tiled"):
        axis_key = axis if isinstance(axis, str) else tuple(axis)
        return _int8_gather(axis_key)(x)
    if dt == "bf16":
        xw = _tree_to_bf16(x)
        _account("all_gather", axis, xw, lambda b, p: (p - 1) * b)
        return _tree_cast_like(jax.lax.all_gather(xw, axis, **kwargs), x)
    _account("all_gather", axis, x, lambda b, p: (p - 1) * b)
    return jax.lax.all_gather(x, axis, **kwargs)


def ppermute(x, axis, perm):
    """``jax.lax.ppermute`` with trace-time comms accounting (one
    neighbor send of the full payload — the ring-step primitive) and
    the ambient ``collective_precision`` wire policy (ISSUE 19): an
    eligible single float array quantizes per row before the hop and
    dequantizes on arrival; gid vectors (int32) and sub-floor stat
    vectors pass through at full precision."""
    dt = collective_dtype()
    if dt == "int8" and _single_array(x) and quantizable(x):
        # Declared HERE, on abstract wire descriptors, not inside the
        # custom_vjp: scan stages the primal fn once and traces the fwd
        # thunk again under its JVP rule, so inner accounting would
        # double-declare every ring hop under grad (see _int8_permute).
        _account("ppermute", axis,
                 jax.ShapeDtypeStruct(x.shape, jnp.int8),
                 lambda b, p: float(b))
        _account("ppermute", axis,
                 jax.ShapeDtypeStruct(x.shape[:-1] + (1,), jnp.float32),
                 lambda b, p: float(b))
        axis_key = axis if isinstance(axis, str) else tuple(axis)
        return _int8_permute(axis_key, tuple(map(tuple, perm)))(x)
    if dt == "bf16":
        xw = _tree_to_bf16(x)
        _account("ppermute", axis, xw, lambda b, p: float(b))
        return _tree_cast_like(jax.lax.ppermute(xw, axis, perm), x)
    _account("ppermute", axis, x, lambda b, p: float(b))
    return jax.lax.ppermute(x, axis, perm)


def chunk_bounds(n: int, chunks: int) -> list[tuple[int, int]]:
    """Static ``[lo, hi)`` row bounds splitting ``n`` rows into
    ``chunks`` contiguous pieces, remainder rows riding the leading
    chunks (sizes differ by at most one; every chunk non-empty)."""
    c = max(1, min(int(chunks), int(n))) if n else 1
    base, rem = divmod(int(n), c)
    bounds, lo = [], 0
    for i in range(c):
        hi = lo + base + (1 if i < rem else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def ppermute_chunked(x, axis, perm, chunks: int):
    """One ring hop split into ``chunks`` independent ppermutes along
    dim 0 (ISSUE 19 — the overlap primitive). Each chunk is its own
    collective in the traced graph, so the async scheduler can start
    chunk k+1's transfer while chunk k's consumer compute runs;
    byte-identical to the monolithic send (the chunks partition the
    payload) and each chunk rides the ambient wire-precision policy
    independently. ``chunks <= 1`` degrades to one plain hop."""
    c = max(int(chunks), 1)
    if c <= 1 or getattr(x, "ndim", 0) < 1 or x.shape[0] <= 1:
        return ppermute(x, axis, perm)
    parts = [ppermute(x[lo:hi], axis, perm)
             for lo, hi in chunk_bounds(x.shape[0], c)]
    return jnp.concatenate(parts, axis=0)


def psum_scatter(x, axis, **kwargs):
    """``jax.lax.psum_scatter`` with trace-time comms accounting and
    the ambient ``collective_precision`` wire policy (the
    reduce-scatter half of an all-reduce). The int8 path covers the
    tiled, scatter-dim-0 shape with the leading dim divisible by the
    group; anything else rides bf16/f32."""
    dt = collective_dtype()
    if dt == "int8" and _single_array(x) and quantizable(x) \
            and set(kwargs) <= {"tiled", "scatter_dimension"} \
            and kwargs.get("tiled") \
            and kwargs.get("scatter_dimension", 0) == 0:
        try:
            divisible = x.shape[0] % _axis_group_size(axis) == 0
        except Exception:  # no axis bound: let lax raise its own error
            divisible = False
        if divisible:
            axis_key = axis if isinstance(axis, str) else tuple(axis)
            return _int8_scatter(axis_key)(x)
    if dt == "bf16":
        xw = _tree_to_bf16(x)
        _account("psum_scatter", axis, xw, lambda b, p: (p - 1) / p * b)
        return _tree_cast_like(
            jax.lax.psum_scatter(xw, axis, **kwargs), x)
    _account("psum_scatter", axis, x, lambda b, p: (p - 1) / p * b)
    return jax.lax.psum_scatter(x, axis, **kwargs)


def pmax(x, axis):
    """``jax.lax.pmax`` with trace-time comms accounting (an all-reduce:
    same wire traffic as psum)."""
    _account("pmax", axis, x, lambda b, p: 2.0 * (p - 1) / p * b)
    return jax.lax.pmax(x, axis)


def all_to_all(x, axis, **kwargs):
    """``jax.lax.all_to_all`` with trace-time comms accounting (each
    device sends every slice but its own: (P-1)/P of the buffer — the
    MoE expert-dispatch and ring-attention head-reshard primitive)."""
    _account("all_to_all", axis, x, lambda b, p: (p - 1) / p * b)
    return jax.lax.all_to_all(x, axis, **kwargs)


def _install_old_jax_transpose_fix() -> None:
    """Own the old-jax ``shard_map`` gradient seam (robustness shim).

    On old jax, differentiating THROUGH a ``shard_map`` whose linearized
    body carries residuals fails with ``_SpecError`` whenever the
    backward pass leaks a cotangent onto a residual input: upstream's
    transpose rule turns every nonzero cotangent ``ad.backward_pass``
    returns into an output of the transposed shard_map, zipped against
    the FORWARD's ``in_names`` — but cotangents are only owed to the
    undefined primals, and a leaked residual cotangent (the transpose of
    ``add`` writes to both operands; a promoted scalar residual arrives
    back as a bare scalar) fails the output spec check. The fixed rule
    below keeps upstream's structure and simply drops cotangents at
    non-undefined positions before binding the transposed shard_map —
    transposition by definition owes nothing there. New jax fixed this
    upstream; old installs get the same semantics from here, which is
    what lets ``jax.grad`` flow through the distributed losses, the TP/
    FSDP steps and the GPipe schedule on this fleet (the pre-elastic
    tier-1 failure set).
    """
    import jax.experimental.shard_map as _shmap
    from jax._src import core as _core
    from jax._src import dtypes as _dtypes
    from jax._src import linear_util as _lu
    from jax._src.interpreters import ad as _ad
    from jax._src.interpreters import partial_eval as _pe
    from jax._src.util import partition_list as _partition_list
    from jax.api_util import flatten_fun_nokwargs as _flatten_nokwargs
    from jax.tree_util import tree_flatten, tree_unflatten
    from math import prod as _prod

    def _transpose_fixed(out_cts, *args, jaxpr, mesh, in_names, out_names,
                         check_rep, rewrite, auto):
        mb_div = lambda x, y: x / y if y != 1 else x  # noqa: E731
        out_cts = [
            _ad.Zero(_shmap._shard_aval(mesh, ns, x.aval))
            if type(x) is _ad.Zero
            else x if rewrite or _dtypes.dtype(x) == _dtypes.float0
            else mb_div(x, _prod(map(mesh.shape.get,
                                     _shmap._unmentioned2(mesh, ns, auto))))
            for ns, x in zip(out_names, out_cts)]
        args = [x if type(x) is not _ad.UndefinedPrimal else
                _ad.UndefinedPrimal(_shmap._shard_aval(mesh, ns, x.aval))
                for ns, x in zip(in_names, args)]
        all_args, in_tree = tree_flatten((out_cts, args))

        @_lu.wrap_init
        def fun_trans(out_cts, args):
            undef = list(map(_ad.is_undefined_primal, args))
            res, undefs = _partition_list(undef, args)
            jaxpr_known, jaxpr_unknown, _, _ = _pe.partial_eval_jaxpr_nounits(
                _pe.close_jaxpr(jaxpr), undef, False)
            res_reshaped = _core.jaxpr_as_fun(jaxpr_known)(*res)
            all_cts = _ad.backward_pass(
                jaxpr_unknown.jaxpr, False, (),
                (*res_reshaped, *undefs), out_cts)
            # jaxpr_unknown's invars are [*new_residuals, *undefined
            # primals]: keep only the trailing undefined-primal
            # cotangents (THE FIX — leaked residual cotangents must not
            # become outputs of the transposed shard_map).
            undef_cts = iter(all_cts[len(all_cts) - len(undefs):])
            out = [next(undef_cts) if u
                   else _ad.Zero(_core.get_aval(x).to_tangent_aval())
                   for u, x in zip(undef, args)]
            out = [_ad.Zero(_shmap._unshard_aval(mesh, ns, x.aval))
                   if type(x) is _ad.Zero
                   else x if rewrite
                   else jax.lax.psum(x, tuple(
                       _shmap._unmentioned2(mesh, ns, auto)))
                   for ns, x in zip(in_names, out)]
            return out

        fun_trans, nz_arg_cts = _ad.nonzero_outputs(fun_trans)
        fun_trans_flat, out_tree = _flatten_nokwargs(fun_trans, in_tree)

        new_in_names = \
            [n for n, x in zip(out_names, out_cts)
             if type(x) is not _ad.Zero] + \
            [n for n, x in zip(in_names, args)
             if type(x) is not _ad.UndefinedPrimal]

        def new_out_names_thunk():
            return tuple(names for names, nz in zip(in_names, nz_arg_cts())
                         if nz)

        out_flat = _shmap.shard_map_p.bind(
            fun_trans_flat, *all_args, mesh=mesh,
            in_names=tuple(new_in_names),
            out_names_thunk=new_out_names_thunk, check_rep=check_rep,
            rewrite=rewrite, auto=auto)
        return tree_unflatten(out_tree(), out_flat)

    _ad.primitive_transposes[_shmap.shard_map_p] = _transpose_fixed


if _OLD_JAX:
    try:
        _install_old_jax_transpose_fix()
    except Exception:  # never break import over a shim install
        logger.exception(
            "old-jax shard_map transpose fix failed to install; "
            "grad-through-shard_map keeps upstream's _SpecError behavior")


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids: Sequence[int] | None = None,
    initialization_timeout: int | None = None,
) -> None:
    """Multi-host rendezvous (the ``mpirun``/``MPI_Init`` role).

    On Cloud TPU all arguments auto-detect from the environment; pass them
    explicitly elsewhere. Safe to call when already initialized or when
    running single-process with no coordinator configured (both are no-ops
    with a log line); explicit-argument failures propagate.

    NOTE: deliberately does NOT touch ``jax.process_count()``/``jax.devices()``
    before initializing — those calls initialize the XLA backends, after
    which ``jax.distributed.initialize`` refuses to run.
    """
    from jax._src import distributed as _distributed

    if _distributed.global_state.client is not None:
        logger.info("jax.distributed already initialized")
        return
    explicit = coordinator_address is not None
    kwargs = {}
    if initialization_timeout is not None:
        # Bound the rendezvous wait (default is 300 s) — e.g. fail-fast
        # health checks on a coordinator that never comes up.
        kwargs["initialization_timeout"] = initialization_timeout
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
            **kwargs,
        )
        logger.info("distributed init: process %d/%d, %d local devices",
                    jax.process_index(), jax.process_count(),
                    jax.local_device_count())
    except (RuntimeError, ValueError):
        if explicit:
            raise  # a configured coordinator that fails is a real error
        # Auto-detection found no cluster: single-process is a supported mode.
        logger.info("no cluster environment detected; single-process mode")


def create_mesh(
    shape: Sequence[int] | None = None,
    axis_names: Sequence[str] = ("data",),
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh over the available devices.

    Default: a 1-D ``('data',)`` mesh over all devices — the classic SimCLR
    data-parallel layout where ``lax.all_gather`` of embeddings rides ICI.
    Pass ``shape``/``axis_names`` for hybrid layouts, e.g.
    ``shape=(4, 2), axis_names=('data', 'model')`` for the ViT/CLIP configs.

    When no explicit device list is given, devices are ordered by
    ``mesh_utils.create_device_mesh`` so mesh-adjacent devices sit on
    adjacent ICI links (raw ``jax.devices()`` order does not guarantee that
    on multi-dim TPU topologies).
    """
    if devices is None:
        n = jax.device_count()
        if shape is None:
            shape = (n,)
        if int(np.prod(shape)) != n:
            raise ValueError(f"mesh shape {tuple(shape)} != {n} devices")
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(tuple(shape))
        return Mesh(dev_array, tuple(axis_names))
    devices = list(devices)
    if shape is None:
        shape = (len(devices),)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {tuple(shape)} != {len(devices)} devices")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def create_hybrid_mesh(
    ici_shape: Sequence[int],
    dcn_shape: Sequence[int],
    axis_names: Sequence[str] = ("data", "model"),
) -> Mesh:
    """Multi-slice mesh: DCN-parallel axes outermost, ICI axes innermost.

    The ICI/DCN layout rule for TPU pods ("How to Scale Your Model"
    recipe): axes whose collectives are frequent and latency-sensitive
    (tensor/sequence parallel psum, ring ppermute) must map onto
    intra-slice ICI links; axes whose collectives are rare and bulky
    (data-parallel gradient reduction) can cross the slower
    data-center network between slices. ``dcn_shape[i]`` multiplies
    ``ici_shape[i]`` into the full axis: e.g. 2 slices of 16 chips with
    ``ici_shape=(4, 4), dcn_shape=(2, 1)`` gives an 8x4 ('data',
    'model') mesh where 'model' collectives never leave a slice and
    'data' spans both.

    On real multi-slice TPU this wraps
    ``mesh_utils.create_hybrid_device_mesh`` (slice-aware device
    ordering); where slice topology is unavailable (CPU meshes, single
    slice) it degrades to the plain ``create_device_mesh`` with the
    combined shape — the same axes, without the physical ordering claim.
    """
    from jax.experimental import mesh_utils

    if len(ici_shape) != len(dcn_shape) or len(ici_shape) != len(axis_names):
        raise ValueError(
            f"ici_shape {tuple(ici_shape)}, dcn_shape {tuple(dcn_shape)} "
            f"and axis_names {tuple(axis_names)} must have equal length")
    total = int(np.prod(ici_shape)) * int(np.prod(dcn_shape))
    if total != jax.device_count():
        raise ValueError(f"hybrid mesh wants {total} devices, have "
                         f"{jax.device_count()}")
    # Degrade to flat ordering ONLY where slice topology does not exist
    # (CPU meshes, single slice) — on real multi-slice hardware a
    # create_hybrid_device_mesh failure is a misconfiguration (e.g.
    # per-slice product != slice size) and must surface, not silently
    # produce the DCN-spanning layout this helper exists to prevent.
    if getattr(jax.devices()[0], "slice_index", None) is None:
        logger.info("no slice topology on this backend; using the flat "
                    "mesh with the combined shape")
        combined = tuple(i * d for i, d in zip(ici_shape, dcn_shape))
        return create_mesh(combined, axis_names)
    dev_array = mesh_utils.create_hybrid_device_mesh(
        tuple(ici_shape), tuple(dcn_shape))
    return Mesh(dev_array, tuple(axis_names))


def data_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Batch-dim sharding: rows split across ``axis``, features replicated."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded_prefetch(iterator, mesh: Mesh, axis: str = "data",
                     depth: int = 2):
    """Async pipeline stage for the sharded train path: batches prefetched
    as COMMITTED global arrays laid out over the mesh's ``axis``.

    The overlap-friendly replacement for a per-step ``trainer.shard_batch``
    (which blocks the critical path on placement every step): a
    ``training.data.DevicePrefetcher`` bound to this mesh's batch sharding
    keeps ``depth`` batches transferring under the running step, and the
    sharded step receives arrays that already match its in_specs.
    """
    from ..training.data import DevicePrefetcher

    return DevicePrefetcher(iterator, depth=depth,
                            sharding=data_sharding(mesh, axis))


def replicate_state(tree, mesh: Mesh):
    """Commit every leaf of a pytree to the mesh, fully replicated.

    Freshly-created arrays are UNcommitted (jit re-places them freely), so
    data-parallel steps appear to work without this — but arrays that come
    back from a checkpoint restore are committed to whatever sharding the
    restore template carried (a fresh template ⇒ single-device), and the
    next sharded step fails with "incompatible devices". Replicating the
    template BEFORE restore places the restored leaves straight onto the
    mesh (CheckpointManager restores onto the template's shardings) —
    which is also what makes a checkpoint from an 8-device run resume on a
    4-device mesh (elastic recovery: the global computation is
    device-count-invariant for replicated params + synced BatchNorm).
    """
    return jax.device_put(tree, replicated_sharding(mesh))


def global_batch(local_batch, mesh: Mesh, axis: str = "data"):
    """Assemble per-process host batches into one global sharded array.

    The multi-host counterpart of ``trainer.shard_batch`` (which only
    handles fully-addressable meshes): every process passes the rows ITS
    devices will own — e.g. each rank's slice of the global batch, the role
    per-rank DataLoaders played in the reference's implied NCCL-SimCLR
    pattern (SURVEY.md §2.2) — and the result is a global ``jax.Array``
    sharded over ``axis`` that sharded train steps consume directly.
    Works single-process too (where it reduces to a device_put).
    """
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)),
        local_batch)


def local_row_gids(axis: str, n_local: int, num_devices: int):
    """Global row indices of this shard's rows in the stacked-view layout.

    Global layout is ``[view1 of all devices; view2 of all devices]`` (the
    order ``lax.all_gather`` + concat produces): device d's view-1 rows are
    ``d*n_local + [0, n_local)`` and its view-2 rows are ``N + d*n_local +
    [0, n_local)`` with ``N = n_local * num_devices``. Call inside
    ``shard_map``.
    """
    import jax.numpy as jnp

    d = jax.lax.axis_index(axis)
    n_total = n_local * num_devices
    base = d * n_local + jnp.arange(n_local, dtype=jnp.int32)
    return jnp.concatenate([base, n_total + base])


def process_info() -> dict:
    """Rank/world-size style info (what MPI_Comm_rank/size reported)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }


# ---------------------------------------------------------------------------
# Elastic topology: logical PartitionSpec trees that survive mesh changes
# ---------------------------------------------------------------------------
#
# A checkpoint taken on an N-device mesh must restore onto an M-device one
# (preemptible fleets shrink and grow back; ROADMAP item 5). The physical
# layout dies with the old mesh, so what gets persisted is the LOGICAL
# placement — a JSON-able PartitionSpec tree over flattened tree paths plus
# the mesh's shape/axis names — and restore re-resolves it against whatever
# mesh the new incarnation built. The helpers below are that vocabulary
# (the match_partition_rules/shard-fn pattern); training/checkpoint.py is
# the consumer.


def _tree_paths_and_leaves(tree: Any, sep: str = "/"):
    """[(path_string, leaf)] over a pytree, flax-style ``a/b/c`` paths."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for entry in path:
            if hasattr(entry, "key"):
                parts.append(str(entry.key))
            elif hasattr(entry, "idx"):
                parts.append(str(entry.idx))
            elif hasattr(entry, "name"):
                parts.append(str(entry.name))
            else:
                parts.append(str(entry))
        out.append((sep.join(parts), leaf))
    return out


def _spec_to_json(spec: P | None) -> list | None:
    """PartitionSpec -> JSON (list per dim: axis name, list of names, or
    None). None means 'no recorded spec' (a non-jax leaf)."""
    if spec is None:
        return None
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append(list(entry))
        else:
            out.append(entry)
    return out


def _spec_from_json(entry: list | None) -> P:
    if not entry:
        return P()
    return P(*[tuple(e) if isinstance(e, list) else e for e in entry])


def mesh_topology(mesh: Mesh | None) -> dict:
    """JSON-able identity of a mesh: what restore compares against the
    ambient world to decide whether re-sharding is needed."""
    if mesh is None:
        return {"device_count": jax.device_count(), "shape": None,
                "axis_names": None,
                "process_count": jax.process_count()}
    return {"device_count": int(mesh.size),
            "shape": [int(s) for s in mesh.devices.shape],
            "axis_names": list(mesh.axis_names),
            "process_count": jax.process_count()}


def tree_partition_specs(tree: Any, sep: str = "/") -> dict:
    """Record the logical placement of a (device) pytree: flattened path ->
    JSON spec, plus the mesh identity. Leaves without a ``NamedSharding``
    (host numpy, scalars) record ``None`` (placement decided at restore).
    """
    specs: dict[str, list | None] = {}
    mesh = None
    for path, leaf in _tree_paths_and_leaves(tree, sep):
        spec = None
        sharding = getattr(leaf, "sharding", None)
        if isinstance(sharding, NamedSharding):
            spec = sharding.spec
            if mesh is None:
                mesh = sharding.mesh
        specs[path] = _spec_to_json(spec)
    return {"specs": specs, "mesh": mesh_topology(mesh), "version": 1}


def match_partition_rules(rules: Sequence[tuple[str, P]], tree: Any,
                          sep: str = "/") -> Any:
    """Pytree of PartitionSpecs from regex rules over flattened paths.

    The classic spec-resolver pattern: ``rules`` is an ordered list of
    ``(regex, PartitionSpec)``; the first regex that ``re.search``-matches
    a leaf's ``a/b/c`` path decides its spec. Scalars (and 1-element
    arrays) are never partitioned regardless of rules. A path no rule
    matches raises — silent replication of a tensor meant to be sharded
    is how elastic restores corrupt layouts quietly.
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def resolve(path: str, leaf: Any) -> P:
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        for pat, spec in compiled:
            if pat.search(path) is not None:
                return spec
        raise ValueError(f"no partition rule matches {path!r}")

    paths = _tree_paths_and_leaves(tree, sep)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    resolved = [resolve(path, leaf) for path, leaf in paths]
    assert len(resolved) == len(leaves)
    return jax.tree_util.tree_unflatten(treedef, resolved)


def resolve_restore_specs(recorded: dict, mesh: Mesh, tree: Any,
                          sep: str = "/") -> Any:
    """Re-resolve a recorded spec tree against a NEW mesh.

    For every leaf: take the recorded logical spec (by flattened path),
    drop axis names the new mesh does not have, and drop any sharded dim
    the leaf's shape no longer divides by the new axis size — the leaf
    then falls back toward replication one axis at a time instead of
    failing the whole restore. Unrecorded paths (grown params, pre-elastic
    checkpoints) resolve to replicated. Returns a PartitionSpec pytree
    shaped like ``tree``.
    """
    specs = recorded.get("specs", {}) if recorded else {}

    def resolve(path: str, leaf: Any) -> P:
        entry = specs.get(path)
        if not entry:
            return P()
        shape = getattr(leaf, "shape", ())
        out = []
        for dim, names in enumerate(_spec_from_json(entry)):
            if names is None:
                out.append(None)
                continue
            group = names if isinstance(names, tuple) else (names,)
            kept = tuple(n for n in group if n in mesh.shape)
            size = int(np.prod([mesh.shape[n] for n in kept])) \
                if kept else 1
            if not kept or dim >= len(shape) or shape[dim] % size:
                out.append(None)
                continue
            out.append(kept if len(kept) > 1 else kept[0])
        return P(*out)

    paths = _tree_paths_and_leaves(tree, sep)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    resolved = [resolve(path, leaf) for path, leaf in paths]
    assert len(resolved) == len(leaves)
    return jax.tree_util.tree_unflatten(treedef, resolved)


def place_with_specs(tree: Any, mesh: Mesh, specs: Any):
    """Commit every leaf onto ``mesh`` under its spec (the shard-fn half
    of the pattern: host values in, mesh-committed global arrays out)."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        tree, specs)
