"""Pair-parallel NT-Xent: balanced symmetric tile assignment across devices.

The global (2N, 2N) similarity matrix is symmetric, so the classic
data-parallel decomposition — every device computes its full local-rows x
global-cols strip (`dist_loss.local_ntxent_allgather`) — computes every
off-diagonal shard-pair tile TWICE across the mesh (device d produces
S[rows_d, cols_e]; device e produces the same tile transposed). Here each
unordered shard pair {d, e} is walked once, on a balanced round-robin
schedule: device d takes column shards (d + k) mod P for k = 0..⌈(P-1)/2⌉,
and for even P the k = P/2 pair (claimed by both endpoints) is weighted ½
on each. Per tile, the dual block kernels
(`ops.ntxent_pallas.block_lse_dual` / `block_grads_dual`) fold the single
MXU walk into BOTH sides' statistics/gradients.

Matmul-unit accounting per shard-pair tile position (P = 8):

| | strip (gather path) | pair-parallel |
|---|---|---|
| forward | 1.0 x P | 1.0 x (P/2 + 1/2) |
| backward | 4.0 x P (rows+cols kernels) | 3.0 x (P/2 + 1/2) |
| fwd+bwd total | 5 P = 40 | 2.25 P = 18 |

i.e. ~2.2x fewer loss matmuls at P = 8. Cross-device assembly: the column
statistics merge with an (2N,)-vector logsumexp psum (forward) and the
gradient contributions with one (2N, D) psum (backward — the same volume
as the strip path's AD-derived reduce-scatter). Positives stay local
(each row's paired view lives on the same shard) and differentiate by AD.

This is an opt-in alternative to the strip path (`impl="pair"` on
`make_sharded_ntxent`); the strip remains the default until the crossover
is profiled on real hardware (the pair schedule trades matmuls for two
extra small collectives and loses when the loss is dispatch-bound).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.ntxent_pallas import block_grads_dual, block_lse_dual
from .mesh import all_gather as _all_gather_acct
from .mesh import axis_index as _axis_index_compat
from .mesh import local_row_gids
from .mesh import pmax as _pmax_acct
from .mesh import psum as _psum_acct
from .mesh import shard_map as _shard_map_compat

__all__ = ["make_pair_ntxent", "ntxent_loss_pair", "pair_body"]

_NEG_INF = -1e30


def _shard_gids(e, n_local: int, num_devices: int):
    """Canonical stacked-view global ids of shard ``e``'s rows: view-1 rows
    [e·n, (e+1)·n) and view-2 rows [N + e·n, N + (e+1)·n)."""
    n_total = n_local * num_devices
    base = e * n_local + jnp.arange(n_local, dtype=jnp.int32)
    return jnp.concatenate([base, base + n_total])


def _tile_schedule(num_devices: int):
    """(k, weight) pairs for this mesh size: offsets each device walks.

    k = 0 is the self tile (its transpose is itself — folded once);
    1..⌈(P-1)/2⌉ are full-weight pairs; for even P the antipodal k = P/2
    pair is claimed by both endpoints at weight ½ each.
    """
    ks = [(0, 1.0)]
    half = (num_devices - 1) // 2
    ks += [(k, 1.0) for k in range(1, half + 1)]
    if num_devices % 2 == 0 and num_devices > 1:
        ks.append((num_devices // 2, 0.5))
    return ks


def _make_pair_lse_sum(temperature: float, axis: str, num_devices: int,
                      interpret: bool | None):
    """custom-VJP scalar ``S = Σ_local rows lse_i`` over the global matrix,
    computed with the balanced pair schedule (see module docstring).

    INVARIANT (uniform cotangent): the backward scales the psum'd GLOBAL
    gradient buffer by this device's own cotangent ``ct`` — valid only
    when ``ct`` is identical on every shard. That holds for the sole
    caller (``_pair_body``: the loss is psum'd then divided by a global
    constant, so AD hands every device the same scalar), and it is what
    makes the pair schedule work — tiles for rows owned by OTHER devices
    are computed here and psum'd home, and a per-device ``ct`` would have
    to travel with each tile's rows (an extra all_gather of P scalars) to
    stay correct. If you reuse this VJP under a non-uniform cotangent,
    psum/gather the per-row owners' cotangents and scale ``buf`` rows
    before the psum instead."""

    @jax.custom_vjp
    def pair_lse_sum(z_local, my_gid):
        return _fwd(z_local, my_gid)[0]

    def _tiles(z_g, d, two_n_local):
        for k, w in _tile_schedule(num_devices):
            e = jax.lax.rem(d + k, num_devices)
            ze = jax.lax.dynamic_slice_in_dim(z_g, e * two_n_local,
                                              two_n_local)
            gid_e = _shard_gids(e, two_n_local // 2, num_devices)
            yield k, w, ze, gid_e

    def _lse_all(z_local, my_gid):
        two_n_local = z_local.shape[0]
        two_n = two_n_local * num_devices
        d = _axis_index_compat(axis)
        z_g = _all_gather_acct(z_local, axis, tiled=True)
        lse_part = jnp.full((two_n,), _NEG_INF, jnp.float32)
        for k, w, ze, gid_e in _tiles(z_g, d, two_n_local):
            lr, lc = block_lse_dual(z_local, ze, my_gid, gid_e,
                                    temperature, two_n,
                                    interpret=interpret)
            if w != 1.0:  # weight in lse space: l·w ⇔ lse + log w
                logw = jnp.float32(math.log(w))
                lr, lc = lr + logw, lc + logw
            lse_part = lse_part.at[my_gid].set(
                jnp.logaddexp(lse_part[my_gid], lr))
            if k != 0:
                # k = 0's transpose is the same tile — folding lc too
                # would double-count the self pair.
                lse_part = lse_part.at[gid_e].set(
                    jnp.logaddexp(lse_part[gid_e], lc))
        m = _pmax_acct(lse_part, axis)
        lse_all = m + jnp.log(
            _psum_acct(jnp.exp(lse_part - m), axis))
        return z_g, lse_all

    def _fwd(z_local, my_gid):
        z_g, lse_all = _lse_all(z_local, my_gid)
        return jnp.sum(jnp.take(lse_all, my_gid)), (
            z_local, my_gid, z_g, lse_all)

    def _bwd(res, ct):
        z_local, my_gid, z_g, lse_all = res
        two_n_local, dim = z_local.shape
        two_n = two_n_local * num_devices
        d = _axis_index_compat(axis)
        buf = jnp.zeros((two_n, dim), jnp.float32)
        for k, w, ze, gid_e in _tiles(z_g, d, two_n_local):
            gr, gc = block_grads_dual(
                z_local, ze, my_gid, gid_e,
                jnp.take(lse_all, my_gid), jnp.take(lse_all, gid_e),
                temperature, two_n, interpret=interpret)
            if k == 0:
                # The self tile's G already contains both directions
                # (lse_r == lse_c there); gc would double it.
                buf = buf.at[my_gid].add(gr)
            else:
                buf = buf.at[my_gid].add(w * gr)
                buf = buf.at[gid_e].add(w * gc)
        grad_full = _psum_acct(buf, axis)
        grad = jnp.take(grad_full, my_gid, axis=0) * (ct / temperature)
        return grad.astype(z_local.dtype), None

    pair_lse_sum.defvjp(_fwd, _bwd)
    return pair_lse_sum


def _pair_body(z1_local, z2_local, temperature, axis, num_devices,
               interpret):
    n_local = z1_local.shape[0]
    two_n = 2 * n_local * num_devices
    inv_t = 1.0 / temperature

    z_local = jnp.concatenate([z1_local, z2_local], axis=0)
    my_gid = local_row_gids(axis, n_local, num_devices)
    # Positives are device-local pairs; their gradient comes from AD of
    # this expression (the -E term of d loss/d s).
    pos = jnp.sum(z1_local * z2_local, axis=-1, dtype=jnp.float32) * inv_t
    pos = jnp.concatenate([pos, pos])

    lse_sum = _make_pair_lse_sum(temperature, axis, num_devices,
                                 interpret)(z_local, my_gid)
    loss_sum = lse_sum - jnp.sum(pos)
    return _psum_acct(loss_sum, axis) / two_n


# Public alias: the per-device body shared with the train-step factory
# (same signature as dist_loss.local_ntxent_allgather).
pair_body = _pair_body


def make_pair_ntxent(
    mesh: Mesh,
    temperature: float = 0.07,
    axis: str = "data",
    interpret: bool | None = None,
):
    """Build a jit-able pair-parallel global-batch NT-Xent over ``mesh``.

    Same contract as ``dist_loss.make_sharded_ntxent`` — (z1, z2) sharded
    along ``axis`` → replicated scalar mean loss with exact gradients —
    at roughly half the loss matmuls (see module docstring).
    """
    body = functools.partial(
        _pair_body,
        temperature=float(temperature),
        axis=axis,
        num_devices=mesh.shape[axis],
        interpret=interpret,
    )
    return _shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(),
        check_vma=False,
    )


def ntxent_loss_pair(
    z1: jax.Array,
    z2: jax.Array,
    mesh: Mesh,
    temperature: float = 0.07,
    axis: str = "data",
    interpret: bool | None = None,
) -> jax.Array:
    """Global-batch canonical NT-Xent, pair-parallel (one-shot form)."""
    return make_pair_ntxent(mesh, temperature, axis, interpret)(z1, z2)
