"""Ring NT-Xent: the ring-attention analog for contrastive loss.

The framework's sequence/context-parallel story (SURVEY.md §2.2, §5.7): the
quadratic object here is the (2N, 2N) similarity matrix, so "long context"
means global batches whose gathered embeddings don't fit per-chip memory. The
ring variant never gathers: each device's embedding block circulates around
the ICI ring via ``lax.ppermute`` while every device folds each visiting
block into flash-style online-softmax statistics (running max m, running sum
l) for its local rows. After P steps each device has seen all 2N columns:
memory is O(N/P) per chip, bandwidth rides neighbor ICI links only, and the
compute/communication pattern is exactly ring attention's (blockwise
accumulate + neighbor ppermute), minus the value accumulation.

Gradients come from ``jax.grad`` through the ``lax.scan`` of ppermute steps:
the VJP of ppermute is the reverse-direction ppermute, so the backward pass
is itself a ring pass — the hand-written reverse-ring NCCL code this replaces.

Scale target: BASELINE.json configs[4] (global batch 32768 CLIP/InfoNCE).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.infonce_pallas import resolve_scale
from ..ops.ntxent_pallas import _exp0, _log_l
from .mesh import comms_scaled as _comms_scaled
from .mesh import local_row_gids
from .mesh import pcast as _pcast_compat
from .mesh import ppermute as _ppermute_acct
from .mesh import psum as _psum_acct
from .mesh import shard_map as _shard_map_compat

__all__ = ["ntxent_loss_ring", "make_ring_ntxent",
           "info_nce_loss_ring", "make_ring_infonce"]

_NEG_INF = -1e30


def _ring_body(z1_local, z2_local, temperature, axis, num_devices):
    n_local, dim = z1_local.shape
    two_n_local = 2 * n_local
    two_n = 2 * n_local * num_devices
    inv_t = 1.0 / temperature

    z_local = jnp.concatenate([z1_local, z2_local], axis=0)
    my_gid = local_row_gids(axis, n_local, num_devices)

    # Positive similarities are device-local in the stacked-view layout:
    # view-1 row i pairs with view-2 row i of the same device.
    pos = jnp.sum(z1_local * z2_local, axis=-1, dtype=jnp.float32) * inv_t
    pos = jnp.concatenate([pos, pos])

    perm = [(i, (i + 1) % num_devices) for i in range(num_devices)]

    def fold(block, block_gid, m, l):
        """Fold one visiting column block into the online-softmax stats."""
        s = jnp.dot(z_local, block.T, preferred_element_type=jnp.float32)
        s = s * inv_t
        mask = my_gid[:, None] == block_gid[None, :]
        s = jnp.where(mask, _NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        l = l * jnp.exp(m - m_new) + jnp.sum(_exp0(s - m_new[:, None]), axis=1)
        return m_new, l

    def step(carry, _):
        block, block_gid, m, l = carry
        m, l = fold(block, block_gid, m, l)
        block = _ppermute_acct(block, axis, perm)
        block_gid = _ppermute_acct(block_gid, axis, perm)
        return (block, block_gid, m, l), None

    # pcast to 'varying': the m/l statistics start device-invariant but
    # become varying across the ring axis inside the scan; the scan carry
    # types must agree.
    init = (
        z_local,
        my_gid,
        _pcast_compat(jnp.full((two_n_local,), _NEG_INF, jnp.float32),
                      (axis,), to="varying"),
        _pcast_compat(jnp.zeros((two_n_local,), jnp.float32),
                      (axis,), to="varying"),
    )
    # P-1 exchanges suffice: fold the final visiting block outside the scan
    # instead of permuting it back to its origin (a wasted ICI hop).
    # comms_scaled: the body's collectives trace once but run P-1 times.
    with _comms_scaled(num_devices - 1):
        (block, block_gid, m, l), _ = jax.lax.scan(
            step, init, None, length=num_devices - 1
        )
    m, l = fold(block, block_gid, m, l)
    lse = m + _log_l(l)
    loss_sum = jnp.sum(lse - pos)
    return _psum_acct(loss_sum, axis) / two_n


def _make_ring_lse_sum(temperature: float, axis: str, num_devices: int,
                       interpret: bool | None):
    """custom-VJP scalar ``S = sum_i lse_i`` over this device's rows, where
    lse is the global-row logsumexp accumulated around the ring with the
    fused Pallas block kernels (ops.ntxent_pallas.block_lse/block_grads).

    Forward: P-1 neighbor exchanges; each hop folds the visiting block's
    per-row lse (one fused kernel call — the (R, C) tile never leaves VMEM)
    into running (m, l) via logaddexp. Backward is a second ring pass: the
    row-side gradient accumulates locally while the column-side gradient of
    each visiting block circulates home WITH the block (P hops = one full
    circle) — ring attention's backward, with the VJP matmuls on the MXU via
    the fused backward kernels instead of AD through the forward scan.
    """
    from ..ops.ntxent_pallas import block_grads, block_lse

    perm = [(i, (i + 1) % num_devices) for i in range(num_devices)]

    @jax.custom_vjp
    def ring_lse_sum(z_local, my_gid):
        return _fwd(z_local, my_gid)[0]

    def _lse(z_local, my_gid):
        two_n = z_local.shape[0] * num_devices

        def step(carry, _):
            blk, bgid, m, l = carry
            lse_k = block_lse(z_local, blk, my_gid, bgid, temperature,
                              two_n, interpret=interpret)
            m_new = jnp.maximum(m, lse_k)
            l = l * jnp.exp(m - m_new) + jnp.exp(lse_k - m_new)
            blk = _ppermute_acct(blk, axis, perm)
            bgid = _ppermute_acct(bgid, axis, perm)
            return (blk, bgid, m_new, l), None

        rows = z_local.shape[0]
        init = (z_local, my_gid,
                jnp.full((rows,), _NEG_INF, jnp.float32),
                jnp.zeros((rows,), jnp.float32))
        with _comms_scaled(num_devices - 1):
            (blk, bgid, m, l), _ = jax.lax.scan(
                step, init, None, length=num_devices - 1)
        lse_k = block_lse(z_local, blk, my_gid, bgid, temperature,
                          two_n, interpret=interpret)
        m_new = jnp.maximum(m, lse_k)
        l = l * jnp.exp(m - m_new) + jnp.exp(lse_k - m_new)
        return m_new + _log_l(l)

    def _fwd(z_local, my_gid):
        lse = _lse(z_local, my_gid)
        return jnp.sum(lse), (z_local, my_gid, lse)

    def _bwd(res, ct):
        z_local, my_gid, lse = res
        two_n = z_local.shape[0] * num_devices

        def step(carry, _):
            blk, bgid, gblk, grows = carry
            gr_k, gc_k = block_grads(z_local, blk, my_gid, bgid, lse,
                                     temperature, two_n,
                                     interpret=interpret)
            grows = grows + gr_k
            gblk = gblk + gc_k
            # gblk rides WITH its block: after num_devices hops both are
            # home, gblk holding every device's column-side contribution.
            blk = _ppermute_acct(blk, axis, perm)
            bgid = _ppermute_acct(bgid, axis, perm)
            gblk = _ppermute_acct(gblk, axis, perm)
            return (blk, bgid, gblk, grows), None

        init = (z_local, my_gid,
                jnp.zeros(z_local.shape, jnp.float32),
                jnp.zeros(z_local.shape, jnp.float32))
        with _comms_scaled(num_devices):
            (_, _, gblk, grows), _ = jax.lax.scan(
                step, init, None, length=num_devices)
        grad = (grows + gblk) * (ct / temperature)
        return grad.astype(z_local.dtype), None

    ring_lse_sum.defvjp(_fwd, _bwd)
    return ring_lse_sum


def _ring_body_fused(z1_local, z2_local, temperature, axis, num_devices,
                     interpret):
    """Fused-kernel ring NT-Xent body (see _make_ring_lse_sum)."""
    n_local = z1_local.shape[0]
    two_n = 2 * n_local * num_devices
    inv_t = 1.0 / temperature

    z_local = jnp.concatenate([z1_local, z2_local], axis=0)
    my_gid = local_row_gids(axis, n_local, num_devices)

    # Positives are device-local in the stacked-view layout; their (simple,
    # dense) gradient flows through plain AD — only the quadratic lse part
    # needs the custom ring VJP.
    pos = jnp.sum(z1_local * z2_local, axis=-1, dtype=jnp.float32) * inv_t

    lse_sum = _make_ring_lse_sum(temperature, axis, num_devices,
                                 interpret)(z_local, my_gid)
    loss_sum = lse_sum - 2.0 * jnp.sum(pos)
    return _psum_acct(loss_sum, axis) / two_n


def make_ring_ntxent(mesh: Mesh, temperature: float = 0.07,
                     axis: str = "data", impl: str = "auto"):
    """Build a jit-able ring NT-Xent over ``mesh`` (see module docstring).

    ``impl``: "fused" folds each visiting block with the Pallas block
    kernels (VMEM-tiled, MXU matmuls, custom ring VJP — the production TPU
    path); "jnp" is the XLA-fused elementwise fold with AD-through-scan
    gradients (the oracle the fused path is tested against; also the faster
    choice under interpret mode); "auto" picks by backend.
    """
    if impl == "auto":
        from ..utils.capability import is_tpu_backend
        impl = "fused" if is_tpu_backend() else "jnp"
    if impl not in ("fused", "jnp"):
        raise ValueError(f"impl must be 'auto', 'fused' or 'jnp', got "
                         f"{impl!r}")
    if impl == "fused":
        body = functools.partial(
            _ring_body_fused,
            temperature=float(temperature),
            axis=axis,
            num_devices=mesh.shape[axis],
            interpret=None,
        )
        # check_vma=False: pallas_call's out_shape carries no varying-mesh-
        # axes annotation, which check_vma=True rejects inside shard_map —
        # same constraint (and comment) as dist_loss.py's pallas bodies.
        return _shard_map_compat(body, mesh=mesh,
                                 in_specs=(P(axis), P(axis)),
                                 out_specs=P(), check_vma=False)
    body = functools.partial(
        _ring_body,
        temperature=float(temperature),
        axis=axis,
        num_devices=mesh.shape[axis],
    )
    return _shard_map_compat(body, mesh=mesh, in_specs=(P(axis), P(axis)),
                             out_specs=P())


def ntxent_loss_ring(
    z1: jax.Array,
    z2: jax.Array,
    mesh: Mesh,
    temperature: float = 0.07,
    axis: str = "data",
    impl: str = "auto",
) -> jax.Array:
    """Global-batch NT-Xent without ever gathering the global batch."""
    return make_ring_ntxent(mesh, temperature, axis, impl)(z1, z2)


def _infonce_ring_body(za_local, zb_local, scale, axis, num_devices):
    """Ring InfoNCE: both cross-modal softmax directions in one ring pass.

    Per exchange step each device folds the visiting za block into its local
    zb rows' statistics (the column direction of s = scale*za@zb.T is the
    row direction of s.T) and the visiting zb block into its local za rows'
    statistics — so one P-1-hop ring of (za, zb) block pairs covers both
    logsumexps. Positives are device-local (s_ii pairs index i of both
    modalities on the same shard); no masking is needed because the diagonal
    is a real cross-modal pair, never a self-similarity.
    """
    n_local, _ = za_local.shape
    n = n_local * num_devices
    pos = jnp.sum(za_local * zb_local, axis=-1,
                  dtype=jnp.float32) * scale             # scale * za_i . zb_i

    perm = [(i, (i + 1) % num_devices) for i in range(num_devices)]

    def fold(rows, blk, m, l):
        # scale applied to the fp32 dot product, so the circulating blocks
        # stay in their original dtype (half the ICI bytes for bf16 inputs).
        s = jnp.dot(rows, blk.T, preferred_element_type=jnp.float32) * scale
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        l = l * jnp.exp(m - m_new) + jnp.sum(_exp0(s - m_new[:, None]), axis=1)
        return m_new, l

    def step(carry, _):
        za_blk, zb_blk, m_a, l_a, m_b, l_b = carry
        m_a, l_a = fold(za_local, zb_blk, m_a, l_a)  # row direction: s rows
        m_b, l_b = fold(zb_local, za_blk, m_b, l_b)  # col direction: s.T rows
        za_blk = _ppermute_acct(za_blk, axis, perm)
        zb_blk = _ppermute_acct(zb_blk, axis, perm)
        return (za_blk, zb_blk, m_a, l_a, m_b, l_b), None

    def stat(v):
        return _pcast_compat(jnp.full((n_local,), v, jnp.float32),
                             (axis,), to="varying")

    # P-1 exchanges; the final visiting block is folded outside the scan.
    init = (za_local, zb_local,
            stat(_NEG_INF), stat(0.0), stat(_NEG_INF), stat(0.0))
    with _comms_scaled(num_devices - 1):
        (za_blk, zb_blk, m_a, l_a, m_b, l_b), _ = jax.lax.scan(
            step, init, None, length=num_devices - 1
        )
    m_a, l_a = fold(za_local, zb_blk, m_a, l_a)
    m_b, l_b = fold(zb_local, za_blk, m_b, l_b)
    lse_a = m_a + _log_l(l_a)
    lse_b = m_b + _log_l(l_b)
    loss_sum = jnp.sum(lse_a - pos) + jnp.sum(lse_b - pos)
    return _psum_acct(loss_sum, axis) / (2 * n)


def _infonce_ring_dual_body(za_local, zb_local, scale, axis, num_devices):
    """Dual ring InfoNCE: ONE matmul and ONE circulating block per hop.

    Observation: in the two-block ring (``_infonce_ring_body``) every
    global similarity tile is produced twice across the mesh — device d
    computes ``S[rows_d, cols_o]`` when o's zb block visits, and device o
    computes the SAME tile transposed (as ``S.T[rows_o, cols_d]``) when
    d's za block visits. Here only the zb blocks circulate, each carrying
    its running column-direction (m, l) statistics: per hop the single
    tile ``za_local @ zb_blk.T`` is folded into the local row statistics
    directly AND into the visiting block's stats transposed. Half the
    matmuls and nearly half the ICI bytes per hop (one (n_local, D) block
    plus two (n_local,) stat vectors instead of two blocks); one extra
    stats-only hop at the end returns each block's completed column
    logsumexp home.
    """
    n_local, _ = za_local.shape
    n = n_local * num_devices
    pos = jnp.sum(za_local * zb_local, axis=-1,
                  dtype=jnp.float32) * scale

    perm = [(i, (i + 1) % num_devices) for i in range(num_devices)]

    def fold_both(zb_blk, m_a, l_a, m_blk, l_blk):
        s = jnp.dot(za_local, zb_blk.T,
                    preferred_element_type=jnp.float32) * scale
        # Row direction: local za rows vs the visiting columns.
        m_new = jnp.maximum(m_a, jnp.max(s, axis=1))
        l_a = l_a * jnp.exp(m_a - m_new) + jnp.sum(
            _exp0(s - m_new[:, None]), axis=1)
        # Column direction: the SAME tile transposed is the visiting zb
        # rows vs this device's za columns.
        st = s.T
        m_bn = jnp.maximum(m_blk, jnp.max(st, axis=1))
        l_blk = l_blk * jnp.exp(m_blk - m_bn) + jnp.sum(
            _exp0(st - m_bn[:, None]), axis=1)
        return m_new, l_a, m_bn, l_blk

    def step(carry, _):
        zb_blk, m_a, l_a, m_blk, l_blk = carry
        m_a, l_a, m_blk, l_blk = fold_both(zb_blk, m_a, l_a, m_blk, l_blk)
        zb_blk, m_blk, l_blk = (
            _ppermute_acct(t, axis, perm) for t in (zb_blk, m_blk, l_blk))
        return (zb_blk, m_a, l_a, m_blk, l_blk), None

    def stat(v):
        return _pcast_compat(jnp.full((n_local,), v, jnp.float32),
                             (axis,), to="varying")

    init = (zb_local, stat(_NEG_INF), stat(0.0), stat(_NEG_INF), stat(0.0))
    with _comms_scaled(num_devices - 1):
        (zb_blk, m_a, l_a, m_blk, l_blk), _ = jax.lax.scan(
            step, init, None, length=num_devices - 1
        )
    m_a, l_a, m_blk, l_blk = fold_both(zb_blk, m_a, l_a, m_blk, l_blk)
    # The block is one hop short of home — send its finished stats there.
    m_blk, l_blk = (_ppermute_acct(t, axis, perm) for t in (m_blk, l_blk))
    lse_a = m_a + _log_l(l_a)
    lse_b = m_blk + _log_l(l_blk)
    loss_sum = jnp.sum(lse_a - pos) + jnp.sum(lse_b - pos)
    return _psum_acct(loss_sum, axis) / (2 * n)


def make_ring_infonce(mesh: Mesh, axis: str = "data", impl: str = "dual"):
    """Build a jit-able ring InfoNCE over ``mesh``: (za, zb, scale) -> loss.

    ``impl="dual"`` (default) circulates one block per hop and folds each
    similarity tile into both softmax directions; ``impl="twoblock"``
    circulates both modality blocks (kept for A/B comparison).
    """
    if impl not in ("dual", "twoblock"):
        raise ValueError(f"unknown ring impl {impl!r}")
    body = functools.partial(
        _infonce_ring_dual_body if impl == "dual" else _infonce_ring_body,
        axis=axis, num_devices=mesh.shape[axis])
    return _shard_map_compat(body, mesh=mesh,
                             in_specs=(P(axis), P(axis), P()),
                             out_specs=P())


def info_nce_loss_ring(
    za: jax.Array,
    zb: jax.Array,
    mesh: Mesh,
    temperature: float = 0.07,
    *,
    scale: jax.Array | float | None = None,
    axis: str = "data",
    impl: str = "dual",
) -> jax.Array:
    """Global-batch InfoNCE without ever gathering the global batch.

    The CLIP-scale path (BASELINE.json configs[4], global batch 32768):
    memory is O(N/P) per chip and all communication is neighbor ICI hops.
    ``impl`` selects the ring body (``"dual"``/``"twoblock"`` — see
    ``make_ring_infonce``).
    """
    return make_ring_infonce(mesh, axis, impl=impl)(
        za, zb, resolve_scale(temperature, scale))
