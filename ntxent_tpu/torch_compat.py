"""Torch autograd bridge: the reference's intended torch UX, actually working.

The reference was a torch CUDA extension whose forward never registered as an
autograd node, so ``loss.backward()`` in its own test could not produce
gradients (/root/reference/tests/test_forward.cpp:29-38; SURVEY.md §3.5).
This module gives torch callers the real thing: ``NTXentLoss`` /
``ntxent_loss_torch`` run the JAX implementation (jnp oracle on CPU, fused
Pallas kernel on TPU) inside a ``torch.autograd.Function`` whose backward
returns the exact dense gradient — so a SimCLR training loop written in
PyTorch can use this loss unchanged. The gradient is computed lazily in
``backward``: a ``torch.no_grad()`` eval loop pays for the forward only.

Conversion is dlpack zero-copy where possible (contiguous CPU tensors).
Torch is an optional dependency: importing this module requires it, but
nothing else in the package does (api.py borrows the converters lazily,
only on torch-typed inputs — by which point torch is already loaded).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import torch

from .ops.ntxent_pallas import ntxent_loss_fused
from .ops.oracle import ntxent_loss

__all__ = ["NTXentLoss", "ntxent_loss_torch", "to_jax", "to_torch"]


def to_jax(t: torch.Tensor, copy: bool = False) -> jax.Array:
    """torch -> jax; dlpack zero-copy when possible, else via numpy
    (routing bf16 — which torch cannot hand to numpy — through float32).

    ``copy=True`` clones the tensor first: zero-copy dlpack aliases the
    caller's storage, and JAX's async dispatch may read it after this call
    returns — a later in-place mutation by the caller would then be observed.
    API boundaries that don't control the caller should pass copy=True.
    """
    if copy:
        t = t.detach().clone()
    try:
        return jnp.from_dlpack(t.detach().contiguous())
    except Exception:
        t = t.detach().cpu()
        if t.dtype == torch.bfloat16:
            return jnp.asarray(t.to(torch.float32).numpy()
                               ).astype(jnp.bfloat16)
        return jnp.asarray(t.numpy())


def to_torch(x: jax.Array) -> torch.Tensor:
    """jax -> torch; dlpack when torch supports the device, else via numpy
    (round-tripping bf16, which numpy-for-torch cannot represent, through
    float32 and casting back so the output dtype matches the input's)."""
    try:
        return torch.from_dlpack(x)
    except Exception:
        if x.dtype == jnp.bfloat16:
            return torch.from_numpy(
                np.asarray(x.astype(jnp.float32))).to(torch.bfloat16)
        return torch.from_numpy(np.asarray(x))


def _loss_fn(z: jax.Array, temperature: float) -> jax.Array:
    # Fused Pallas kernel where it compiles natively; jnp oracle elsewhere
    # (interpret-mode Pallas on CPU would be needlessly slow).
    from .utils.capability import is_tpu_backend

    if is_tpu_backend():
        return ntxent_loss_fused(z, temperature)
    return ntxent_loss(z, temperature)


class _NTXentFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, z: torch.Tensor, temperature: float) -> torch.Tensor:
        # copy=True: ctx.zj must NOT alias z's storage — the gradient is
        # computed lazily in backward, and a zero-copy alias would silently
        # see in-place mutations of z that torch's version counter cannot
        # track across the dlpack boundary.
        zj = to_jax(z.detach().to(dtype=torch.float32, copy=True))
        ctx.zj = zj
        ctx.temperature = temperature
        ctx.in_dtype = z.dtype
        ctx.in_device = z.device
        return to_torch(_loss_fn(zj, temperature)).to(z.device)

    @staticmethod
    def backward(ctx, grad_output: torch.Tensor):
        grad = to_torch(jax.grad(_loss_fn)(ctx.zj, ctx.temperature))
        grad = grad.to(device=ctx.in_device)
        return (grad_output * grad).to(ctx.in_dtype), None


def ntxent_loss_torch(z: torch.Tensor,
                      temperature: float = 0.07) -> torch.Tensor:
    """Canonical NT-Xent for torch callers, differentiable through autograd.

    z: (2N, D) embeddings (stacked views, positives at offset N). The loss
    value and the exact dense gradient are computed by the JAX path; autograd
    sees an ordinary differentiable op.
    """
    if z.ndim != 2 or z.shape[0] % 2 != 0:
        raise ValueError(f"z must be (2N, D) with even 2N, got {tuple(z.shape)}")
    return _NTXentFn.apply(z, float(temperature))


class NTXentLoss(torch.nn.Module):
    """``torch.nn.Module`` wrapper: ``NTXentLoss(T)(z1, z2)`` or ``(z)``."""

    def __init__(self, temperature: float = 0.07):
        super().__init__()
        self.temperature = temperature

    def forward(self, z1: torch.Tensor,
                z2: torch.Tensor | None = None) -> torch.Tensor:
        z = z1 if z2 is None else torch.cat([z1, z2], dim=0)
        return ntxent_loss_torch(z, self.temperature)
