"""import-boundary: the router tier must never (statically) reach JAX.

The ``ntxent-fleet`` router process exists to restart in milliseconds;
its import surface (cli + serving router/ladder/cache/fleet + obs +
faults/crashsim) is deliberately JAX-free, held together by PEP 562
lazy package inits. Until now the only enforcement was a runtime
subprocess tripwire (tests/test_fleet.py) — an end-to-end proof, but
one that names no culprit when it trips and covers only the modules it
happens to import. This checker walks the STATIC import graph from the
boundary roots: every module-level ``import``/``from`` (including
inside class bodies and module-level ``if``/``try`` arms, excluding
function bodies and ``TYPE_CHECKING`` guards — those don't run at
import time) is an edge; reaching any forbidden module (``jax`` or the
eager-jax importers ``flax``/``optax``/...) is an error that names the
exact file:line and the chain from the root that reaches it.

``reachable_modules()`` is public API: the runtime tripwire asserts
its loaded-module set is a subset of this checker's reachable set, so
the static and dynamic proofs can never drift apart (ISSUE 13
satellite).
"""

from __future__ import annotations

import ast
import os

from .framework import (
    Checker,
    LintConfig,
    LintContext,
    SourceFile,
    iter_source_files,
)

__all__ = ["ImportBoundaryChecker", "reachable_modules",
           "module_graph"]


def _module_name(rel: str) -> str | None:
    """'ntxent_tpu/serving/router.py' -> 'ntxent_tpu.serving.router';
    package __init__ files name the package itself; non-package loose
    files ('bench.py') name their stem."""
    if not rel.endswith(".py"):
        return None
    parts = rel[:-3].replace(os.sep, "/").split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    if not parts:
        return None
    return ".".join(parts)


def _import_time_imports(tree: ast.Module):
    """Every import statement that executes at module import time:
    module scope, class bodies, and module-level ``if``/``try``/
    ``with``/``for``/``while``/``match`` arms — NOT function bodies,
    NOT ``if TYPE_CHECKING:`` bodies."""
    out: list[ast.stmt] = []

    def is_type_checking(test: ast.AST) -> bool:
        return (isinstance(test, ast.Name)
                and test.id == "TYPE_CHECKING") or (
            isinstance(test, ast.Attribute)
            and test.attr == "TYPE_CHECKING")

    def walk(stmts) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                out.append(stmt)
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            elif isinstance(stmt, ast.If):
                if not is_type_checking(stmt.test):
                    walk(stmt.body)
                walk(stmt.orelse)
            elif isinstance(stmt, ast.Try):
                walk(stmt.body)
                for handler in stmt.handlers:
                    walk(handler.body)
                walk(stmt.orelse)
                walk(stmt.finalbody)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                walk(stmt.body)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                # Module-level loop bodies DO run at import time.
                walk(stmt.body)
                walk(stmt.orelse)
            elif isinstance(stmt, ast.ClassDef):
                walk(stmt.body)
            elif hasattr(ast, "Match") and isinstance(stmt, ast.Match):
                for case in stmt.cases:
                    walk(case.body)
    walk(tree.body)
    return out


def _resolve_deps(module: str, is_pkg: bool, node: ast.stmt,
                  known: set[str]) -> list[str]:
    """Module names a single import statement pulls in at import time.

    ``import a.b.c`` executes a, a.b AND a.b.c; ``from a.b import c``
    executes a.b, plus a.b.c when c is itself a known module file
    (otherwise it is an attribute and costs nothing extra)."""
    deps: list[str] = []

    def add_with_parents(name: str) -> None:
        parts = name.split(".")
        for i in range(1, len(parts) + 1):
            deps.append(".".join(parts[:i]))

    if isinstance(node, ast.Import):
        for alias in node.names:
            add_with_parents(alias.name)
        return deps
    assert isinstance(node, ast.ImportFrom)
    if node.level == 0:
        base = node.module or ""
    else:
        parts = module.split(".")
        if not is_pkg:
            parts = parts[:-1]
        parts = parts[:len(parts) - (node.level - 1)]
        base = ".".join(parts)
        if node.module:
            base = f"{base}.{node.module}" if base else node.module
    if base:
        add_with_parents(base)
    for alias in node.names:
        candidate = f"{base}.{alias.name}" if base else alias.name
        if candidate in known:
            add_with_parents(candidate)
    return deps


def module_graph(ctx: LintContext):
    """(modules, edges): modules maps name -> SourceFile; edges maps
    name -> list of (dep_name, import_node)."""
    modules: dict[str, SourceFile] = {}
    is_pkg: dict[str, bool] = {}
    for src in ctx.files:
        name = _module_name(src.rel)
        if name is not None:
            modules[name] = src
            is_pkg[name] = src.rel.endswith("__init__.py")
    edges: dict[str, list[tuple[str, ast.stmt]]] = {}
    known = set(modules)
    for name, src in modules.items():
        deps: list[tuple[str, ast.stmt]] = []
        for node in _import_time_imports(src.tree):
            for dep in _resolve_deps(name, is_pkg[name], node, known):
                deps.append((dep, node))
        edges[name] = deps
    return modules, edges


def _reach(roots, modules, edges):
    """BFS over in-repo modules; returns (reached set, parent map)."""
    parent: dict[str, str | None] = {}
    queue = [r for r in roots if r in modules]
    for r in queue:
        parent.setdefault(r, None)
    while queue:
        name = queue.pop(0)
        for dep, _node in edges.get(name, ()):
            if dep in modules and dep not in parent:
                parent[dep] = name
                queue.append(dep)
    return set(parent), parent


def _chain(name: str, parent: dict) -> str:
    out = [name]
    while parent.get(name) is not None:
        name = parent[name]
        out.append(name)
    return " <- ".join(out)


def reachable_modules(
    root: str | None = None,
    roots: tuple[str, ...] | None = None,
    config: LintConfig | None = None,
) -> dict[str, str]:
    """name -> repo-relative path of every module statically reachable
    from the boundary roots (the set the runtime tripwire must stay
    inside). Stdlib-only: safe to call from any test or script."""
    config = config or LintConfig()
    if root is not None:
        config.root = root
    if roots is not None:
        config.boundary_roots = tuple(roots)
    files = []
    for abs_path, rel in iter_source_files(config.root, config.targets):
        try:
            with open(abs_path, encoding="utf-8") as f:
                files.append(SourceFile(abs_path, rel, f.read()))
        except (OSError, SyntaxError, ValueError):
            continue
    ctx = LintContext(config=config, files=files)
    modules, edges = module_graph(ctx)
    reached, _ = _reach(config.boundary_roots, modules, edges)
    return {name: modules[name].rel for name in sorted(reached)}


class ImportBoundaryChecker(Checker):
    rule = "import-boundary"
    describe = ("a module statically reachable from the JAX-free "
                "router tier imports jax (or an eager-jax dependency) "
                "at import time")
    incident = ("PR 8 pass 3: an eager import on the router chain "
                "dragged the multi-second JAX init into the "
                "milliseconds-restart tier")

    def finalize(self, ctx: LintContext):
        cfg = ctx.config
        modules, edges = module_graph(ctx)
        reached, parent = _reach(cfg.boundary_roots, modules, edges)
        forbidden = set(cfg.boundary_forbidden)
        for name in sorted(reached):
            src = modules[name]
            for dep, node in edges[name]:
                if dep.split(".")[0] in forbidden and "." not in dep:
                    yield src.finding(
                        self.rule, node,
                        f"`{dep}` imported at module level in `{name}`,"
                        f" which the JAX-free router tier reaches "
                        f"({_chain(name, parent)}) — defer it into the "
                        f"function that needs it")
