"""Recompile-cause differ: every compile gets a WHY, not just a count.

The serving stack counts compiles (``serving_compiles_total``, the
ragged smoke's "compiles stay flat" assertion) but a bare count cannot
distinguish the four very different stories behind a cache miss: a new
ladder rung (healthy adaptation), a dtype change (a quantized rung
coming up), a weight reload (healthy rollout), or a structure change
(a full re-AOT of the ladder — expensive, and alarming mid-traffic).
This module records each lowering's signature per cache key and, on a
miss, diffs against the nearest prior signature so the ``compile``
event and the ``serving_compiles_by_cause_total{reason=...}`` counter
carry a *cause*.

Pure stdlib by design: the differ is imported by ``serving/engine.py``
(already a JAX module) but also by the audit CLI's event-log analysis,
which must not pay a JAX import to read a JSONL file.

Cause vocabulary (priority order when several fields differ — the most
expensive explanation wins, because it is the one an operator must
react to):

* ``structure`` — the model pytree changed (new architecture): the
  whole ladder recompiles.
* ``dtype`` — same model, different wire dtype (an int8 rung ladder
  coming up next to the f32 one).
* ``weights_reload`` — same structure, new version (a checkpoint
  swap through ``update_variables``-style invalidation).
* ``new_shape`` — a batch-shape (bucket) never compiled before: the
  ladder growing.
* ``first_compile`` — no prior signature to diff against.
* ``recompile`` — an identical signature compiled AGAIN: cache
  thrash, the one cause that is never healthy (eviction racing, or a
  key that fails to capture something the executable depends on).
"""

from __future__ import annotations

import threading

from ..framework import Finding

__all__ = ["RecompileDiffer", "diff_signatures", "churn_findings",
           "CAUSES"]

CAUSES = ("first_compile", "new_shape", "dtype", "weights_reload",
          "structure", "recompile")

# Diff priority: first listed field that differs names the cause.
_FIELD_TO_CAUSE = (
    ("structure", "structure"),
    ("dtype", "dtype"),
    ("version", "weights_reload"),
    ("shape", "new_shape"),
    ("sharding", "structure"),
    ("static", "new_shape"),
)


def diff_signatures(new: dict, prior: dict) -> str:
    """Cause of compiling ``new`` given the nearest ``prior``."""
    for field, cause in _FIELD_TO_CAUSE:
        if new.get(field) != prior.get(field):
            return cause
    return "recompile"


def _distance(a: dict, b: dict) -> int:
    keys = set(a) | set(b)
    return sum(1 for k in keys if a.get(k) != b.get(k))


class RecompileDiffer:
    """Per-store signature history: ``observe(key, signature)`` returns
    the cause of this compile. Thread-safe (the engine compiles outside
    its own lock; two racing misses on one key both get a truthful
    answer — the second one is ``recompile``).

    History is BOUNDED (``max_history``, insertion-order eviction): a
    long-lived worker mints a fresh cache key per rollout (model_hash
    changes), and the engine prunes its executable cache on swaps but
    nothing would prune this — an unbounded dict plus an O(history)
    nearest-prior scan per compile is exactly the slow leak the audit
    exists to catch elsewhere. Recent signatures are the only useful
    diff neighbors anyway.
    """

    def __init__(self, max_history: int = 256):
        self._lock = threading.Lock()
        self._by_key: dict = {}
        self._max_history = max(int(max_history), 1)

    def _insert(self, key, signature: dict) -> None:
        self._by_key.pop(key, None)  # move-to-newest on re-observe
        self._by_key[key] = dict(signature)
        while len(self._by_key) > self._max_history:
            self._by_key.pop(next(iter(self._by_key)))

    def observe(self, key, signature: dict) -> str:
        with self._lock:
            prior = self._by_key.get(key)
            if prior is not None:
                self._insert(key, signature)
                return diff_signatures(signature, prior) \
                    if signature != prior else "recompile"
            if not self._by_key:
                self._insert(key, signature)
                return "first_compile"
            nearest = min(self._by_key.values(),
                          key=lambda s: _distance(signature, s))
            self._insert(key, signature)
            return diff_signatures(signature, nearest)


def churn_findings(events, churn_threshold: int = 3) -> list:
    """Audit a stream of ``compile`` event dicts (an ``--events`` JSONL
    already parsed, or any iterable of dicts): serving compiles (those
    carrying a ``bucket``) must carry a ``cause``, and the same
    signature compiling ``churn_threshold``+ times is cache thrash —
    the exact pathology a bare counter hides. Training compiles (no
    ``bucket`` field) are exempt: one AOT compile per attempt is their
    whole lifecycle."""
    out: list[Finding] = []
    seen: dict[tuple, int] = {}
    for ev in events:
        if ev.get("event") != "compile" or "bucket" not in ev:
            continue
        cause = ev.get("cause")
        if not cause:
            out.append(Finding(
                rule="recompile-cause",
                path="events://compile",
                line=0,
                message=(
                    f"serving compile event (bucket={ev.get('bucket')}, "
                    f"dtype={ev.get('dtype')}) carries no cause — the "
                    f"differ is unwired on this path, so this compile "
                    f"is a bare count again"),
                snippet=f"causeless|{ev.get('bucket')}|{ev.get('dtype')}"))
        if cause == "weights_reload":
            # A reload's version differs even though the event's
            # (bucket, dtype, structure) triple does not carry it —
            # counting reload recompiles here would flag every healthy
            # rollout as cache thrash.
            continue
        sig = (ev.get("bucket"), ev.get("dtype"), ev.get("structure"))
        seen[sig] = seen.get(sig, 0) + 1
    for sig, n in sorted(seen.items()):
        if n >= churn_threshold:
            bucket, dtype, structure = sig
            out.append(Finding(
                rule="recompile-cause",
                path="events://compile",
                line=0,
                message=(
                    f"signature (bucket={bucket}, dtype={dtype}, "
                    f"structure={structure}) compiled {n} times — cache "
                    f"thrash (an executable this key fails to pin, or "
                    f"eviction racing the ladder)"),
                snippet=f"churn|{bucket}|{dtype}|{structure}"))
    return out
