"""The audited entry points: small, real instances of every graph
class the suite must see.

Each target is a *builder* (construction deferred so ``--list`` and
argument parsing never pay a trace) returning the callable + example
args for one audit. The suite covers:

* **census-fwd** — forward losses whose graph census must EXACTLY
  match the shim-declared ring formulas (``dist_loss`` strip, the
  ``ring`` scan path, and the ISSUE 19 chunked ring-overlap schedule,
  all at the ambient device count): any drift means a collective
  bypassed the shims or the byte model diverged.
* **census-grad** — ``jax.grad`` through the same losses: the census
  sees the AD duals (and the old-jax transpose's residual recompute)
  the shims never fire for; the remainder over the declared sites is
  the previously-invisible traffic published as
  ``collective_graph_bytes_total{source="ad"}``.
* **census-gspmd** — a jit-with-shardings program whose jaxpr holds NO
  collective eqns at all: everything the compiled module moves was
  GSPMD-inserted (the TP/FSDP class ROADMAP item 1 left open; detected
  from the optimized HLO text, EQuARX-style).
* **wire-dtype** — the gradient-reduce graphs under
  ``collective_precision("int8"|"bf16")``: every eligible-sized
  collective must carry a compressed payload (verified in the graph,
  not by the shims that did the compressing).
* **donation** — the real (donated) train step over a tiny model:
  broken-promise / returned-view donated leaves (the PR 1 / PR 5
  incident class).

Sizes are deliberately tiny (trace-only, CPU, seconds): the graph
STRUCTURE is what's audited, and it is size-independent.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable

__all__ = ["AuditTarget", "audit_mesh", "default_targets"]


@dataclasses.dataclass(frozen=True)
class AuditTarget:
    """One audited entry point. ``build()`` -> dict with at least
    ``fn`` and ``args``; wire-dtype targets set ``policy``; donation
    targets set ``donate`` (argnums into ``args``)."""

    name: str
    kind: str  # census-fwd | census-grad | census-gspmd | wire-dtype | donation
    build: Callable[[], dict]
    policy: str | None = None
    donate: tuple[int, ...] = ()


def audit_mesh(p: int | None = None):
    """The audit's data mesh over the first ``p`` local devices
    (default: all — 8 under the test/CLI environment, matching the
    pinned formulas)."""
    import jax

    from ...parallel.mesh import create_mesh

    devices = jax.devices()
    p = len(devices) if p is None else min(int(p), len(devices))
    return create_mesh((p,), ("data",), devices=devices[:p])


def _loss_args(mesh, dim: int = 8, n_local: int = 2):
    import jax.numpy as jnp
    import numpy as np

    p = mesh.shape["data"]
    rng = np.random.default_rng(0)
    z1 = jnp.asarray(rng.standard_normal((p * n_local, dim)), jnp.float32)
    z2 = jnp.asarray(rng.standard_normal((p * n_local, dim)), jnp.float32)
    return z1, z2


def _dist_loss(mesh, grad: bool):
    def build():
        import jax

        from ...parallel.dist_loss import make_sharded_ntxent

        loss = make_sharded_ntxent(mesh, temperature=0.1, impl="strip")
        fn = jax.grad(lambda a, b: loss(a, b)) if grad else loss
        return {"fn": fn, "args": _loss_args(mesh)}

    return build


def _dist_loss_chunked(mesh, grad: bool):
    def build():
        import jax

        from ...parallel.dist_loss import make_sharded_ntxent

        loss = make_sharded_ntxent(mesh, temperature=0.1, impl="chunked",
                                   ring_chunks=2)
        fn = jax.grad(lambda a, b: loss(a, b)) if grad else loss
        return {"fn": fn, "args": _loss_args(mesh)}

    return build


def _dist_loss_chunked_int8(mesh):
    """The chunked schedule under the int8 wire policy: every circulating
    embedding block (2 rows x 512 dims = 1024 elems, exactly at the
    quantization floor) must be int8 on the wire; the per-chunk scale
    columns ride f32 legally below the floor."""

    def build():
        from ...parallel import mesh as pm
        from ...parallel.dist_loss import make_sharded_ntxent

        loss = make_sharded_ntxent(mesh, temperature=0.1, impl="chunked",
                                   ring_chunks=2)

        def fn(a, b):
            with pm.collective_precision("int8"):
                return loss(a, b)

        return {"fn": fn, "args": _loss_args(mesh, dim=512)}

    return build


def _ring_loss(mesh, grad: bool):
    def build():
        import jax

        from ...parallel.ring import make_ring_ntxent

        loss = make_ring_ntxent(mesh, temperature=0.1, impl="jnp")
        fn = jax.grad(lambda a, b: loss(a, b)) if grad else loss
        return {"fn": fn, "args": _loss_args(mesh)}

    return build


def _grad_reduce(mesh, policy: str):
    def build():
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ...parallel import mesh as pm

        tree = {"w": jnp.ones((4096,), jnp.float32),
                "b": jnp.ones((4,), jnp.float32)}
        if policy == "int8":
            residual = {"w": jnp.zeros((4096,), jnp.float32),
                        "b": jnp.zeros((4,), jnp.float32)}

            def body(t, r):
                reduced, _ = pm.quantized_grad_reduce(t, r, "data")
                return reduced

            fn = pm.shard_map(body, mesh, in_specs=(P(), P()),
                              out_specs=P(), check_vma=False)
            return {"fn": fn, "args": (tree, residual)}

        def body(t):
            with pm.collective_precision(policy):
                return pm.pmean(t, "data")

        fn = pm.shard_map(body, mesh, in_specs=(P(),), out_specs=P(),
                          check_vma=False)
        return {"fn": fn, "args": (tree,)}

    return build


def _gspmd_matmul(mesh):
    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        w = jax.device_put(jnp.ones((16, 8), jnp.float32),
                           NamedSharding(mesh, P("data", None)))
        x = jax.device_put(jnp.ones((4, 16), jnp.float32),
                           NamedSharding(mesh, P()))
        fn = jax.jit(lambda a, b: a @ b,
                     out_shardings=NamedSharding(mesh, P()))
        return {"fn": fn, "args": (x, w)}

    return build


def _tiny_state():
    """A real TrainState over the smallest honest model (one Dense +
    normalize): the donated-step graphs under audit are the package's
    own factories, only the encoder is shrunk."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from ...training.trainer import TrainerConfig, create_train_state

    class _TinyProj(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            z = nn.Dense(8, dtype=jnp.float32)(
                x.reshape((x.shape[0], -1)))
            return z / (jnp.linalg.norm(z, axis=-1, keepdims=True)
                        + 1e-6)

    cfg = TrainerConfig(batch_size=4, total_steps=10, warmup_steps=2)
    state = create_train_state(_TinyProj(), jax.random.PRNGKey(0),
                               (2, 4, 4, 3), cfg)
    return state


def _serving_rung_int8():
    """The engine's quantized rung forward, exactly as compiled (the
    in-graph dequant over an int8 payload + per-example scales): its
    census must be EMPTY — a serving forward that grew a collective
    would be paying ICI on every request."""

    def build():
        import jax.numpy as jnp

        from ...serving.engine import InferenceEngine

        w = jnp.ones((4, 8), jnp.float32)
        eng = InferenceEngine(lambda v, x: x @ v, w, example_shape=(4,),
                              buckets=(4,), dtype="int8")
        return {"fn": eng._jit_fn, "args": (w,) + eng._dummy_args(4)}

    return build


def _donated_train_step():
    def build():
        import jax.numpy as jnp
        import numpy as np

        from ...training.trainer import make_train_step

        state = _tiny_state()
        step = make_train_step(temperature=0.1, use_fused=False)
        rng = np.random.default_rng(1)
        v1 = jnp.asarray(rng.standard_normal((4, 4, 4, 3)), jnp.float32)
        v2 = jnp.asarray(rng.standard_normal((4, 4, 4, 3)), jnp.float32)
        return {"fn": step, "args": (state, v1, v2)}

    return build


def default_targets(mesh=None) -> list[AuditTarget]:
    """The standing audit suite (tests and ``ntxent-audit`` share it)."""
    if mesh is None:
        mesh = audit_mesh()
    return [
        AuditTarget("dist_loss/fwd", "census-fwd", _dist_loss(mesh, False)),
        AuditTarget("dist_loss/grad", "census-grad", _dist_loss(mesh, True)),
        AuditTarget("dist_loss_chunked/fwd", "census-fwd",
                    _dist_loss_chunked(mesh, False)),
        AuditTarget("dist_loss_chunked/grad", "census-grad",
                    _dist_loss_chunked(mesh, True)),
        AuditTarget("ring/fwd", "census-fwd", _ring_loss(mesh, False)),
        AuditTarget("ring/grad", "census-grad", _ring_loss(mesh, True)),
        AuditTarget("gspmd/matmul", "census-gspmd", _gspmd_matmul(mesh)),
        AuditTarget("serving/rung_int8", "census-fwd",
                    _serving_rung_int8()),
        AuditTarget("grad_reduce/int8", "wire-dtype",
                    _grad_reduce(mesh, "int8"), policy="int8"),
        AuditTarget("dist_loss_chunked/int8", "wire-dtype",
                    _dist_loss_chunked_int8(mesh), policy="int8"),
        AuditTarget("grad_reduce/bf16", "wire-dtype",
                    _grad_reduce(mesh, "bf16"), policy="bf16"),
        AuditTarget("train_step/donated", "donation",
                    _donated_train_step(), donate=(0,)),
    ]
