"""Collective census: every collective in the TRACED program, not just
the shim-declared call sites.

The PR 7 comms accounting records collectives where the *python* call
site runs through a ``parallel/mesh.py`` shim — which is exactly once
per trace, and only for the forward-traced sites. Two whole classes of
real wire traffic are invisible to it:

* **AD duals** — the reduce-scatter behind an ``all_gather``'s
  gradient, the broadcast behind a ``psum``'s, and (on the old-jax
  shard_map transpose) the residual recompute inside the transposed
  shard_map. These are built by JAX's transpose rules from the jaxpr,
  never by re-running the python body, so no shim fires.
* **GSPMD-inserted collectives** — the TP/FSDP parameter gathers and
  gradient reductions the XLA partitioner materializes from sharding
  constraints. They exist only in the compiled module.

This module counts both. ``jaxpr_census`` walks a ``ClosedJaxpr``
(recursing into scan/cond/while/pjit/custom_vjp/shard_map sub-jaxprs,
multiplying scanned bodies by their trip count — the graph-level
counterpart of ``mesh.comms_scaled``) and prices every collective eqn
with the SAME ring-algorithm byte model the shims use, at the operand's
actual on-wire dtype. ``hlo_census`` does the regex half over compiled
StableHLO/HLO text, which is where GSPMD collectives live (EQuARX does
this verification *inside* XLA; the detection half is doable from the
lowered text). ``census_of_callable`` brackets a trace with
``CommsAccounting`` so the census can be cross-checked against the
declared sites — equality for forward float32 graphs, and a published
remainder (``collective_graph_bytes_total{source="ad"|"gspmd"}``) for
everything the shims cannot see.

Everything here is TRACE-ONLY (``jax.make_jaxpr``): no device math, so
the census runs under ``JAX_PLATFORMS=cpu`` and rides tier-1.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import re

logger = logging.getLogger(__name__)

__all__ = [
    "CensusEntry",
    "RING_FACTORS",
    "jaxpr_census",
    "hlo_census",
    "census_totals",
    "census_bytes",
    "census_of_callable",
    "graph_remainder",
    "publish_graph_census",
]

# Ring-algorithm per-device byte factors, keyed by the CANONICAL op
# name (the shims' spelling). Payload B is the eqn's summed operand
# bytes; P the axis group size. MUST stay equal to the lambdas in
# parallel/mesh.py — tests/test_graph_audit.py pins census totals
# against the declared accounting, which is how the two models are
# held together.
RING_FACTORS = {
    "all_gather": lambda b, p: (p - 1) * b,
    "psum": lambda b, p: 2.0 * (p - 1) / p * b,
    "pmax": lambda b, p: 2.0 * (p - 1) / p * b,
    "pmin": lambda b, p: 2.0 * (p - 1) / p * b,
    "psum_scatter": lambda b, p: (p - 1) / p * b,
    "all_to_all": lambda b, p: (p - 1) / p * b,
    "ppermute": lambda b, p: float(b),
}

# jaxpr primitive name -> canonical op name. psum2 is the
# check_rep-rewrite spelling of psum; reduce_scatter is what
# lax.psum_scatter binds. Annotation-only primitives (pbroadcast /
# pvary / pcast / axis_index) move no data and are skipped entirely —
# the shims record pcast at 0 bytes for the same reason, and the
# cross-check compares byte-moving ops only.
_PRIM_TO_OP = {
    "psum": "psum",
    "psum2": "psum",
    "pmax": "pmax",
    "pmin": "pmin",
    "all_gather": "all_gather",
    "reduce_scatter": "psum_scatter",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute",
}

# HLO instruction name -> canonical op. all-reduce covers psum/pmax
# (the reduction computation is opaque at this granularity — the byte
# model is identical anyway).
_HLO_TO_OP = {
    "all-reduce": "psum",
    "all-gather": "all_gather",
    "reduce-scatter": "psum_scatter",
    "all-to-all": "all_to_all",
    "collective-permute": "ppermute",
}


@dataclasses.dataclass(frozen=True)
class CensusEntry:
    """One collective in the graph: op, axis label, payload identity,
    modeled per-device wire bytes, and how many times it EXECUTES
    (trip-count multipliers folded in). ``source`` is "jaxpr" or
    "hlo"; ``unbounded`` marks entries under a ``while`` whose trip
    count the census cannot know (counted once, flagged)."""

    op: str
    axis: str
    shape: tuple[int, ...]
    dtype: str
    calls: int
    bytes_per_call: float
    source: str = "jaxpr"
    unbounded: bool = False

    @property
    def nelems(self) -> int:
        n = 1
        for d in self.shape:
            n *= int(d)
        return n

    @property
    def total_bytes(self) -> float:
        return self.bytes_per_call * self.calls


def _as_jaxpr(x):
    """Jaxpr from Jaxpr-or-ClosedJaxpr (None otherwise)."""
    inner = getattr(x, "jaxpr", x)
    return inner if hasattr(inner, "eqns") else None


def _eqn_axes(params) -> tuple[str, ...]:
    axes = params.get("axes", params.get("axis_name", ()))
    if isinstance(axes, str):
        return (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _eqn_payload(eqn) -> tuple[float, tuple[int, ...], str]:
    """(bytes, shape, dtype name) summed over the eqn's array operands
    — the operand side is the payload in every ring formula (the local
    shard for all_gather, the full pre-scatter buffer for
    reduce-scatter)."""
    total = 0.0
    shape: tuple[int, ...] = ()
    dtypes = set()
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        dt = getattr(aval, "dtype", None)
        if aval is None or dt is None:
            continue
        n = 1
        for d in getattr(aval, "shape", ()):
            n *= int(d)
        total += float(n) * dt.itemsize
        if not shape:
            shape = tuple(int(d) for d in getattr(aval, "shape", ()))
        dtypes.add(dt.name)
    if not dtypes:
        dtype = "none"
    elif len(dtypes) == 1:
        dtype = dtypes.pop()
    else:
        dtype = "mixed"
    return total, shape, dtype


def _group_size(params, axes, axis_sizes) -> int | None:
    """Axis group size for a collective eqn: the explicit
    ``axis_size`` param where the primitive carries one (all_gather /
    reduce_scatter), else the product of the ambient mesh's sizes for
    the named axes (threaded down from the enclosing shard_map)."""
    if params.get("axis_size") is not None:
        return int(params["axis_size"])
    p = 1
    for a in axes:
        if a not in axis_sizes:
            return None
        p *= int(axis_sizes[a])
    return p if axes else None


def jaxpr_census(closed_jaxpr, axis_sizes: dict | None = None,
                 _mult: int = 1, _unbounded: bool = False) -> list:
    """Every collective the traced program executes, with trip counts.

    Recurses into sub-jaxprs wherever eqn params carry them: ``scan``
    bodies multiply by ``length``, ``while`` bodies count once and flag
    ``unbounded``, ``cond`` contributes its most expensive branch (a
    census is a budget, not an average), ``shard_map`` pushes its mesh's
    axis sizes for the psum-family eqns that don't carry an explicit
    ``axis_size``. Entries whose axis size cannot be resolved are
    DROPPED with a debug log — a collective over an unbound axis will
    fail in jax with its own, better error.
    """
    jaxpr = _as_jaxpr(closed_jaxpr)
    if jaxpr is None:
        return []
    axis_sizes = dict(axis_sizes or {})
    out: list[CensusEntry] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        op = _PRIM_TO_OP.get(name)
        if op is not None:
            axes = _eqn_axes(eqn.params)
            p = _group_size(eqn.params, axes, axis_sizes)
            if p is None:
                logger.debug("census: dropped %s over unresolvable axes %r",
                             name, axes)
            else:
                nbytes, shape, dtype = _eqn_payload(eqn)
                out.append(CensusEntry(
                    op=op, axis="|".join(axes) if axes else "",
                    shape=shape, dtype=dtype, calls=_mult,
                    bytes_per_call=RING_FACTORS[op](nbytes, p),
                    unbounded=_unbounded))
            continue
        if name == "scan":
            out.extend(jaxpr_census(
                eqn.params["jaxpr"], axis_sizes,
                _mult * int(eqn.params.get("length", 1)), _unbounded))
            continue
        if name == "while":
            # cond_jaxpr runs per iteration too, but collectives in a
            # while COND would be exotic; both bodies count once,
            # flagged unbounded.
            for key in ("cond_jaxpr", "body_jaxpr"):
                if key in eqn.params:
                    out.extend(jaxpr_census(eqn.params[key], axis_sizes,
                                            _mult, True))
            continue
        if name == "cond":
            branches = [jaxpr_census(b, axis_sizes, _mult, _unbounded)
                        for b in eqn.params.get("branches", ())]
            if branches:
                out.extend(max(
                    branches,
                    key=lambda es: sum(e.total_bytes for e in es)))
            continue
        if name == "shard_map":
            mesh = eqn.params.get("mesh")
            inner_sizes = dict(axis_sizes)
            shape_map = getattr(mesh, "shape", None)
            if shape_map:
                inner_sizes.update(
                    {str(k): int(v) for k, v in dict(shape_map).items()})
            out.extend(jaxpr_census(eqn.params.get("jaxpr"), inner_sizes,
                                    _mult, _unbounded))
            continue
        # Generic: any params value that is (or contains) a jaxpr —
        # pjit, custom_vjp/jvp calls, remat, pallas grids.
        for value in eqn.params.values():
            items = value if isinstance(value, (list, tuple)) else (value,)
            for item in items:
                sub = _as_jaxpr(item)
                if sub is not None:
                    out.extend(jaxpr_census(sub, axis_sizes, _mult,
                                            _unbounded))
    return out


# -- compiled-module census (the GSPMD half) --------------------------------

# `%x = f32[4,8]{1,0} all-reduce(f32[4,8]{1,0} %dot), replica_groups=...`
# The operand types are printed inline; the first operand is the
# payload. `-start` variants are the async halves of the same op
# (`-done` carries no payload and is skipped).
_HLO_INSTR_RE = re.compile(
    r"=\s*(?:\([^)]*\)|[a-z0-9]+\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\("
    r"\s*([a-z0-9]+)\[([0-9,]*)\]")
_REPLICA_ITOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_REPLICA_LIST_RE = re.compile(r"replica_groups=\{\{([0-9, ]+)\}")

_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}
_HLO_DTYPE_NAME = {
    "f32": "float32", "bf16": "bfloat16", "f16": "float16", "s8": "int8",
    "u8": "uint8", "s32": "int32", "u32": "uint32", "f64": "float64",
    "s64": "int64", "pred": "bool",
}


def _hlo_group_size(line: str, default: int) -> int:
    m = _REPLICA_ITOTA_RE.search(line)
    if m:  # [ngroups, group_size]<=[n]
        return max(int(m.group(2)), 1)
    m = _REPLICA_LIST_RE.search(line)
    if m:
        return max(len([x for x in m.group(1).split(",") if x.strip()]), 1)
    # `replica_groups={}` (the all-replicas form) and any future
    # printing the regexes miss fall back to the caller's default —
    # which callers MUST therefore set to the world size, or a P=1
    # fallback prices every unrecognized collective at (P-1)·B = 0 and
    # the gspmd series silently under-reports.
    return default


def hlo_census(hlo_text: str, default_group_size: int = 1) -> list:
    """Collectives in compiled StableHLO/HLO text — where
    GSPMD-inserted ops (TP/FSDP parameter gathers, sharding-propagated
    reductions) become visible.

    Granularity caveat (documented, deliberate): HLO loops print their
    body once, so scanned collectives appear with ``calls=1`` here —
    the jaxpr census is authoritative for trip counts; this census
    exists to SEE what the partitioner inserted, which the jaxpr never
    contains. Payload is the first operand's type at its printed
    shape; group size from ``replica_groups`` (iota or literal form),
    falling back to ``default_group_size``.
    """
    out: list[CensusEntry] = []
    for line in hlo_text.splitlines():
        m = _HLO_INSTR_RE.search(line)
        if m is None:
            continue
        hlo_op, dt, dims = m.groups()
        op = _HLO_TO_OP[hlo_op]
        shape = tuple(int(d) for d in dims.split(",") if d)
        n = 1
        for d in shape:
            n *= d
        nbytes = float(n) * _HLO_DTYPE_BYTES.get(dt, 4)
        p = _hlo_group_size(line, default_group_size)
        out.append(CensusEntry(
            op=op, axis="", shape=shape,
            dtype=_HLO_DTYPE_NAME.get(dt, dt), calls=1,
            bytes_per_call=RING_FACTORS[op](nbytes, p), source="hlo"))
    return out


# -- totals, cross-check, publication ---------------------------------------


def census_totals(entries) -> dict:
    """``{(op, axis): (calls, bytes)}`` — the shape
    ``CommsAccounting.delta`` produces, so the two compare directly."""
    out: dict[tuple[str, str], list] = {}
    for e in entries:
        slot = out.setdefault((e.op, e.axis), [0, 0.0])
        slot[0] += e.calls
        slot[1] += e.total_bytes
    return {k: (int(c), float(b)) for k, (c, b) in out.items()}


def census_bytes(entries) -> float:
    return float(sum(e.total_bytes for e in entries))


def _declared_byte_totals(declared: dict) -> dict:
    """Normalize a CommsAccounting delta for comparison with a census:
    pmean folds into psum (it traces as psum + div — identical wire
    bytes) and zero-byte entries (pcast annotations) are dropped."""
    out: dict[tuple[str, str], list] = {}
    for (op, axis), (calls, nbytes) in declared.items():
        if not nbytes:
            continue
        op = "psum" if op == "pmean" else op
        slot = out.setdefault((op, axis), [0, 0.0])
        slot[0] += calls
        slot[1] += nbytes
    return {k: (int(c), float(b)) for k, (c, b) in out.items()}


def census_of_callable(fn, *args, suppress_accounting: bool = False):
    """(entries, declared_totals) for one callable: trace it once,
    bracketing the process-wide ``CommsAccounting`` so the shim-declared
    traffic of exactly this trace comes back alongside the graph's.

    ``suppress_accounting=True`` zeroes the shims' recording for the
    duration (``comms_scaled(0)``) — the mode for RE-tracing a program
    whose first trace already counted (train_loop's census bracket must
    not double-bump ``collective_bytes_total``); declared totals are
    then empty by construction.
    """
    import contextlib

    import jax

    from ...parallel.mesh import comms_accounting, comms_scaled

    acct = comms_accounting()
    mark = acct.totals()
    scope = comms_scaled(0) if suppress_accounting \
        else contextlib.nullcontext()
    with scope:
        closed = jax.make_jaxpr(fn)(*args)
    declared = {} if suppress_accounting else acct.delta(mark)
    return jaxpr_census(closed), declared


def graph_remainder(entries, declared: dict) -> dict:
    """The census-vs-declared summary published to /metrics.

    ``ad_bytes`` is the graph traffic the shims never saw (AD duals,
    transpose-time residual recompute) — census minus declared, floored
    at zero per (op, axis) so an over-declared site cannot cancel an
    under-declared one. For pure-HLO entries (GSPMD), callers pass them
    as ``entries`` with no declared counterpart and read the same field
    as gspmd bytes.
    """
    cen = census_totals(e for e in entries if e.total_bytes)
    dec = _declared_byte_totals(declared)
    remainder = 0.0
    for key, (_, b) in cen.items():
        remainder += max(b - dec.get(key, (0, 0.0))[1], 0.0)
    return {
        "graph_bytes": round(sum(b for _, b in cen.values()), 3),
        "declared_bytes": round(sum(b for _, b in dec.values()), 3),
        "ad_bytes": round(remainder, 3),
        "graph_calls": int(sum(c for c, _ in cen.values())),
    }


def publish_graph_census(ad_bytes: float = 0.0, gspmd_bytes: float = 0.0,
                         registry=None) -> None:
    """Bump ``collective_graph_bytes_total{source=ad|gspmd}`` — the
    previously-invisible remainder, itemized by who inserted it. The
    unlabeled ``collective_bytes_total`` stays the shim-declared series
    (its docstring and the README row point here for the rest)."""
    if registry is None:
        from ...obs.registry import default_registry

        registry = default_registry()
    for source, nbytes in (("ad", ad_bytes), ("gspmd", gspmd_bytes)):
        if nbytes and math.isfinite(nbytes):
            registry.counter(
                "collective_graph_bytes_total",
                "graph-level collective bytes beyond the shim-declared "
                "sites (AD duals / GSPMD-inserted), per compiled program",
                labels={"source": source}).inc(float(nbytes))
