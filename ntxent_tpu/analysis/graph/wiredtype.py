"""Wire-dtype verifier: the quantization claim checked in the GRAPH.

ISSUE 12's int8/bf16 collectives were verified by the host-side shims'
own accounting — the same code that performs the compression reports
the wire bytes, so a bug that silently left a payload in float32 would
also report it quantized. This verifier closes the loop from the other
side: census the traced gradient-reduce graph and FAIL if any
eligible-sized collective still carries a float32 payload under an
int8/bf16 policy. The graph cannot lie about its own dtypes.

Eligibility mirrors ``parallel/precision.py``: payloads under
``MIN_QUANT_ELEMS`` elements ride in full precision by design (scales
cost more than they save — the per-chunk f32 scale columns of the int8
schedule itself are the canonical example), and ``pmax`` never
quantizes (a max over quantized values loses the extremes it exists to
find). Since ISSUE 19 ``ppermute`` rides the policy too — the chunked
ring schedule circulates embedding blocks hop by hop, and a single f32
hop would leak the whole PR 11 byte cut — so it is eligible here; the
ring losses' small stat vectors and int32 gid blocks stay admitted by
the element floor and the int-dtype allowance. What remains — psum /
all_gather / psum_scatter / all_to_all / ppermute payloads at or above
the floor — must be on the wire at the policy dtype.
"""

from __future__ import annotations

from ..framework import Finding

__all__ = ["ELIGIBLE_OPS", "ALLOWED_WIRE_DTYPES", "wire_dtype_findings"]

# Ops the precision policy compresses (pmax is exempt by policy,
# annotation ops never appear in a census; ppermute joined with the
# ISSUE 19 chunked ring schedule).
ELIGIBLE_OPS = ("psum", "all_gather", "psum_scatter", "all_to_all",
                "ppermute")

# Per policy: the dtypes a payload may legally occupy on the wire.
# float32 stays legal for int8's scale columns — but scales sit far
# below the eligibility floor, which is what actually admits them.
ALLOWED_WIRE_DTYPES = {
    "int8": {"int8", "uint8", "bfloat16", "float16", "int32", "uint32",
             "bool"},
    "bf16": {"int8", "uint8", "bfloat16", "float16", "int32", "uint32",
             "bool"},
}


def wire_dtype_findings(entries, policy: str, target: str,
                        min_elems: int | None = None) -> list:
    """Findings for every census entry that should be compressed but
    is not. ``entries`` is a ``jaxpr_census`` result of a graph traced
    UNDER ``collective_precision(policy)``; ``target`` names the audited
    entry point (it becomes the finding's pseudo-path, so the baseline
    key stays stable across line churn the way lint findings do)."""
    if policy not in ALLOWED_WIRE_DTYPES:
        raise ValueError(f"policy must be one of "
                         f"{sorted(ALLOWED_WIRE_DTYPES)}, got {policy!r}")
    if min_elems is None:
        from ...parallel.precision import MIN_QUANT_ELEMS

        min_elems = MIN_QUANT_ELEMS
    allowed = ALLOWED_WIRE_DTYPES[policy]
    out = []
    for e in entries:
        if e.op not in ELIGIBLE_OPS:
            continue
        if e.nelems < min_elems:
            continue
        if e.dtype in allowed:
            continue
        out.append(Finding(
            rule="wire-dtype",
            path=f"graph://{target}",
            line=0,
            message=(
                f"{e.op} over axis {e.axis or '?'} carries "
                f"{e.dtype}[{','.join(map(str, e.shape))}] "
                f"({e.nelems} elems >= the {min_elems}-elem quantization "
                f"floor) on the wire under collective_precision"
                f"({policy!r}) — an uncompressed leak the host-side "
                f"accounting cannot see"),
            snippet=f"{e.op}|{e.axis}|{e.dtype}|"
                    f"{'x'.join(map(str, e.shape))}"))
    return out
