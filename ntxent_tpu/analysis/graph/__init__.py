"""ntxent-audit: graph-level program audit (ISSUE 14).

Where ``ntxent-lint`` (the sibling package) guards the *source*, this
package audits the *traced program*: the jaxpr and compiled-HLO truth
the source-level rules cannot see. Four analyzers, sharing the lint
framework's finding/baseline machinery and output formats:

* ``collective-census`` (census.py) — every collective in the graph,
  with scan trip counts, priced by the same ring byte model as the
  mesh shims; cross-checked against the shim-declared sites, with the
  AD-dual / GSPMD remainders published to /metrics.
* ``wire-dtype`` (wiredtype.py) — under an int8/bf16 policy, no
  eligible-sized collective may carry f32 on the wire.
* ``donation`` (donation.py) — declared donations that XLA can never
  alias, and donated buffers returned as outputs (the PR 1 / PR 5
  incident class).
* ``recompile-cause`` (recompile.py) — lowering-signature diffs so
  serving ``compile`` events carry a cause; the analyzer flags
  cause-less serving compiles and same-signature churn in an event
  stream.

IMPORT DISCIPLINE: this ``__init__`` stays empty of imports — the
parent ``ntxent_tpu.analysis`` package is on the JAX-free
import-boundary roots, and the census/donation modules here import jax
at module level. Import submodules explicitly
(``from ntxent_tpu.analysis.graph import census``); ``recompile`` is
itself pure stdlib (the serving engine and the event-log analyzer both
use it without paying for the rest).
"""
