"""Donation/aliasing auditor: the PR 1 / PR 5 incident class, caught
statically.

Two shipped incidents were donation bugs the type system cannot see:

* **PR 1**: ``donate_argnums`` on the guarded train step — whose every
  output is a where-select against the PRE-step state — hit an XLA:CPU
  aliasing miscompile (the int32 step came back holding a float's bit
  pattern). The step shipped UNDONATED with a comment; nothing guards
  the next entry point.
* **PR 5**: ``device_get`` on CPU returns zero-copy VIEWS, so a host
  snapshot of state N was silently overwritten when the donated step
  N+1 reused the buffer — caught only by the crash audit's CRC compare.

This auditor checks every registered jitted entry point at the JAXPR
level (trace-only — CPU backends don't implement donation, so the
executable's alias table proves nothing under tier-1):

* **broken-promise**: a donated leaf whose (shape, dtype) class has
  fewer outputs than donated inputs can never be reused by XLA — the
  caller gave the buffer up and got nothing for it; worse, callers now
  ASSUME the input is dead and may skip defensive copies that were
  load-bearing.
* **returned-donated-view**: a donated leaf returned UNCHANGED (the
  output var IS the input var). The caller ends the call holding two
  handles to one buffer it believes it donated; the next donating call
  through either handle invalidates the other — exactly how a
  zero-copy snapshot of "old" state ends up aliasing freshly-donated
  memory (the PR 5 corruption, as a graph shape).

Where the backend DOES establish aliasing at lowering (jax marks
donated StableHLO args with ``tf.aliasing_output``), ``lowered_alias
_report`` reads it back as corroborating evidence; absence is not a
finding on its own (dead donated args are legitimately elided).
"""

from __future__ import annotations

import logging
import re

from ..framework import Finding

logger = logging.getLogger(__name__)

__all__ = ["donation_findings", "lowered_alias_report"]


def _flat_donated_indices(args, donate_argnums) -> tuple[set, int]:
    """(flattened invar indices that are donated, total leaf count) for
    a concrete example-argument tuple — the positional map from
    ``donate_argnums`` (a pytree-argument property) onto jaxpr invars
    (flattened leaves)."""
    import jax

    donated: set[int] = set()
    offset = 0
    donate = set(donate_argnums)
    for i, arg in enumerate(args):
        n = len(jax.tree_util.tree_leaves(arg))
        if i in donate:
            donated.update(range(offset, offset + n))
        offset += n
    return donated, offset


def donation_findings(fn, args, donate_argnums, target: str) -> list:
    """Audit one entry point: trace ``fn`` (the UNDERLYING function or
    a jit wrapper — donation is taken from ``donate_argnums``, not the
    wrapper) on ``args`` and flag broken-promise / returned-view
    donated leaves. ``target`` names the entry point for the finding's
    pseudo-path."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    jaxpr = closed.jaxpr
    donated, n_leaves = _flat_donated_indices(args, donate_argnums)
    if len(jaxpr.invars) != n_leaves:
        # const-hoisting or a signature mismatch broke the positional
        # map; a wrong audit is worse than none.
        logger.warning(
            "donation audit of %s skipped: %d jaxpr invars vs %d "
            "flattened arg leaves", target, len(jaxpr.invars), n_leaves)
        return []
    out: list[Finding] = []
    invars = list(jaxpr.invars)
    outvar_ids = {id(v) for v in jaxpr.outvars}

    def classes(vs):
        by: dict[tuple, int] = {}
        for v in vs:
            aval = getattr(v, "aval", None)
            key = (tuple(getattr(aval, "shape", ())),
                   getattr(getattr(aval, "dtype", None), "name", "?"))
            by[key] = by.get(key, 0) + 1
        return by

    donated_vars = [invars[i] for i in sorted(donated)]
    out_classes = classes(jaxpr.outvars)

    # returned-donated-view: output var IS a donated input var.
    passthrough: list = []
    for i in sorted(donated):
        v = invars[i]
        if id(v) in outvar_ids:
            passthrough.append((i, v))
            aval = getattr(v, "aval", None)
            shape = "x".join(str(d) for d in getattr(aval, "shape", ()))
            dtype = getattr(getattr(aval, "dtype", None), "name", "?")
            out.append(Finding(
                rule="donation",
                path=f"graph://{target}",
                line=0,
                message=(
                    f"donated operand (flat arg {i}, {dtype}[{shape}]) is "
                    f"returned UNCHANGED — the caller now holds two "
                    f"handles to one donated buffer, and any zero-copy "
                    f"snapshot of the 'old' value aliases memory the next "
                    f"donating call overwrites (the PR 5 incident class)"),
                snippet=f"returned-view|arg{i}|{dtype}|{shape}"))

    # broken-promise: per (shape, dtype) class, more donated inputs
    # than outputs that could reuse them. Passthrough donations already
    # reported above are excluded — their buffer IS reused, just
    # dangerously.
    reported_pass = {id(v) for _, v in passthrough}
    promise_vars = [v for v in donated_vars if id(v) not in reported_pass]
    donated_classes = classes(promise_vars)
    for key, n_don in sorted(donated_classes.items()):
        n_out = out_classes.get(key, 0)
        excess = n_don - n_out
        if excess > 0:
            shape, dtype = key
            out.append(Finding(
                rule="donation",
                path=f"graph://{target}",
                line=0,
                message=(
                    f"{excess} donated operand(s) of shape "
                    f"{dtype}[{'x'.join(map(str, shape))}] have no "
                    f"same-shaped output to alias onto — the donation "
                    f"is a broken memory promise (XLA matches donated "
                    f"buffers to identically-sized outputs; none exists)"),
                snippet=f"broken-promise|{dtype}|"
                        f"{'x'.join(map(str, shape))}|x{excess}"))
    return out


_ALIAS_ARG_RE = re.compile(
    r"%arg(\d+):[^)]*?\{[^}]*tf\.aliasing_output\s*=\s*(\d+)")


def lowered_alias_report(stablehlo_text: str) -> dict:
    """``{arg_index: output_index}`` of the input-output aliases jax
    established at lowering (the ``tf.aliasing_output`` annotations) —
    corroborating evidence where the backend supports donation; an
    empty dict on CPU-style backends means nothing by itself."""
    return {int(a): int(o)
            for a, o in _ALIAS_ARG_RE.findall(stablehlo_text)}
