"""``ntxent-audit``: run the graph-level analyzers, gate on NEW
findings.

The trace-level sibling of ``ntxent-lint``: same exit-code contract
(0 = clean or baselined, 1 = new findings, 2 = usage error), same
count-keyed baseline file semantics (``audit_baseline.json``), same
output formats (text / json / github via the shared reporter). The
difference is what gets audited: not source lines but the traced
jaxprs and compiled modules of the registered entry points
(``targets.py``) — so findings carry pseudo-paths
(``graph://dist_loss/grad``, ``events://compile``) whose baseline
identity is the finding's stable snippet, not a source line.

Runs TRACE-ONLY on CPU: the process pins ``JAX_PLATFORMS=cpu`` and an
8-virtual-device host platform BEFORE importing jax (matching the
test environment the golden formulas are pinned under), so the audit
needs no accelerator and rides CI next to the lint gate.

Typical invocations::

    ntxent-audit                       # full suite, text output
    ntxent-audit --analyzers wire-dtype,donation
    ntxent-audit --format json         # per-target census report too
    ntxent-audit --format github       # CI annotations
    ntxent-audit --events run.jsonl    # recompile-cause over a log
    ntxent-audit --write-baseline      # accept current findings
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

BASELINE_NAME = "audit_baseline.json"

ANALYZERS = ("collective-census", "wire-dtype", "donation",
             "recompile-cause")

_DESCRIBE = {
    "collective-census": (
        "graph census of every collective (jaxpr + compiled HLO) "
        "cross-checked against the shim-declared ring formulas",
        "PR 7: accounting scope excluded AD duals and GSPMD-inserted "
        "collectives — /metrics under-reported real wire traffic"),
    "wire-dtype": (
        "no eligible-sized collective may carry f32 on the wire under "
        "an int8/bf16 precision policy (verified in the graph)",
        "PR 11: the quant claim was only measured by the same host "
        "shims that performed the compression"),
    "donation": (
        "declared donations must be aliasable and never returned as "
        "outputs",
        "PR 1: donated guarded step miscompiled; PR 5: zero-copy "
        "snapshot of a donated buffer was overwritten mid-save"),
    "recompile-cause": (
        "serving compile events must carry a cause; identical "
        "signatures must not churn",
        "PR 9: 'compiles stay flat' was a bare count — a miss could "
        "not say WHY it compiled"),
}

__all__ = ["main", "ANALYZERS", "BASELINE_NAME", "run_analyzers"]


def _ensure_cpu_trace_env() -> None:
    """Pin the trace-only environment BEFORE jax import: CPU platform,
    8 virtual devices (the pinned-formula world). Respects explicit
    caller settings — the test suite's conftest already did both."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def _parse_args(argv):
    parser = argparse.ArgumentParser(
        prog="ntxent-audit",
        description="graph-level program audit (ISSUE 14)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detect upward "
                             "from the cwd)")
    parser.add_argument("--analyzers", default=None,
                        help="comma-separated subset of analyzers")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: <root>/"
                             f"{BASELINE_NAME} when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline: every finding is new")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept the current findings and exit 0")
    parser.add_argument("--format", choices=("text", "json", "github"),
                        default="text")
    parser.add_argument("--list-analyzers", action="store_true",
                        help="print the analyzer table and exit")
    parser.add_argument("--events", default=None,
                        help="JSONL event log for the recompile-cause "
                             "analyzer (compile events)")
    parser.add_argument("--churn-threshold", type=int, default=3,
                        help="same-signature compiles that count as "
                             "churn (default 3)")
    parser.add_argument("--fixture-module", default=None,
                        help="python file whose targets(mesh) extends "
                             "the audit suite (gate self-tests)")
    parser.add_argument("--devices", type=int, default=None,
                        help="mesh size for the audit targets "
                             "(default: all local devices)")
    parser.add_argument("--no-publish", action="store_true",
                        help="skip bumping collective_graph_bytes_total "
                             "(metrics publication is for wired-in "
                             "callers; the CLI publishes by default so "
                             "a scrape of the audit process shows the "
                             "remainder)")
    return parser.parse_args(argv)


def _load_fixture_targets(path: str, mesh):
    import importlib.util

    spec = importlib.util.spec_from_file_location("_audit_fixture", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return list(module.targets(mesh))


def _census_analyzer(targets, report):
    """collective-census over the census-* targets; returns findings
    and fills ``report`` with per-target totals + remainders."""
    from ..framework import Finding
    from .census import (
        census_of_callable,
        census_totals,
        graph_remainder,
        hlo_census,
        jaxpr_census,
    )

    findings = []
    ad_bytes = 0.0
    gspmd_bytes = 0.0
    for t in targets:
        if not t.kind.startswith("census-"):
            continue
        built = t.build()
        entries, declared = census_of_callable(built["fn"], *built["args"])
        summary = graph_remainder(entries, declared)
        summary["totals"] = {f"{op}|{ax}": [c, b] for (op, ax), (c, b)
                             in sorted(census_totals(entries).items())}
        report[t.name] = summary
        if t.kind == "census-fwd":
            # Forward graphs: census must equal the declared ring
            # formulas EXACTLY (per op and axis) — this is the pinned
            # cross-check; any drift is a shim bypass or a byte-model
            # fork.
            from .census import _declared_byte_totals

            cen = {k: v for k, v in census_totals(
                e for e in entries if e.total_bytes).items()}
            dec = _declared_byte_totals(declared)
            for key in sorted(set(cen) | set(dec)):
                c = cen.get(key, (0, 0.0))
                d = dec.get(key, (0, 0.0))
                if c[0] != d[0] or abs(c[1] - d[1]) > 1e-6:
                    op, ax = key
                    findings.append(Finding(
                        rule="collective-census",
                        path=f"graph://{t.name}", line=0,
                        message=(
                            f"census/declared mismatch for {op} over "
                            f"{ax or '?'}: graph says {c[0]} calls / "
                            f"{c[1]:.1f} B, shims declared {d[0]} / "
                            f"{d[1]:.1f} B — a collective bypassed the "
                            f"mesh shims or the byte model drifted"),
                        snippet=f"mismatch|{op}|{ax}"))
        elif t.kind == "census-grad":
            if summary["ad_bytes"] <= 0.0:
                findings.append(Finding(
                    rule="collective-census",
                    path=f"graph://{t.name}", line=0,
                    message=(
                        "grad graph census found NO traffic beyond the "
                        "forward-declared sites — the AD duals are "
                        "invisible again (census recursion broke)"),
                    snippet="ad-remainder-zero"))
            ad_bytes += summary["ad_bytes"]
        elif t.kind == "census-gspmd":
            hlo_entries = []
            try:
                import jax

                compiled = built["fn"].lower(*built["args"]).compile()
                # World size as the group-size fallback: an HLO form
                # the replica_groups regexes miss must price at the
                # full group, never P=1 (which zeroes the ring model).
                hlo_entries = hlo_census(
                    compiled.as_text(),
                    default_group_size=jax.device_count())
            except Exception as e:  # noqa: BLE001 — report, don't crash
                findings.append(Finding(
                    rule="collective-census",
                    path=f"graph://{t.name}", line=0,
                    message=f"GSPMD target failed to compile for the "
                            f"HLO census: {type(e).__name__}: {e}",
                    snippet="gspmd-compile-failed"))
                continue
            jax_bytes = summary["graph_bytes"]
            hlo_bytes = sum(e.total_bytes for e in hlo_entries)
            summary["hlo_bytes"] = round(hlo_bytes, 3)
            summary["hlo_ops"] = sorted({e.op for e in hlo_entries})
            if jax_bytes == 0.0 and hlo_bytes <= 0.0:
                findings.append(Finding(
                    rule="collective-census",
                    path=f"graph://{t.name}", line=0,
                    message=(
                        "GSPMD target produced no collectives in either "
                        "census — the detection half (EQuARX-style HLO "
                        "walk) sees nothing"),
                    snippet="gspmd-detection-blind"))
            if jax_bytes == 0.0:
                gspmd_bytes += hlo_bytes
    report["_remainder"] = {"ad_bytes": round(ad_bytes, 3),
                            "gspmd_bytes": round(gspmd_bytes, 3)}
    return findings


def run_analyzers(targets, analyzers, events_path=None,
                  churn_threshold: int = 3, publish: bool = True):
    """(findings, census_report) over the selected analyzers."""
    findings = []
    report: dict = {}
    if "collective-census" in analyzers:
        findings.extend(_census_analyzer(targets, report))
        if publish:
            from .census import publish_graph_census

            rem = report.get("_remainder", {})
            publish_graph_census(rem.get("ad_bytes", 0.0),
                                 rem.get("gspmd_bytes", 0.0))
    if "wire-dtype" in analyzers:
        from .census import census_of_callable
        from .wiredtype import wire_dtype_findings

        for t in targets:
            if t.kind != "wire-dtype":
                continue
            built = t.build()
            entries, _ = census_of_callable(built["fn"], *built["args"])
            findings.extend(
                wire_dtype_findings(entries, t.policy, t.name))
    if "donation" in analyzers:
        from .donation import donation_findings

        for t in targets:
            if t.kind != "donation":
                continue
            built = t.build()
            fn = built["fn"]
            findings.extend(donation_findings(
                getattr(fn, "__wrapped__", fn), built["args"],
                t.donate, t.name))
    if "recompile-cause" in analyzers and events_path:
        from .recompile import churn_findings

        events = []
        with open(events_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        continue
        findings.extend(churn_findings(events, churn_threshold))
    findings.sort(key=lambda f: (f.path, f.rule, f.snippet))
    return findings, report


def main(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    if args.list_analyzers:
        for name in ANALYZERS:
            describe, incident = _DESCRIBE[name]
            print(f"{name}\n    {describe}\n    incident: {incident}")
        return 0
    analyzers = tuple(a.strip() for a in args.analyzers.split(",")) \
        if args.analyzers else ANALYZERS
    unknown = set(analyzers) - set(ANALYZERS)
    if unknown:
        print(f"ntxent-audit: unknown analyzer(s): {sorted(unknown)}",
              file=sys.stderr)
        return 2
    # Misconfiguration must be loud, not a green no-op: an EXPLICITLY
    # selected recompile-cause run with no event log audits nothing,
    # and an --events file nobody reads is the converse typo. (The
    # default full run without --events stays legal — the other three
    # analyzers are the suite there.)
    if args.analyzers and "recompile-cause" in analyzers \
            and not args.events:
        print("ntxent-audit: --analyzers recompile-cause needs "
              "--events FILE (there is nothing else for it to audit)",
              file=sys.stderr)
        return 2
    if args.events and "recompile-cause" not in analyzers:
        print("ntxent-audit: --events given but the recompile-cause "
              "analyzer is not selected — the file would be ignored",
              file=sys.stderr)
        return 2

    _ensure_cpu_trace_env()
    from ..cli import find_root
    from ..framework import (
        compare_with_baseline,
        load_baseline,
        write_baseline,
    )
    from .targets import audit_mesh, default_targets

    root = os.path.abspath(args.root) if args.root else find_root()
    t0 = time.perf_counter()
    needs_targets = set(analyzers) - {"recompile-cause"} \
        or args.fixture_module
    targets = []
    if needs_targets:
        mesh = audit_mesh(args.devices)
        targets = default_targets(mesh)
        if args.fixture_module:
            targets = targets + _load_fixture_targets(
                args.fixture_module, mesh)
    findings, report = run_analyzers(
        targets, analyzers, events_path=args.events,
        churn_threshold=args.churn_threshold,
        publish=not args.no_publish)

    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    if args.write_baseline:
        to_write = list(findings)
        if args.analyzers and os.path.isfile(baseline_path):
            # A scoped run only re-decides the SELECTED analyzers:
            # entries for every other analyzer are carried over
            # untouched, not silently dropped from the rewritten file
            # (same rule as ntxent-lint's scoped --write-baseline).
            from ..framework import Finding

            for (rule, rel, snippet), n in \
                    load_baseline(baseline_path).items():
                if rule not in analyzers:
                    to_write.extend(
                        Finding(rule=rule, path=rel, line=0,
                                message="(carried baseline entry)",
                                snippet=snippet)
                        for _ in range(n))
        write_baseline(baseline_path, to_write)
        print(f"ntxent-audit: baseline with {len(to_write)} finding(s) "
              f"written to {baseline_path}")
        return 0
    baseline = None
    if not args.no_baseline and os.path.isfile(baseline_path):
        baseline = load_baseline(baseline_path)
        if args.analyzers:
            baseline = type(baseline)(
                {k: v for k, v in baseline.items() if k[0] in analyzers})
    if baseline:
        new, accepted, stale = compare_with_baseline(findings, baseline)
    else:
        new, accepted, stale = list(findings), [], []
    elapsed = time.perf_counter() - t0

    if args.format == "json":
        print(json.dumps({
            "new": [vars(f) for f in new],
            "baselined": [vars(f) for f in accepted],
            "stale_baseline": [list(k) for k in stale],
            "census": report,
            "elapsed_s": round(elapsed, 3),
        }, indent=2))
    elif args.format == "github":
        from ..reporting import print_github

        print_github(new, "ntxent-audit", stale=stale)
        print(f"ntxent-audit: {len(new)} new, {len(accepted)} baselined "
              f"({elapsed:.1f}s)", file=sys.stderr)
    else:
        for f in new:
            print(f.format())
        for key in stale:
            print(f"stale baseline entry (fix landed — remove it): "
                  f"{key[0]} @ {key[1]}: {key[2]}", file=sys.stderr)
        rem = report.get("_remainder", {})
        if rem:
            print(f"ntxent-audit: graph remainder beyond declared sites: "
                  f"ad={rem.get('ad_bytes', 0.0):.1f} B, "
                  f"gspmd={rem.get('gspmd_bytes', 0.0):.1f} B "
                  f"(collective_graph_bytes_total{{source=...}})",
                  file=sys.stderr)
        print(f"ntxent-audit: {len(new)} new, {len(accepted)} baselined, "
              f"{len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} ({elapsed:.1f}s)",
              file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
