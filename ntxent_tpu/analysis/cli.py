"""``ntxent-lint``: run the project checkers, gate on NEW findings.

Exit codes: 0 = clean (or every finding baselined/suppressed);
1 = new findings (or parse errors); 2 = usage error.

Typical invocations::

    ntxent-lint                       # repo root auto-detected, text out
    ntxent-lint --rules collective-shim,host-sync
    ntxent-lint --format json         # tooling view (findings + stale)
    ntxent-lint --write-baseline      # accept the current findings
    ntxent-lint --list-rules          # rule table with incidents
    ntxent-lint --boundary-modules    # the static JAX-free module list

The process must stay JAX-free: scripts/lint_gate.sh asserts ``jax``
never enters ``sys.modules`` during a lint run (<20 s, pure ast).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .framework import (
    LintConfig,
    all_rules,
    compare_with_baseline,
    load_baseline,
    run_lint,
    write_baseline,
)
from .imports import reachable_modules

BASELINE_NAME = "lint_baseline.json"

__all__ = ["main", "find_root", "BASELINE_NAME"]


def find_root(start: str | None = None) -> str:
    """Nearest ancestor holding the package dir (repo checkout root)."""
    path = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.isdir(os.path.join(path, "ntxent_tpu")):
            return path
        parent = os.path.dirname(path)
        if parent == path:
            break
        path = parent
    # Installed-package fallback: lint the tree this file lives in.
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _parse_args(argv):
    parser = argparse.ArgumentParser(
        prog="ntxent-lint",
        description="project-native static analysis (ISSUE 13)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detect upward "
                             "from the cwd)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated subset of rules to run")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: <root>/"
                             f"{BASELINE_NAME} when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline: every finding is new")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept the current findings into the "
                             "baseline file and exit 0")
    parser.add_argument("--format", choices=("text", "json", "github"),
                        default="text",
                        help="github = workflow-command annotations "
                             "(::error file=...) via the reporter "
                             "shared with ntxent-audit")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--boundary-modules", action="store_true",
                        help="print the import-boundary checker's "
                             "statically reachable module list and exit")
    return parser.parse_args(argv)


def main(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    if args.list_rules:
        for rule, checker in sorted(all_rules().items()):
            print(f"{rule}\n    {checker.describe}\n"
                  f"    incident: {checker.incident}")
        return 0
    root = os.path.abspath(args.root) if args.root else find_root()
    config = LintConfig(root=root)
    if args.boundary_modules:
        for name, rel in reachable_modules(config=config).items():
            print(f"{name}  {rel}")
        return 0
    rules = tuple(r.strip() for r in args.rules.split(",")) \
        if args.rules else None
    t0 = time.perf_counter()
    try:
        result = run_lint(config, rules=rules)
    except ValueError as e:
        print(f"ntxent-lint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    if args.write_baseline:
        to_write = list(result.findings)
        if rules is not None and os.path.isfile(baseline_path):
            # A scoped run only re-decides the SELECTED rules: entries
            # for every other rule are carried over untouched, not
            # silently dropped from the rewritten file.
            from .framework import Finding

            for (rule, rel, snippet), n in \
                    load_baseline(baseline_path).items():
                if rule not in rules:
                    to_write.extend(
                        Finding(rule=rule, path=rel, line=0,
                                message="(carried baseline entry)",
                                snippet=snippet)
                        for _ in range(n))
        write_baseline(baseline_path, to_write)
        print(f"ntxent-lint: baseline with {len(to_write)} "
              f"finding(s) written to {baseline_path}")
        return 0
    baseline = None
    if not args.no_baseline and os.path.isfile(baseline_path):
        baseline = load_baseline(baseline_path)
        if rules is not None:
            # Scope the comparison to the selected rules: a partial run
            # must not misreport other rules' live entries as stale.
            baseline = type(baseline)(
                {k: v for k, v in baseline.items() if k[0] in rules})
    if baseline:
        new, accepted, stale = compare_with_baseline(result.findings,
                                                     baseline)
    else:
        new, accepted, stale = list(result.findings), [], []
    elapsed = time.perf_counter() - t0

    if args.format == "json":
        print(json.dumps({
            "new": [vars(f) for f in new],
            "baselined": [vars(f) for f in accepted],
            "suppressed": [vars(f) for f in result.suppressed],
            "stale_baseline": [list(k) for k in stale],
            "parse_errors": [list(p) for p in result.parse_errors],
            "elapsed_s": round(elapsed, 3),
        }, indent=2))
    elif args.format == "github":
        from .reporting import print_github

        print_github(new, "ntxent-lint", stale=stale,
                     parse_errors=result.parse_errors)
        print(f"ntxent-lint: {len(new)} new, {len(accepted)} baselined,"
              f" {len(result.suppressed)} suppressed ({elapsed:.2f}s)",
              file=sys.stderr)
    else:
        for f in new:
            print(f.format())
        for path, err in result.parse_errors:
            print(f"{path}: parse error: {err}")
        for key in stale:
            print(f"stale baseline entry (fix landed — remove it): "
                  f"{key[0]} @ {key[1]}: {key[2]}", file=sys.stderr)
        print(f"ntxent-lint: {len(new)} new, {len(accepted)} baselined,"
              f" {len(result.suppressed)} suppressed, {len(stale)} "
              f"stale baseline entr{'y' if len(stale) == 1 else 'ies'} "
              f"({elapsed:.2f}s)", file=sys.stderr)
    return 1 if new or result.parse_errors else 0


if __name__ == "__main__":
    sys.exit(main())
