"""lock-discipline: no blocking work under serving/obs locks; no lock
acquisition inside signal handlers.

Two PR-caught incidents share the rule:

* PR 8: SHA-1 hashing and serial rollback HTTP rode inside
  ``with self._lock:`` on the serving request path — every concurrent
  request convoyed behind one holder's I/O. The checker flags LEXICAL
  blocking calls (file open, subprocess, sleep, thread join, sockets,
  HTTP) inside ``with <lock>:`` bodies under ``serving/`` and ``obs/``.
  The repo's own fix pattern is the one to copy: snapshot under the
  lock, do the slow work outside (obs/events.py dump_flight).
* PR 3: a signal handler that takes a lock the interrupted thread may
  already hold is a self-deadlock — handlers must only flip flags
  (training/preemption.py and obs/profiler.py are the clean exemplars).
  Flagged repo-wide: ``with <lock>:`` or ``.acquire()`` inside any
  function statically registered via ``signal.signal``.

``.wait()`` is deliberately NOT in the blocking set: condition
variables wait UNDER their lock by design (releasing it while parked).
"""

from __future__ import annotations

import ast
import re

from .framework import Checker, LintContext, SourceFile

__all__ = ["LockDisciplineChecker"]

# Module-attribute calls that block: receiver.attr pairs.
_BLOCKING_MODULE_CALLS = {
    ("time", "sleep"),
    ("subprocess", "run"), ("subprocess", "Popen"),
    ("subprocess", "call"), ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("socket", "create_connection"),
    ("os", "fsync"),
    ("shutil", "copy"), ("shutil", "copy2"), ("shutil", "copytree"),
    ("shutil", "rmtree"),
}
_BLOCKING_BARE_CALLS = {"open", "sleep", "urlopen"}
# method names that block regardless of receiver module
_BLOCKING_METHODS = {"urlopen", "recv", "sendall", "connect",
                     "getresponse"}


def _terminal_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


# Word-boundary match, not substring: `self.clock`, `blocked`,
# `blocklist` must NOT read as locks; `_lock`, `label_lock`, `rlock`,
# `lock2` do.
_LOCK_NAME = re.compile(r"(^|_)r?locks?(\d*)($|_)", re.IGNORECASE)


def _is_lock_expr(node: ast.AST) -> bool:
    name = _terminal_name(node)
    return name is not None and _LOCK_NAME.search(name) is not None


def _blocking_reason(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Name):
        if func.id in _BLOCKING_BARE_CALLS:
            return f"`{func.id}()`"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    recv = func.value
    if isinstance(recv, ast.Name) \
            and (recv.id, func.attr) in _BLOCKING_MODULE_CALLS:
        return f"`{recv.id}.{func.attr}()`"
    if func.attr in _BLOCKING_METHODS:
        return f"`.{func.attr}()`"
    if func.attr == "join":
        # thread.join() / thread.join(timeout) blocks; str.join(iter)
        # does not. Receivers that are string literals, and calls whose
        # single argument is a non-numeric expression (the iterable),
        # are the string spelling.
        if isinstance(recv, ast.Constant) and isinstance(recv.value, str):
            return None
        if not call.args:
            return "`.join()`"
        if len(call.args) == 1 and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, (int, float)):
            return "`.join(timeout)`"
        return None
    return None


class _WithLockVisitor(ast.NodeVisitor):
    """Blocking calls lexically inside ``with <lock>:`` bodies.

    Nested defs inside the with-body are skipped: defining a closure
    under a lock does not run it there.
    """

    def __init__(self):
        self.lock_depth = 0
        self.hits: list[tuple[ast.Call, str]] = []

    def visit_With(self, node: ast.With) -> None:
        locked = any(_is_lock_expr(item.context_expr)
                     or (isinstance(item.context_expr, ast.Call)
                         and _is_lock_expr(item.context_expr.func))
                     for item in node.items)
        if locked:
            self.lock_depth += 1
        self.generic_visit(node)
        if locked:
            self.lock_depth -= 1

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node) -> None:
        if self.lock_depth == 0:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        if self.lock_depth > 0:
            reason = _blocking_reason(node)
            if reason is not None:
                self.hits.append((node, reason))
        self.generic_visit(node)


def _signal_handler_names(tree: ast.AST) -> set[str]:
    """Function names statically passed to ``signal.signal(...)``."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or len(node.args) < 2:
            continue
        func = node.func
        is_signal = (
            isinstance(func, ast.Attribute) and func.attr == "signal"
            and isinstance(func.value, ast.Name)
            and "signal" in func.value.id
        ) or (isinstance(func, ast.Name) and func.id == "signal")
        if not is_signal:
            continue
        handler = node.args[1]
        if isinstance(handler, ast.Name):
            out.add(handler.id)
        elif isinstance(handler, ast.Attribute):
            out.add(handler.attr)
    return out


class LockDisciplineChecker(Checker):
    rule = "lock-discipline"
    describe = ("blocking call under a serving/obs lock, or lock "
                "acquisition inside a signal handler")
    incident = ("PR 8: SHA-1 + rollback HTTP under the cache lock "
                "convoyed the request path; PR 3: handler-side lock = "
                "self-deadlock")

    def check(self, src: SourceFile, ctx: LintContext):
        if any(src.rel.startswith(scope)
               for scope in ctx.config.lock_scopes):
            visitor = _WithLockVisitor()
            visitor.visit(src.tree)
            for call, reason in visitor.hits:
                yield src.finding(
                    self.rule, call,
                    f"{reason} inside a `with <lock>:` block — snapshot "
                    f"under the lock, do the blocking work outside it")
        # Signal-handler half: repo-wide.
        handlers = _signal_handler_names(src.tree)
        if not handlers:
            return
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                    or node.name not in handlers:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        if _is_lock_expr(item.context_expr):
                            yield src.finding(
                                self.rule, sub,
                                f"signal handler `{node.name}` takes a "
                                f"lock — the interrupted thread may "
                                f"already hold it (flip a flag instead)")
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "acquire" \
                        and _is_lock_expr(sub.func.value):
                    yield src.finding(
                        self.rule, sub,
                        f"signal handler `{node.name}` acquires a lock "
                        f"— self-deadlock hazard (flip a flag instead)")
