"""collective-shim: every jax.lax collective must ride parallel/mesh.py.

The mesh shims are where comms accounting (PR 7) and the quantized
precision policy (PR 11) live. A raw ``jax.lax.psum`` elsewhere still
COMPUTES correctly — which is exactly why PR 7's hand audit was needed:
it silently under-counts ``collective_bytes_total`` and skips the wire
dtype policy, invalidating every measured byte claim downstream. This
checker turns that audit into a standing guarantee: any spelling of a
collective (``jax.lax.psum(...)``, ``lax.psum(...)``, or a
``from jax.lax import psum`` making bare ``psum(...)`` calls) outside
the shim file is an error.

``axis_index`` is in the set deliberately: besides accounting symmetry,
the shim owns the old-jax custom_vjp-under-shard_map lowering fix —
a raw ``lax.axis_index`` in that position is the seed-era UNIMPLEMENTED
partition-id failure waiting to recur.
"""

from __future__ import annotations

import ast

from .framework import Checker, LintContext, SourceFile

__all__ = ["CollectiveShimChecker", "COLLECTIVES"]

COLLECTIVES = frozenset({
    "psum", "pmean", "all_gather", "ppermute", "psum_scatter",
    "all_to_all", "pmax", "pcast", "axis_index",
})


def _dotted(node: ast.AST) -> str | None:
    """'jax.lax.psum' for an Attribute/Name chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class CollectiveShimChecker(Checker):
    rule = "collective-shim"
    describe = ("jax.lax collective call outside parallel/mesh.py "
                "(bypasses comms accounting + the wire precision policy)")
    incident = ("PR 7: unshimmed all_to_all/pmax under-counted "
                "collective_bytes_total, the measured baseline ROADMAP "
                "item 2 claims wins against")

    def check(self, src: SourceFile, ctx: LintContext):
        if src.rel in ctx.config.shim_paths:
            return
        # Every spelling that can reach a lax collective, aliases
        # included — `import jax.lax as foo; foo.psum(...)` must not
        # defeat the rule:
        #   bare:      from jax.lax import psum [as p]
        #   lax_names: lax / import jax.lax as foo / from jax import
        #              lax as jl  ->  <name>.psum(...)
        #   jax_names: jax / import jax as j  ->  <name>.lax.psum(...)
        bare: set[str] = set()
        lax_names = {"lax"}
        jax_names = {"jax"}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax.lax" and alias.asname:
                        lax_names.add(alias.asname)
                    elif alias.name == "jax" and alias.asname:
                        jax_names.add(alias.asname)
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                if node.module == "jax.lax":
                    for alias in node.names:
                        if alias.name in COLLECTIVES:
                            bare.add(alias.asname or alias.name)
                            yield src.finding(
                                self.rule, node,
                                f"`from jax.lax import {alias.name}` — "
                                f"use the parallel/mesh.py shim instead")
                elif node.module == "jax":
                    for alias in node.names:
                        if alias.name == "lax":
                            lax_names.add(alias.asname or "lax")
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name is None:
                continue
            op = name.rsplit(".", 1)[-1]
            if op not in COLLECTIVES:
                continue
            head = name[:-(len(op) + 1)]
            is_lax = head in lax_names or (
                head.endswith(".lax")
                and head[:-4] in jax_names)
            if is_lax or (name == op and op in bare):
                yield src.finding(
                    self.rule, node,
                    f"raw `{name}` bypasses the mesh shim — call "
                    f"`parallel.mesh.{op}` so comms accounting and the "
                    f"collective_precision policy see it")
