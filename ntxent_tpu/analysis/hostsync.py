"""host-sync: no per-step device→host syncs on step state in hot loops.

The PR 5 incident: ``int(s.step)`` executed EVERY step inside the train
loop forces a device round-trip that serializes the host against the
pipelined device queue — the async-dispatch win the pipeline PR measured
evaporates one scalar at a time. The repaired loop pays one ``int()``
at restore and tracks the step host-side; the per-step metrics reads
ride the lag-1 drain (already-on-host values).

Rule shape (deliberately narrow — this is an incident encoder, not a
general performance lint): inside a HOT function (``train_loop``/
``fit``/step hooks, and the serving dispatch bodies), inside a
``for``/``while`` loop, a sync call — ``int()``, ``float()``,
``.item()``, ``np.array()``/``np.asarray()``, ``jax.device_get()``,
``block_until_ready`` — whose operand involves STEP STATE (an
expression mentioning ``state`` or an attribute named ``.step``).
Values already drained to host (``metrics`` dicts after
``block_until_ready`` of the lag-1 slot) are not step state and stay
legal.
"""

from __future__ import annotations

import ast

from .framework import Checker, LintContext, SourceFile

__all__ = ["HostSyncChecker"]

_SYNC_BUILTINS = {"int", "float"}
_SYNC_NP = {("np", "array"), ("np", "asarray"),
            ("numpy", "array"), ("numpy", "asarray")}
_STATE_NAMES = {"state", "train_state", "new_state"}


def _mentions_step_state(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "step":
            return True
        if isinstance(sub, ast.Name) and sub.id in _STATE_NAMES:
            return True
    return False


def _sync_operand(call: ast.Call) -> ast.AST | None:
    """The operand being synced, when ``call`` is a sync spelling."""
    func = call.func
    if isinstance(func, ast.Name) and func.id in _SYNC_BUILTINS \
            and len(call.args) == 1:
        return call.args[0]
    if isinstance(func, ast.Attribute):
        recv = func.value
        if func.attr == "item" and not call.args:
            return recv
        if func.attr == "block_until_ready":
            # x.block_until_ready() syncs x; jax.block_until_ready(x)
            # syncs its argument.
            if isinstance(recv, ast.Name) and recv.id == "jax":
                return call.args[0] if call.args else None
            return recv
        if func.attr == "device_get" and isinstance(recv, ast.Name) \
                and recv.id == "jax" and call.args:
            return call.args[0]
        if isinstance(recv, ast.Name) \
                and (recv.id, func.attr) in _SYNC_NP and call.args:
            return call.args[0]
    return None


class _HotLoopVisitor(ast.NodeVisitor):
    """Collect sync-on-step-state calls inside loops of one hot body."""

    def __init__(self):
        self.loop_depth = 0
        self.hits: list[ast.Call] = []

    def visit_For(self, node: ast.For) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_AsyncFor = visit_For

    def visit_While(self, node: ast.While) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    def visit_FunctionDef(self, node) -> None:
        # A nested def is a new (cold-until-called) scope: a sync in a
        # callback defined inside the loop is the CALLER's problem at
        # its own call site, not a per-iteration sync here.
        return

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        if self.loop_depth > 0:
            operand = _sync_operand(node)
            if operand is not None and _mentions_step_state(operand):
                self.hits.append(node)
        self.generic_visit(node)


class HostSyncChecker(Checker):
    rule = "host-sync"
    describe = ("device→host sync on step state inside a hot loop "
                "(train_loop / step hooks / serving dispatch)")
    incident = ("PR 5: per-step `int(s.step)` serialized the host "
                "against the async device queue every step")

    def _is_hot(self, name: str, rel: str, cfg) -> bool:
        if name in cfg.hot_functions:
            return True
        if any(name.endswith(sfx) for sfx in cfg.hot_suffixes):
            return True
        if rel.startswith("ntxent_tpu/serving/") \
                and name in cfg.hot_serving:
            return True
        return False

    def check(self, src: SourceFile, ctx: LintContext):
        cfg = ctx.config
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not self._is_hot(node.name, src.rel, cfg):
                continue
            visitor = _HotLoopVisitor()
            for stmt in node.body:
                visitor.visit(stmt)
            for call in visitor.hits:
                yield src.finding(
                    self.rule, call,
                    f"host sync on step state inside `{node.name}`'s "
                    f"loop — hoist it out of the per-step path (track "
                    f"the step host-side; read metrics off the lag-1 "
                    f"drain)")
