"""telemetry-schema: event types, metric names, and label keys are a
closed vocabulary, checked at lint time.

The event stream accepts unknown types at runtime BY DESIGN (it is
extensible), which makes a typo'd ``emit("divergnce", ...)`` silent
forever — no reader ever matches it. Same for metric names the
exposition escaper would mangle, and for label keys: the pow2-
cardinality rule (ISSUE 10) bounds label VALUES, but an unreviewed new
label KEY is how unbounded cardinality sneaks in (per-tenant, per-
request ids). So:

* every ``emit("<literal>", ...)`` type must be in ``EVENT_TYPES`` —
  extracted statically from obs/events.py, so the checker and the
  runtime share one source of truth;
* every registry ``counter``/``gauge``/``histogram`` literal name must
  already be exposition-legal (``prometheus_name`` would pass it
  through unchanged);
* every literal label key must come from the bounded vocabulary in
  ``LintConfig.label_vocab`` — adding a key is a deliberate,
  reviewable config diff, not a drive-by.

Non-literal arguments are skipped (a dynamic event type is a different
design smell, not this rule's).
"""

from __future__ import annotations

import ast
import os
import re

from .framework import Checker, LintContext, SourceFile

__all__ = ["TelemetrySchemaChecker"]

# Mirror of obs.registry's exposition-name legality (kept in literal
# sync by tests/test_lint.py rather than an import: the linter must not
# import the package it lints).
_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
# Receivers that make a .emit(...) call an EVENT-LOG emit (vs. any
# other class's unrelated .emit method).
_EMIT_RECEIVERS = {"events", "obs_events", "_events"}


def _extract_event_types(src: SourceFile) -> tuple[str, ...] | None:
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "EVENT_TYPES"
                for t in node.targets):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                vals = []
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        vals.append(elt.value)
                return tuple(vals)
    return None


def _is_event_emit(func: ast.AST) -> bool:
    if isinstance(func, ast.Name):
        return func.id == "emit"
    if isinstance(func, ast.Attribute) and func.attr == "emit":
        recv = func.value
        name = recv.attr if isinstance(recv, ast.Attribute) \
            else recv.id if isinstance(recv, ast.Name) else ""
        return name in _EMIT_RECEIVERS or "log" in name.lower() \
            or "event" in name.lower()
    return False


def _registry_aliases(tree: ast.AST) -> set[str]:
    """Local names bound to something registry-shaped — the repo's
    dominant spelling is ``r = self.registry; r.counter(...)``, so the
    receiver check must see through one assignment hop. File-level
    over-approximation (an alias in one function matches uses in
    another): acceptable, because only ``counter``/``gauge``/
    ``histogram`` calls on the alias are ever inspected."""
    aliases: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) \
                or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            continue
        value = node.value
        if isinstance(value, ast.Call):
            value = value.func  # MetricsRegistry() / default_registry()
        name = value.attr if isinstance(value, ast.Attribute) \
            else value.id if isinstance(value, ast.Name) else ""
        if "registry" in name.lower():
            aliases.add(node.targets[0].id)
    return aliases


def _is_registry_factory(func: ast.AST, aliases: set[str]) -> bool:
    if not (isinstance(func, ast.Attribute)
            and func.attr in _METRIC_FACTORIES):
        return False
    recv = func.value
    name = recv.attr if isinstance(recv, ast.Attribute) \
        else recv.id if isinstance(recv, ast.Name) else ""
    if "registry" in name.lower() or name in aliases:
        return True
    # default_registry().counter(...)
    if isinstance(recv, ast.Call):
        f = recv.func
        fname = f.attr if isinstance(f, ast.Attribute) \
            else f.id if isinstance(f, ast.Name) else ""
        return "registry" in fname.lower()
    return False


class TelemetrySchemaChecker(Checker):
    rule = "telemetry-schema"
    describe = ("event type outside EVENT_TYPES, exposition-illegal "
                "metric name, or label key outside the bounded "
                "vocabulary")
    incident = ("runtime accepts unknown event types by design, so a "
                "typo'd type/label is silent forever; unreviewed label "
                "keys are the unbounded-cardinality backdoor ISSUE 10 "
                "closed for values")

    _types_cache: tuple[str, ...] | None = None

    def _event_types(self, ctx: LintContext) -> tuple[str, ...]:
        # check() runs once per file; the vocabulary is constant for the
        # whole run — extract it once, not ~110 ast.walks per lint.
        if self._types_cache is not None:
            return self._types_cache
        cfg = ctx.config
        if cfg.event_types is not None:
            types = tuple(cfg.event_types)
        else:
            types = ()
            rel = cfg.events_path.replace(os.sep, "/")
            src = ctx.file_by_rel(rel)
            if src is not None:
                types = _extract_event_types(src) or ()
        self._types_cache = types
        return types

    def check(self, src: SourceFile, ctx: LintContext):
        event_types = self._event_types(ctx)
        vocab = set(ctx.config.label_vocab)
        aliases = _registry_aliases(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if event_types and _is_event_emit(node.func) and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) \
                        and isinstance(first.value, str) \
                        and first.value not in event_types:
                    yield src.finding(
                        self.rule, node,
                        f"event type {first.value!r} is not in "
                        f"EVENT_TYPES — a typo here is silent at "
                        f"runtime (add it to obs/events.py if it is a "
                        f"new core type)")
            if _is_registry_factory(node.func, aliases):
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    name = node.args[0].value
                    if not _NAME_OK.match(name):
                        yield src.finding(
                            self.rule, node,
                            f"metric name {name!r} is not exposition-"
                            f"legal (prometheus_name would rewrite it; "
                            f"name it legally at the source)")
                for kw in node.keywords:
                    if kw.arg != "labels" \
                            or not isinstance(kw.value, ast.Dict):
                        continue
                    for key in kw.value.keys:
                        if isinstance(key, ast.Constant) \
                                and isinstance(key.value, str) \
                                and key.value not in vocab:
                            yield src.finding(
                                self.rule, key,
                                f"label key {key.value!r} is outside "
                                f"the bounded vocabulary — new keys "
                                f"need a LintConfig.label_vocab entry "
                                f"(and a cardinality story, per the "
                                f"pow2 rule)")
