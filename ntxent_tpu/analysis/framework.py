"""Shared visitor framework for the ntxent-lint checkers.

Design (deliberately small):

* one parse per file (``SourceFile`` owns the ``ast`` tree, the raw
  lines, and the per-line suppression map);
* checkers are objects with a ``rule`` name and two hooks —
  ``check(src, ctx)`` per file and ``finalize(ctx)`` once per run (the
  import-boundary checker works on the whole graph, not one file);
* findings carry ``file:line`` plus the stripped source line as their
  BASELINE IDENTITY: line numbers churn on every edit, the offending
  text does not, so a committed baseline survives unrelated diffs;
* suppression is lexical and rule-scoped: ``# ntxent: lint-ok[rule]
  reason`` on the finding's line or the line directly above. A
  suppression naming the WRONG rule does not suppress (tests pin this).

Pure stdlib by contract — the linter must run in processes that never
pay a JAX import (scripts/lint_gate.sh asserts ``jax`` stays out of
``sys.modules``).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from collections import Counter

__all__ = [
    "Finding",
    "LintConfig",
    "LintResult",
    "SourceFile",
    "Checker",
    "compare_with_baseline",
    "load_baseline",
    "run_lint",
    "write_baseline",
    "iter_source_files",
]

# ``# ntxent: lint-ok[rule]`` or ``lint-ok[rule-a,rule-b]``; anything
# after the bracket is the human reason (required by convention,
# unenforced — the review sees the diff either way).
_SUPPRESS_RE = re.compile(r"#\s*ntxent:\s*lint-ok\[([a-zA-Z0-9_,\- ]+)\]")

# Default scan set, relative to the repo root: the package plus the
# loose top-level/scripts python that rides the same invariants.
# tests/ stays out — fixtures there VIOLATE rules on purpose.
_DEFAULT_TARGETS = ("ntxent_tpu", "bench.py", "scripts")
_SKIP_DIRS = {"__pycache__", ".git", "tests", "benchmark_results"}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a precise location.

    ``snippet`` (the stripped source line) is the stable half of the
    baseline key — see module docstring."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    snippet: str = ""

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class LintConfig:
    """Project knobs the checkers read; tests override to point the
    same checkers at fixture trees."""

    root: str = "."
    targets: tuple[str, ...] = _DEFAULT_TARGETS
    # collective-shim: the one file allowed to spell raw lax collectives.
    shim_paths: tuple[str, ...] = ("ntxent_tpu/parallel/mesh.py",)
    # import-boundary: the JAX-free tier's root modules (mirrors the
    # runtime tripwire's import list — test_fleet pins the agreement).
    boundary_roots: tuple[str, ...] = (
        "ntxent_tpu.cli",
        "ntxent_tpu.serving",
        "ntxent_tpu.serving.router",
        "ntxent_tpu.serving.ladder",
        "ntxent_tpu.serving.cache",
        "ntxent_tpu.serving.fleet",
        "ntxent_tpu.obs",
        "ntxent_tpu.resilience",
        "ntxent_tpu.resilience.faults",
        "ntxent_tpu.resilience.crashsim",
        "ntxent_tpu.analysis",
        # ISSUE 15: the retrieval tier (ANN index + /search router
        # surface) rides the router process — backend-init latency or
        # an accelerator hold in a search path would be a regression
        # the tripwire test also pins end-to-end.
        "ntxent_tpu.retrieval",
        # ISSUE 20: the shard worker + journal run as standalone
        # subprocesses (python -m ntxent_tpu.retrieval.shard) — a JAX
        # import there would pay backend init on every supervised
        # restart, exactly when repair latency matters most.
        "ntxent_tpu.retrieval.shard",
        "ntxent_tpu.retrieval.journal",
    )
    boundary_forbidden: tuple[str, ...] = (
        # jax plus everything that eagerly imports it: any of these at
        # module level in a reachable module drags the whole backend in.
        "jax", "jaxlib", "flax", "optax", "chex", "einops",
    )
    # lock-discipline: directories whose locks guard request paths.
    lock_scopes: tuple[str, ...] = ("ntxent_tpu/serving/",
                                    "ntxent_tpu/obs/")
    # host-sync: function names that ARE the hot path.
    hot_functions: tuple[str, ...] = ("train_loop", "eval_loop", "fit")
    hot_suffixes: tuple[str, ...] = ("_hook",)
    # serving dispatch bodies (scoped to serving/ by the checker).
    hot_serving: tuple[str, ...] = ("_run", "_serve_batch", "_take_batch",
                                    "submit", "submit_async", "dispatch",
                                    "_dispatch", "_flush")
    # telemetry-schema: where EVENT_TYPES lives, and the bounded label
    # vocabulary (adding a key here is the deliberate act the
    # pow2-cardinality rule wants a diff line for).
    events_path: str = "ntxent_tpu/obs/events.py"
    event_types: tuple[str, ...] | None = None  # None: parse events_path
    label_vocab: tuple[str, ...] = (
        "op", "axis", "dtype", "stage", "run_id", "reason", "instance",
        "bucket", "slo", "rows", "mode", "worker",
        # ISSUE 14: collective_graph_bytes_total{source=ad|gspmd} — a
        # two-value closed set naming who inserted the traffic.
        "source",
        # ISSUE 15: retrieval_ops_total{kind=build|seal|compact|
        # promote|rollback|stale|rebuild} — the index lifecycle, a
        # closed set (retrieval_latency_ms rides the existing `stage`
        # key).
        "kind",
        # ISSUE 16: tenant_admitted/rejected_total{tenant=...} — open
        # set at the wire (clients pick their own X-Tenant), but the
        # router bounds cardinality itself: at most max_tenants tracked
        # label values, everything past the cap melts into "other".
        "tenant",
        # ISSUE 18: obs_anomalies_total{series=...} — bounded by the
        # history store's own max_series cap (the detector only ever
        # sees series the recorder admitted).
        "series",
        # ISSUE 20: retrieval_shard_up{shard=0..N-1} — one value per
        # configured shard endpoint, bounded by --search-shards (the
        # fan-out mints the gauges at attach, clients can't add more).
        "shard",
    )


class SourceFile:
    """One parsed python file: ast tree + lines + suppression map."""

    def __init__(self, abs_path: str, rel_path: str, text: str):
        self.abs_path = abs_path
        self.rel = rel_path.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel_path)
        self.suppressions: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.suppressions[i] = rules

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def suppressed(self, rule: str, line: int) -> bool:
        for at in (line, line - 1):
            if rule in self.suppressions.get(at, ()):
                return True
        return False

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(rule=rule, path=self.rel, line=line,
                       message=message, snippet=self.snippet(line))


class Checker:
    """Base checker: subclasses set ``rule``/``describe``/``incident``
    and implement ``check`` (per file) and/or ``finalize`` (per run)."""

    rule: str = ""
    describe: str = ""
    incident: str = ""  # the past-PR defect this rule encodes

    def check(self, src: SourceFile, ctx: "LintContext"):
        return ()

    def finalize(self, ctx: "LintContext"):
        return ()


@dataclasses.dataclass
class LintContext:
    config: LintConfig
    files: list[SourceFile]

    def file_by_rel(self, rel: str) -> SourceFile | None:
        for src in self.files:
            if src.rel == rel:
                return src
        return None


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]            # active (unsuppressed)
    suppressed: list[Finding]          # matched a lint-ok
    parse_errors: list[tuple[str, str]]  # (path, error)

    def by_rule(self) -> dict[str, list[Finding]]:
        out: dict[str, list[Finding]] = {}
        for f in self.findings:
            out.setdefault(f.rule, []).append(f)
        return out


def iter_source_files(root: str,
                      targets: tuple[str, ...]) -> list[tuple[str, str]]:
    """(abs_path, rel_path) for every .py under the configured targets."""
    out = []
    for target in targets:
        base = os.path.join(root, target)
        if os.path.isfile(base):
            if base.endswith(".py"):
                out.append((base, target))
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                abs_path = os.path.join(dirpath, name)
                rel = os.path.relpath(abs_path, root)
                out.append((abs_path, rel))
    return out


def _all_checkers() -> list[Checker]:
    # Local imports: checker modules import this one for the base class.
    from .collectives import CollectiveShimChecker
    from .hostsync import HostSyncChecker
    from .imports import ImportBoundaryChecker
    from .locks import LockDisciplineChecker
    from .telemetry import TelemetrySchemaChecker

    return [CollectiveShimChecker(), HostSyncChecker(),
            LockDisciplineChecker(), ImportBoundaryChecker(),
            TelemetrySchemaChecker()]


def all_rules() -> dict[str, Checker]:
    return {c.rule: c for c in _all_checkers()}


def run_lint(config: LintConfig | None = None,
             rules: tuple[str, ...] | None = None) -> LintResult:
    """Parse the configured tree once, run the (selected) checkers,
    partition findings by suppression."""
    config = config or LintConfig()
    files: list[SourceFile] = []
    parse_errors: list[tuple[str, str]] = []
    for abs_path, rel in iter_source_files(config.root, config.targets):
        try:
            with open(abs_path, encoding="utf-8") as f:
                text = f.read()
            files.append(SourceFile(abs_path, rel, text))
        except (OSError, SyntaxError, ValueError) as e:
            # A file the linter cannot parse is itself a finding-grade
            # problem, but not THIS linter's: report and continue.
            parse_errors.append((rel.replace(os.sep, "/"), str(e)))
    ctx = LintContext(config=config, files=files)
    checkers = _all_checkers()
    if rules is not None:
        unknown = set(rules) - {c.rule for c in checkers}
        if unknown:
            raise ValueError(f"unknown lint rule(s): {sorted(unknown)}")
        checkers = [c for c in checkers if c.rule in rules]
    active: list[Finding] = []
    suppressed: list[Finding] = []
    for checker in checkers:
        produced: list[Finding] = []
        for src in files:
            produced.extend(checker.check(src, ctx))
        produced.extend(checker.finalize(ctx))
        for finding in produced:
            src = ctx.file_by_rel(finding.path)
            if src is not None and src.suppressed(finding.rule,
                                                  finding.line):
                suppressed.append(finding)
            else:
                active.append(finding)
    active.sort(key=lambda f: (f.path, f.line, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(active, suppressed, parse_errors)


# ---------------------------------------------------------------------------
# Baseline: committed, count-keyed acceptance of pre-existing findings
# ---------------------------------------------------------------------------
#
# Key = (rule, path, stripped source line); counts make duplicates (the
# same offending line appearing N times in one file) explicit. The gate
# fails only on findings BEYOND the baselined count; baseline entries
# with no surviving finding are STALE and reported so the file shrinks
# as debt is paid instead of fossilizing.


def load_baseline(path: str) -> Counter:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    counts: Counter = Counter()
    for entry in data.get("findings", []):
        key = (entry["rule"], entry["path"], entry.get("snippet", ""))
        counts[key] += int(entry.get("count", 1))
    return counts


def write_baseline(path: str, findings: list[Finding]) -> None:
    # Regenerating must not clobber justifications a maintainer already
    # wrote (the workflow REQUIRES a reason per accepted entry): carry
    # existing reasons over by key, TODO-stamp only genuinely new ones.
    reasons: dict[tuple, str] = {}
    if os.path.isfile(path):
        try:
            with open(path, encoding="utf-8") as f:
                for entry in json.load(f).get("findings", []):
                    key = (entry["rule"], entry["path"],
                           entry.get("snippet", ""))
                    reasons[key] = entry.get("reason", "")
        except (OSError, ValueError, KeyError):
            pass  # unreadable prior baseline: write fresh
    counts = Counter(f.key() for f in findings)
    entries = [
        {"rule": rule, "path": rel, "snippet": snippet, "count": n,
         "reason": reasons.get((rule, rel, snippet))
         or "TODO: justify why this finding is accepted"}
        for (rule, rel, snippet), n in sorted(counts.items())
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": entries}, f, indent=2)
        f.write("\n")


def compare_with_baseline(
    findings: list[Finding], baseline: Counter,
) -> tuple[list[Finding], list[Finding], list[tuple]]:
    """(new, accepted, stale_keys): findings beyond their baselined
    count are new; baseline entries beyond the current count are stale."""
    remaining = Counter(baseline)
    new: list[Finding] = []
    accepted: list[Finding] = []
    for f in findings:
        if remaining.get(f.key(), 0) > 0:
            remaining[f.key()] -= 1
            accepted.append(f)
        else:
            new.append(f)
    stale = sorted(key for key, n in remaining.items() if n > 0)
    return new, accepted, stale
