"""ntxent-lint: project-native static analysis (ISSUE 13).

Three consecutive review passes kept re-finding the same mechanical
defect classes by hand; each checker here encodes one of them as a
machine check so the invariant is a standing guarantee instead of
reviewer vigilance:

* ``collective-shim`` — a ``jax.lax`` collective outside
  ``parallel/mesh.py`` bypasses comms accounting AND the quantized
  precision policy (PR 7 found ``all_to_all``/``pmax`` holes that
  silently under-counted the very baseline ROADMAP item 2 claims wins
  against).
* ``host-sync`` — per-step host syncs on step state (``int(s.step)``
  every step, PR 5) stall the device pipeline from inside the hot loop.
* ``lock-discipline`` — blocking work lexically under a serving/obs
  lock (SHA-1 under the cache lock, serial rollback POSTs on the
  deciding thread, PR 8) and lock acquisition inside signal handlers
  (the PR 3 self-deadlock hazard).
* ``import-boundary`` — the router tier must never import JAX
  (PR 8 pass 3); the static graph here agrees by test with the runtime
  subprocess tripwire so the two cannot drift.
* ``telemetry-schema`` — event types outside ``EVENT_TYPES``, illegal
  exposition metric names, and metric label keys outside the bounded
  vocabulary (the pow2-cardinality rule) are silent typos at runtime.

Everything in this package is pure stdlib (``ast``-based): linting the
repo must never pay a JAX import (``scripts/lint_gate.sh`` asserts it).
Inline suppression: ``# ntxent: lint-ok[rule] reason`` on the finding's
line or the line above. Accepted pre-existing findings live in the
committed ``lint_baseline.json``; ``ntxent-lint`` exits nonzero only on
NEW findings.
"""

from .framework import (
    Finding,
    LintConfig,
    LintResult,
    compare_with_baseline,
    load_baseline,
    run_lint,
    write_baseline,
)
from .imports import reachable_modules

__all__ = [
    "Finding",
    "LintConfig",
    "LintResult",
    "compare_with_baseline",
    "load_baseline",
    "reachable_modules",
    "run_lint",
    "write_baseline",
]
