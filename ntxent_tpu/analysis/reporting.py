"""Shared finding reporters for the analysis CLIs (lint + audit).

``--format github`` renders findings as GitHub workflow commands
(``::error file=...``) so a CI job annotates the diff inline instead
of burying findings in a log. One implementation, both tools — the
formats must not drift (ISSUE 14 satellite).

Pure stdlib, like everything import-reachable from ``ntxent-lint``.
"""

from __future__ import annotations

__all__ = ["github_annotations", "print_github"]


def _escape_property(value: str) -> str:
    """Workflow-command property escaping (the documented set)."""
    return (value.replace("%", "%25").replace("\r", "%0D")
            .replace("\n", "%0A").replace(":", "%3A").replace(",", "%2C"))


def _escape_data(value: str) -> str:
    return value.replace("%", "%25").replace("\r", "%0D") \
        .replace("\n", "%0A")


def github_annotations(findings, tool: str, stale=(), parse_errors=()):
    """Workflow-command lines for NEW findings (+ notices for stale
    baseline entries and parse errors). ``file``/``line`` come from the
    finding; graph findings carry pseudo-paths (``graph://target``) —
    GitHub renders those as plain annotations, which is the right
    degradation (there is no source line for a traced-graph defect)."""
    lines = []
    for f in findings:
        props = f"file={_escape_property(f.path)}"
        if f.line:
            props += f",line={f.line}"
        props += f",title={_escape_property(f'{tool}[{f.rule}]')}"
        lines.append(f"::error {props}::{_escape_data(f.message)}")
    for path, err in parse_errors:
        lines.append(
            f"::error file={_escape_property(path)},"
            f"title={_escape_property(f'{tool}[parse]')}"
            f"::{_escape_data(err)}")
    for key in stale:
        rule, path, snippet = key
        lines.append(
            f"::notice file={_escape_property(path)},"
            f"title={_escape_property(f'{tool}[stale-baseline]')}"
            f"::stale baseline entry (fix landed — remove it): "
            f"{_escape_data(f'{rule}: {snippet}')}")
    return lines


def print_github(findings, tool: str, stale=(), parse_errors=()) -> None:
    for line in github_annotations(findings, tool, stale, parse_errors):
        print(line)
