"""Timing / profiling helpers for the re-hosted benchmark harnesses.

The reference times with per-iteration ``cudaDeviceSynchronize``
(/root/reference/src/benchmark.cpp:30-39) and brackets timed regions with
``torch.cuda.synchronize`` (python/test.py:109-121). The JAX equivalents are
``jax.block_until_ready`` per iteration and ``jax.profiler`` traces in place
of nvprof/-lineinfo builds (SURVEY.md §5.1)."""

from __future__ import annotations

import contextlib
import statistics
import time
from dataclasses import dataclass, asdict

import jax

__all__ = ["BenchmarkResults", "time_fn", "time_fn_chained",
           "compile_chain", "time_chain", "trace", "measured_flops",
           "flops_from_compiled"]


@dataclass
class BenchmarkResults:
    """Mirror of the C++ BenchmarkResults struct (benchmark.cpp:9-14)."""

    mean_ms: float
    std_ms: float
    min_ms: float
    max_ms: float

    def as_dict(self) -> dict:
        return asdict(self)


def time_fn(fn, *args, warmup: int = 10, runs: int = 100) -> BenchmarkResults:
    """Time ``fn(*args)`` with device sync per iteration.

    Mirrors the reference's protocol: warmup iterations then ``runs`` timed
    iterations, each ending in a full device sync (benchmark.cpp:25-39 uses
    warmup=1, runs=100; python/test.py:97-121 uses warmup=10, runs=100).
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    times_ms = []
    for _ in range(runs):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times_ms.append((time.perf_counter() - t0) * 1e3)
    return BenchmarkResults(
        mean_ms=statistics.fmean(times_ms),
        std_ms=statistics.pstdev(times_ms) if len(times_ms) > 1 else 0.0,
        min_ms=min(times_ms),
        max_ms=max(times_ms),
    )


def compile_chain(step_fn, carry, length: int):
    """AOT-compile a jitted ``lax.scan`` chain of ``length`` steps.

    ``step_fn: carry -> (carry, scalar)``. The returned executable maps
    ``carry -> (final_carry, last_scalar)``; its ``cost_analysis()`` gives
    the whole chain's FLOPs (divide by ``length`` for per-step counts).
    """
    from jax import lax

    @jax.jit
    def chain(c0):
        def body(c, _):
            c2, s = step_fn(c)
            return c2, s

        cf, scalars = lax.scan(body, c0, None, length=length)
        return cf, scalars[-1]

    return chain.lower(carry).compile()


def time_chain(chain_exec, carry, *, length: int,
               spans: int = 3) -> tuple[float, object, float]:
    """(best_per_step_ms, final_carry, final_scalar) of a compiled chain.

    One warmup span, then best-of-``spans`` timed spans, each ending in an
    actual device-to-host read of the chain's final scalar. Because the
    steps inside the chain are data-dependent (each consumes the previous
    carry) and the whole span is ONE dispatch, this protocol survives
    remote-relay backends, which distort the naive ones in BOTH
    directions: per-iteration ``block_until_ready`` can return before the
    work physically ran (observed: sub-physical means, >100% MFU), while
    a per-call Python chain pays one relay round-trip per step (observed:
    ~7.7 ms/step of pure RPC at the 4096x128 headline shape). The final
    scalar read guarantees the work happened.
    """
    carry, s = chain_exec(carry)  # warmup span
    final = float(s)
    best_ms = float("inf")
    for _ in range(spans):
        t0 = time.perf_counter()
        carry, s = chain_exec(carry)
        final = float(s)  # D2H: returns only after the work ran
        best_ms = min(best_ms, (time.perf_counter() - t0) * 1e3 / length)
    return best_ms, carry, final


def time_fn_chained(loss_fn, z, *, length: int = 100, spans: int = 3,
                    lr: float = 0.01,
                    with_grad: bool = True) -> tuple[float, float]:
    """Steady-state per-step ms of ``loss_fn`` via an on-device chain.

    Builds a data-dependent SGD-like step from ``loss_fn`` (gradient
    update + renormalize; or a loss-folded perturbation when
    ``with_grad=False``) and measures it with ``compile_chain`` +
    ``time_chain`` (see there for the protocol rationale). Returns
    ``(best_per_step_ms, final_loss)``.
    """
    import jax.numpy as jnp

    if with_grad:
        def step(zz):
            loss, g = jax.value_and_grad(loss_fn)(zz)
            z2 = zz - lr * g
            z2 = z2 / jnp.linalg.norm(z2, axis=-1, keepdims=True)
            return z2.astype(zz.dtype), loss
    else:
        def step(zz):
            loss = loss_fn(zz)
            # forward-only data dependence: fold the loss back into the
            # input so step k+1 cannot start (or be folded away) before
            # step k finishes.
            z2 = zz * (1.0 + 1e-6 * loss).astype(zz.dtype)
            return z2, loss

    chain_exec = compile_chain(step, z, length)
    best_ms, _, final = time_chain(chain_exec, z, length=length, spans=spans)
    return best_ms, final


def flops_from_compiled(compiled) -> float | None:
    """FLOP count off an already-compiled executable's cost analysis, or
    None when the backend provides no analysis."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):  # some backends wrap it in a list
            analysis = analysis[0]
        return float(analysis["flops"])
    except Exception:  # no analysis on this backend/version
        return None


def measured_flops(fn, *args) -> float | None:
    """FLOPs of one ``fn(*args)`` call from XLA's compiled cost analysis.

    The honest input to MFU accounting (trainer.estimate_mfu): analytic
    per-model FLOP formulas drift as architectures change; the compiler's
    own count does not. Returns None when the backend provides no analysis.
    """
    try:
        compiled = jax.jit(fn).lower(*args).compile()
    except Exception:  # not jittable / backend refused AOT
        return None
    return flops_from_compiled(compiled)


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/ntxent_tpu_trace"):
    """jax.profiler trace context (TensorBoard/XProf viewable)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
