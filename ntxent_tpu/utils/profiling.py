"""Timing / profiling helpers for the re-hosted benchmark harnesses.

The reference times with per-iteration ``cudaDeviceSynchronize``
(/root/reference/src/benchmark.cpp:30-39) and brackets timed regions with
``torch.cuda.synchronize`` (python/test.py:109-121). The JAX equivalents are
``jax.block_until_ready`` per iteration and ``jax.profiler`` traces in place
of nvprof/-lineinfo builds (SURVEY.md §5.1)."""

from __future__ import annotations

import contextlib
import statistics
import time
from dataclasses import dataclass, asdict

import jax

__all__ = ["BenchmarkResults", "time_fn", "time_fn_chained",
           "compile_chain", "time_chain", "trace", "measured_flops",
           "flops_from_compiled", "chain_flops_per_step"]


@dataclass
class BenchmarkResults:
    """Mirror of the C++ BenchmarkResults struct (benchmark.cpp:9-14)."""

    mean_ms: float
    std_ms: float
    min_ms: float
    max_ms: float

    def as_dict(self) -> dict:
        return asdict(self)


def time_fn(fn, *args, warmup: int = 10, runs: int = 100) -> BenchmarkResults:
    """Time ``fn(*args)`` with device sync per iteration.

    Mirrors the reference's protocol: warmup iterations then ``runs`` timed
    iterations, each ending in a full device sync (benchmark.cpp:25-39 uses
    warmup=1, runs=100; python/test.py:97-121 uses warmup=10, runs=100).
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    times_ms = []
    for _ in range(runs):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times_ms.append((time.perf_counter() - t0) * 1e3)
    return BenchmarkResults(
        mean_ms=statistics.fmean(times_ms),
        std_ms=statistics.pstdev(times_ms) if len(times_ms) > 1 else 0.0,
        min_ms=min(times_ms),
        max_ms=max(times_ms),
    )


def compile_chain(step_fn, carry, length: int, *consts):
    """AOT-compile a jitted ``lax.scan`` chain of ``length`` steps.

    ``step_fn: (carry, *consts) -> (carry, scalar)``. The returned
    executable maps ``(carry, *consts) -> (final_carry, last_scalar)``;
    for per-step FLOP counts off its cost analysis use
    ``chain_flops_per_step`` (backends disagree on whether a scan body is
    counted once or x trip count).

    ``consts`` (e.g. a fixed benchmark batch) MUST ride as arguments, not
    closures: a closed-over device array becomes an HLO literal, and at
    trainer-batch sizes the serialized module then carries hundreds of MB
    of constant payload — big enough to blow a remote-compile relay's
    request limit (observed: HTTP 413 at RN50 batch 256, ~308 MB of
    embedded views).
    """
    from jax import lax

    @jax.jit
    def chain(c0, *cs):
        def body(c, _):
            c2, s = step_fn(c, *cs)
            return c2, s

        cf, scalars = lax.scan(body, c0, None, length=length)
        return cf, scalars[-1]

    return chain.lower(carry, *consts).compile()


def time_chain(chain_exec, carry, *consts, length: int,
               spans: int = 3) -> tuple[float, object, float]:
    """(best_per_step_ms, final_carry, final_scalar) of a compiled chain.

    One warmup span, then best-of-``spans`` timed spans, each ending in an
    actual device-to-host read of the chain's final scalar. Because the
    steps inside the chain are data-dependent (each consumes the previous
    carry) and the whole span is ONE dispatch, this protocol survives
    remote-relay backends, which distort the naive ones in BOTH
    directions: per-iteration ``block_until_ready`` can return before the
    work physically ran (observed: sub-physical means, >100% MFU), while
    a per-call Python chain pays one relay round-trip per step (observed:
    ~7.7 ms/step of pure RPC at the 4096x128 headline shape). The final
    scalar read guarantees the work happened.
    """
    carry, s = chain_exec(carry, *consts)  # warmup span
    final = float(s)
    best_ms = float("inf")
    for _ in range(spans):
        t0 = time.perf_counter()
        carry, s = chain_exec(carry, *consts)
        final = float(s)  # D2H: returns only after the work ran
        best_ms = min(best_ms, (time.perf_counter() - t0) * 1e3 / length)
    return best_ms, carry, final


def time_fn_chained(loss_fn, z, *, length: int = 100, spans: int = 3,
                    lr: float = 0.01,
                    with_grad: bool = True,
                    min_span_ms: float | None | str = "auto",
                    ) -> tuple[float, float]:
    """Steady-state per-step ms of ``loss_fn`` via an on-device chain.

    Builds a data-dependent SGD-like step from ``loss_fn`` (gradient
    update + renormalize; or a loss-folded perturbation when
    ``with_grad=False``) and measures it with ``compile_chain`` +
    ``time_chain`` (see there for the protocol rationale). Returns
    ``(best_per_step_ms, final_loss)``.

    ``min_span_ms``: if the whole measured span (length x per-step) comes
    in under this, the chain is re-compiled longer so one span amortizes
    the tunnel's FIXED dispatch+transfer overhead (~64 ms measured at the
    headline shape — on a 1.7 ms step, a 20-step span mis-attributes
    ~3 ms/step of pure RPC; at sub-millisecond steps a short-chain vote
    is effectively random). The adjustment iterates (the first estimate
    is itself overhead-inflated, so one pass undershoots), capped at
    4000 steps / 3 recompiles. The default ``"auto"`` resolves to 400 ms
    on accelerator backends — the protocol-level fix, not a per-caller
    opt-in — and to None (off) on CPU, where there is no relay.
    """
    import jax.numpy as jnp

    if with_grad:
        def step(zz):
            loss, g = jax.value_and_grad(loss_fn)(zz)
            z2 = zz - lr * g
            z2 = z2 / jnp.linalg.norm(z2, axis=-1, keepdims=True)
            return z2.astype(zz.dtype), loss
    else:
        def step(zz):
            loss = loss_fn(zz)
            # forward-only data dependence: fold the loss back into the
            # input so step k+1 cannot start (or be folded away) before
            # step k finishes.
            z2 = zz * (1.0 + 1e-6 * loss).astype(zz.dtype)
            return z2, loss

    if min_span_ms == "auto":
        min_span_ms = (400.0 if jax.default_backend() in ("tpu", "axon")
                       else None)
    chain_exec = compile_chain(step, z, length)
    best_ms, _, final = time_chain(chain_exec, z, length=length, spans=spans)
    for _ in range(3):
        if (min_span_ms is None or length >= 4000
                or best_ms * length >= min_span_ms):
            break
        longer = min(4000, int(min_span_ms / max(best_ms, 1e-6)) + 1)
        if longer <= length:
            break
        length = longer
        chain_exec = compile_chain(step, z, length)
        best_ms, _, final = time_chain(chain_exec, z, length=length,
                                       spans=spans)
    return best_ms, final


def flops_from_compiled(compiled) -> float | None:
    """FLOP count off an already-compiled executable's cost analysis, or
    None when the backend provides no analysis."""
    return _cost_analysis_value(compiled, "flops")


_SCAN_FLOP_SEMANTICS: dict[str, str] = {}


def _scan_body_flop_semantics() -> str:
    """How this backend's cost analysis accounts a scan body: "once" or
    "scaled" (multiplied by trip count).

    Probed empirically with a throwaway 8-wide chain whose analytic FLOP
    count is known — the compile is trivial and the answer is memoized
    per backend. Observed: both XLA:CPU and the TPU backend report the
    body ONCE (a 30-step RN50 chain's "flops" equals the single step's
    own count), so dividing the chain total by the trip count understates
    MFU by exactly the chain length. Unknown/failed probe returns
    "scaled": the conservative reading (MFU understated, never inflated).
    """
    backend = jax.default_backend()
    cached = _SCAN_FLOP_SEMANTICS.get(backend)
    if cached is not None:
        return cached
    import jax.numpy as jnp

    n, length = 8, 10
    single = 2.0 * n * n * n  # one n x n matmul

    def probe_step(c):
        c2 = c @ c
        return c2, c2[0, 0]

    try:
        exec_ = compile_chain(probe_step, jnp.eye(n, dtype=jnp.float32),
                              length)
        total = flops_from_compiled(exec_)
    except Exception:  # AOT refused (e.g. flaky tunnel)
        total = None
    if not total or total <= 0:
        # Do NOT memoize a failed probe: a transient tunnel hiccup here
        # must not pin the conservative reading (and its chain-length-x
        # MFU understatement) for the whole process. Retry next call.
        import logging

        logging.getLogger(__name__).warning(
            "scan-body FLOP-semantics probe failed on backend %r; "
            "assuming trip-count scaling for THIS call (MFU may read "
            "low by the caller's chain length); will re-probe on the "
            "next call", backend)
        return "scaled"
    verdict = ("once"
               if abs(total - single) < abs(total - single * length)
               else "scaled")
    _SCAN_FLOP_SEMANTICS[backend] = verdict
    return verdict


def _cost_analysis_value(compiled, key: str) -> float | None:
    """One scalar off a compiled executable's cost analysis, or None when
    the backend provides no analysis (or not this key)."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):  # some backends wrap it in a list
            analysis = analysis[0]
        return float(analysis[key])
    except Exception:  # no analysis on this backend/version
        return None


def bytes_accessed_from_compiled(compiled) -> float | None:
    """HBM traffic ("bytes accessed") off a compiled executable's cost
    analysis — the denominator of roofline arithmetic-intensity
    accounting."""
    return _cost_analysis_value(compiled, "bytes accessed")


def chain_bytes_per_step(chain_exec, length: int) -> float | None:
    """Per-step bytes accessed from a compiled scan chain's cost
    analysis — same scan-body trip-count caveat (and probe) as
    chain_flops_per_step."""
    total = bytes_accessed_from_compiled(chain_exec)
    if not total:
        return None
    if _scan_body_flop_semantics() == "once":
        return total
    return total / length


def chain_flops_per_step(chain_exec, length: int) -> float | None:
    """Per-step FLOPs from a compiled scan chain's cost analysis.

    XLA's HLO cost analysis does NOT reliably scale a while/scan body by
    its trip count (see _scan_body_flop_semantics) — reading the chain
    total at face value and dividing by ``length`` understated MFU 30x
    on TPU. The probe decides which interpretation this backend needs.
    """
    total = flops_from_compiled(chain_exec)
    if not total:
        return None
    if _scan_body_flop_semantics() == "once":
        return total
    return total / length


def measured_flops(fn, *args) -> float | None:
    """FLOPs of one ``fn(*args)`` call from XLA's compiled cost analysis.

    The honest input to MFU accounting (trainer.estimate_mfu): analytic
    per-model FLOP formulas drift as architectures change; the compiler's
    own count does not. Returns None when the backend provides no analysis.
    """
    try:
        compiled = jax.jit(fn).lower(*args).compile()
    except Exception:  # not jittable / backend refused AOT
        return None
    return flops_from_compiled(compiled)


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/ntxent_tpu_trace"):
    """jax.profiler trace context (TensorBoard/XProf viewable)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
