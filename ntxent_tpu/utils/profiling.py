"""Timing / profiling helpers for the re-hosted benchmark harnesses.

The reference times with per-iteration ``cudaDeviceSynchronize``
(/root/reference/src/benchmark.cpp:30-39) and brackets timed regions with
``torch.cuda.synchronize`` (python/test.py:109-121). The JAX equivalents are
``jax.block_until_ready`` per iteration and ``jax.profiler`` traces in place
of nvprof/-lineinfo builds (SURVEY.md §5.1)."""

from __future__ import annotations

import contextlib
import statistics
import time
from dataclasses import dataclass, asdict

import jax

__all__ = ["BenchmarkResults", "time_fn", "trace", "measured_flops"]


@dataclass
class BenchmarkResults:
    """Mirror of the C++ BenchmarkResults struct (benchmark.cpp:9-14)."""

    mean_ms: float
    std_ms: float
    min_ms: float
    max_ms: float

    def as_dict(self) -> dict:
        return asdict(self)


def time_fn(fn, *args, warmup: int = 10, runs: int = 100) -> BenchmarkResults:
    """Time ``fn(*args)`` with device sync per iteration.

    Mirrors the reference's protocol: warmup iterations then ``runs`` timed
    iterations, each ending in a full device sync (benchmark.cpp:25-39 uses
    warmup=1, runs=100; python/test.py:97-121 uses warmup=10, runs=100).
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    times_ms = []
    for _ in range(runs):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times_ms.append((time.perf_counter() - t0) * 1e3)
    return BenchmarkResults(
        mean_ms=statistics.fmean(times_ms),
        std_ms=statistics.pstdev(times_ms) if len(times_ms) > 1 else 0.0,
        min_ms=min(times_ms),
        max_ms=max(times_ms),
    )


def measured_flops(fn, *args) -> float | None:
    """FLOPs of one ``fn(*args)`` call from XLA's compiled cost analysis.

    The honest input to MFU accounting (trainer.estimate_mfu): analytic
    per-model FLOP formulas drift as architectures change; the compiler's
    own count does not. Returns None when the backend provides no analysis.
    """
    try:
        compiled = jax.jit(fn).lower(*args).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):  # some backends wrap it in a list
            analysis = analysis[0]
        return float(analysis["flops"])
    except Exception:  # no analysis on this backend/version
        return None


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/ntxent_tpu_trace"):
    """jax.profiler trace context (TensorBoard/XProf viewable)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
