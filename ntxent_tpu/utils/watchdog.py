"""Stall detection for long-running training loops.

The reference has no failure detection at all (SURVEY.md §5.3 — its error
handling is throw-on-CUDA-error and exit(1) in harnesses). On real
multi-chip runs the common failure mode is not an exception but SILENCE: a
wedged collective, a hung host-device transfer, or a stuck input pipeline
leaves the process alive and the logs frozen. ``StallWatchdog`` turns that
silence into a diagnosis and an action:

* the training loop calls ``beat()`` every step (``train_loop`` does this
  automatically when given a watchdog);
* a daemon thread checks the time since the last beat; past ``timeout_s``
  it dumps EVERY thread's Python stack via ``faulthandler`` (to stderr or
  ``dump_path``) — the "where is it stuck" evidence — and invokes
  ``on_stall`` through a ONE-SHOT latch (e.g. a preemption-style
  force-checkpoint, a metrics alarm, or ``os.kill(os.getpid(), SIGTERM)``
  to trigger the ``PreemptionGuard`` save-and-exit path). The latch stays
  closed until an explicit ``reset()``: beats resuming after a dump re-arm
  DETECTION (``stalled`` clears, later stalls still dump), but never the
  callback — a policy like "checkpoint and restart" firing twice in one
  incident would race its own recovery. ``resilience.Supervisor`` resets
  the latch at each attempt boundary.

The watchdog never kills anything by itself: policy lives in ``on_stall``
— escalation to an acting layer is exactly what ``resilience.Supervisor``
wires up (its ``on_stall`` stops the attempt at a step boundary via
``PreemptionGuard`` and restarts from the last valid checkpoint).
"""

from __future__ import annotations

import faulthandler
import logging
import threading
import time
from typing import Callable

logger = logging.getLogger(__name__)

__all__ = ["StallWatchdog"]


class StallWatchdog:
    """Background thread that flags a loop which stopped making progress.

    Usage::

        with StallWatchdog(timeout_s=600, on_stall=save_and_die) as dog:
            for batch in data:
                state, metrics = train_step(state, *batch)
                dog.beat()

    or pass it to ``train_loop(..., watchdog=dog)`` which beats per step.
    """

    def __init__(
        self,
        timeout_s: float = 600.0,
        on_stall: Callable[[float], None] | None = None,
        poll_s: float | None = None,
        dump_path: str | None = None,
    ):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be positive, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s) if poll_s is not None \
            else max(0.05, self.timeout_s / 10.0)
        self.on_stall = on_stall
        self.dump_path = dump_path
        self.stalled = threading.Event()
        self.fired = threading.Event()  # one-shot on_stall latch
        self._last_beat = time.monotonic()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def beat(self) -> None:
        """Record progress; re-arms stall DETECTION after a stall (the
        ``on_stall`` latch stays closed — see ``reset``)."""
        self._last_beat = time.monotonic()
        self.stalled.clear()

    def reset(self) -> None:
        """Re-open the one-shot ``on_stall`` latch (and clear detection).

        Deliberately the ONLY way to re-arm the callback: beats resuming
        after a dump must not let a second slow step re-fire a policy
        that is already mid-recovery (e.g. the supervisor's
        checkpoint-and-restart). Call at a recovery boundary — the
        supervisor does so before each attempt.
        """
        self.fired.clear()
        self.beat()

    def _dump_stacks(self) -> None:
        try:
            if self.dump_path is not None:
                with open(self.dump_path, "a") as f:
                    f.write(f"=== StallWatchdog dump @ {time.time():.0f} "
                            f"(no beat for {self.silent_for():.1f}s) ===\n")
                    f.flush()
                    faulthandler.dump_traceback(file=f)
            else:
                faulthandler.dump_traceback()
        except Exception:  # diagnosis must never take the process down
            logger.exception("watchdog stack dump failed")

    def silent_for(self) -> float:
        return time.monotonic() - self._last_beat

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            quiet = self.silent_for()
            if quiet >= self.timeout_s and not self.stalled.is_set():
                self.stalled.set()
                # Countable stall evidence (ISSUE 3): the stack dump is
                # human forensics; the counter is what a scrape (and a
                # post-mortem of the JSONL stream's absence of `step`
                # events) can alert on. Lazy import: utils must stay a
                # leaf package at import time. Shielded like every other
                # diagnostic here — telemetry failing (e.g. interpreter
                # teardown) must not kill the monitor thread before the
                # dump and the on_stall escalation below run.
                try:
                    from ..obs.registry import default_registry

                    default_registry().counter(
                        "watchdog_stalls_total",
                        "silent-loop stalls detected").inc()
                except Exception:
                    logger.exception("watchdog stall counter failed")
                logger.error("training stalled: no progress for %.1fs "
                             "(timeout %.1fs) — dumping thread stacks",
                             quiet, self.timeout_s)
                self._dump_stacks()
                if self.on_stall is not None and not self.fired.is_set():
                    self.fired.set()
                    try:
                        self.on_stall(quiet)
                    except Exception:
                        logger.exception("watchdog on_stall callback failed")

    def start(self) -> "StallWatchdog":
        if self._thread is not None:
            raise RuntimeError("watchdog already started")
        self._stop.clear()  # stop() leaves it set; allow restart
        self.beat()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ntxent-stall-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_s * 4 + 1.0)
            self._thread = None

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
