"""Device-memory tracking, mirroring the reference's GPUMemoryTracker
(/root/reference/python/test.py:25-40): per-step allocated/reserved samples
dumped to ``memory_profile.json``. On TPU the numbers come from
``Device.memory_stats()`` (bytes_in_use / bytes_limit)."""

from __future__ import annotations

import json
import logging
from pathlib import Path

import jax

logger = logging.getLogger(__name__)

__all__ = ["DeviceMemoryTracker", "device_memory_mb"]


def device_memory_mb(device: jax.Device | None = None) -> dict[str, float]:
    """Current memory usage of one device, in MB. Empty dict if unsupported."""
    device = device or jax.local_devices()[0]
    stats = device.memory_stats() or {}
    out: dict[str, float] = {}
    if "bytes_in_use" in stats:
        out["allocated_mb"] = stats["bytes_in_use"] / 1024**2
    if "peak_bytes_in_use" in stats:
        out["peak_allocated_mb"] = stats["peak_bytes_in_use"] / 1024**2
    if "bytes_limit" in stats:
        out["reserved_mb"] = stats["bytes_limit"] / 1024**2
    return out


class DeviceMemoryTracker:
    """Samples device memory at named steps; saves a JSON profile.

    API mirror of GPUMemoryTracker (python/test.py:25-40): ``log_memory(step)``
    appends a sample and logs it; ``save_profile(path)`` dumps JSON.
    """

    def __init__(self, device: jax.Device | None = None):
        self.device = device or jax.local_devices()[0]
        self.snapshots: list[dict] = []

    def log_memory(self, step: str) -> dict:
        sample = {"step": step, **device_memory_mb(self.device)}
        self.snapshots.append(sample)
        alloc = sample.get("allocated_mb")
        if alloc is not None:
            logger.info("Memory at %s: %.1f MB allocated", step, alloc)
        else:
            logger.info("Memory at %s: stats unavailable on %s", step, self.device)
        return sample

    def save_profile(self, path: str | Path = "memory_profile.json") -> Path:
        path = Path(path)
        path.write_text(json.dumps(self.snapshots, indent=2))
        return path
