"""Accelerator capability probes.

TPU-native re-design of the reference's CUDA capability utilities
(/root/reference/include/ntxent_kernel.cuh:79-110): ``get_optimal_block_size``
becomes a (rows, dim, dtype)-keyed block-shape table in ops/blocks.py, and
``check_tensor_core_support`` (compute capability >= 7.0, i.e. "has tensor
cores") becomes "has a matrix unit": TPU MXU, or GPU with tensor cores.
"""

from __future__ import annotations

import functools

import jax

__all__ = [
    "check_tensor_core_support",
    "is_tpu_backend",
    "device_kind",
    "has_mxu",
    "supports_bf16_matmul",
    "verify_accelerator_requirements",
]


@functools.cache
def device_kind(backend: str | None = None) -> str:
    """Human-readable kind of the default device (e.g. 'TPU v5 lite')."""
    return jax.devices(backend)[0].device_kind if jax.devices(backend) else "none"


@functools.cache
def has_mxu(backend: str | None = None) -> bool:
    """True when the default device has a hardware matrix unit."""
    devices = jax.devices(backend)
    if not devices:
        return False
    platform = devices[0].platform
    if platform == "tpu" or platform == "axon":
        return True  # every TPU generation JAX supports has an MXU
    if platform == "gpu":
        # Mirror of the reference's CC >= 7.0 test (ntxent_kernel.cuh:98-110).
        cc = getattr(devices[0], "compute_capability", None)
        try:
            return cc is not None and float(cc) >= 7.0
        except (TypeError, ValueError):
            return False
    return False


def is_tpu_backend(backend: str | None = None) -> bool:
    """THE fused-path predicate: does the (given or default) backend
    compile Pallas kernels natively? 'tpu' on real hosts, 'axon' through
    the tunnel plugin — one copy of this tuple, so adding/renaming a
    backend cannot silently leave a caller on the ~100x interpret path."""
    return (backend or jax.default_backend()) in ("tpu", "axon")


def check_tensor_core_support() -> bool:
    """Reference-compatible probe (binding_new.cpp:19-20): matrix unit present?"""
    return has_mxu()


def supports_bf16_matmul() -> bool:
    """bf16 is native on all TPUs and Ampere+ GPUs; fp32-emulated on CPU."""
    platform = jax.devices()[0].platform
    return platform in ("tpu", "axon", "gpu")


def verify_accelerator_requirements(require_accelerator: bool = True) -> None:
    """Mirror of python/test.py:42-55 (verify_gpu_requirements).

    Raises RuntimeError unless an accelerator with a matrix unit is present.
    """
    if not require_accelerator:
        return
    if not has_mxu():
        raise RuntimeError(
            "No accelerator with a matrix unit found "
            f"(default device: {device_kind()!r}); NT-Xent kernels require "
            "a TPU or a tensor-core GPU (reference gate: CC >= 7.0)."
        )
