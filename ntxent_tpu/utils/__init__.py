from ntxent_tpu.utils.capability import (
    check_tensor_core_support,
    is_tpu_backend,
    device_kind,
    has_mxu,
    supports_bf16_matmul,
    verify_accelerator_requirements,
)
from ntxent_tpu.utils.logging_utils import setup_logging
from ntxent_tpu.utils.memory import DeviceMemoryTracker, device_memory_mb
from ntxent_tpu.utils.profiling import (
    BenchmarkResults,
    chain_flops_per_step,
    compile_chain,
    flops_from_compiled,
    measured_flops,
    time_chain,
    time_fn,
    time_fn_chained,
    trace,
)
from ntxent_tpu.utils.watchdog import StallWatchdog

__all__ = [
    "check_tensor_core_support",
    "device_kind",
    "has_mxu",
    "supports_bf16_matmul",
    "verify_accelerator_requirements",
    "setup_logging",
    "DeviceMemoryTracker",
    "device_memory_mb",
    "BenchmarkResults",
    "chain_flops_per_step",
    "compile_chain",
    "flops_from_compiled",
    "measured_flops",
    "time_chain",
    "time_fn",
    "time_fn_chained",
    "trace",
    "StallWatchdog",
]
