"""Logging setup mirroring the reference harness (python/test.py:18-23)."""

from __future__ import annotations

import logging

__all__ = ["setup_logging"]


def setup_logging(level: int = logging.INFO) -> logging.Logger:
    logging.basicConfig(
        level=level,
        format="%(asctime)s - %(levelname)s - %(message)s",
        force=False,
    )
    return logging.getLogger("ntxent_tpu")
