"""Logging setup mirroring the reference harness (python/test.py:18-23).

Fixed here (ISSUE 3 satellite): ``logging.basicConfig(force=False)`` is a
silent no-op once ANY handler exists on the root logger, so the second
caller of ``setup_logging`` (e.g. a test after the CLI, or a notebook
re-run) kept the first call's level and format without any indication.
``setup_logging`` now reconfigures deterministically: the requested
level always takes effect, and the root handler's formatter is updated
in place instead of being silently ignored.

``format_kv`` / ``KeyValueFormatter`` are the structured ``key=value``
rendering the event log's mirror-to-logger mode uses
(obs/events.py:EventLog(mirror_logger=True)): one greppable line per
record, values quoted only when they need it.
"""

from __future__ import annotations

import logging

__all__ = ["setup_logging", "format_kv", "KeyValueFormatter"]

_DEFAULT_FORMAT = "%(asctime)s - %(levelname)s - %(message)s"
_KV_FORMAT = "%(asctime)s %(levelname)s %(message)s"


def format_kv(fields: dict) -> str:
    """``key=value`` pairs in insertion order, shell-grep friendly.

    Values containing whitespace, quotes, or '=' are json-quoted so the
    line stays splittable on spaces; None renders as ``key=null``.
    """
    import json

    parts = []
    for key, value in fields.items():
        if value is None:
            rendered = "null"
        elif isinstance(value, bool):
            rendered = "true" if value else "false"
        elif isinstance(value, (int, float)):
            rendered = repr(value)
        else:
            text = str(value)
            needs_quote = any(c in text for c in ' \t\n"=') or not text
            rendered = json.dumps(text) if needs_quote else text
        parts.append(f"{key}={rendered}")
    return " ".join(parts)


class KeyValueFormatter(logging.Formatter):
    """Formatter emitting ``asctime level key=value ...`` lines.

    Plain-string records pass through as ``msg="..."``; dict records
    (``logger.info({"step": 3, ...})``) render as their pairs — the
    event-log mirror logs pre-rendered ``format_kv`` strings, so both
    shapes appear in practice.
    """

    def __init__(self, datefmt: str | None = None):
        super().__init__(fmt=_KV_FORMAT, datefmt=datefmt)

    def format(self, record: logging.LogRecord) -> str:
        if isinstance(record.msg, dict):
            # Render the dict as pairs; bypass %-interpolation (a dict
            # msg with args would TypeError inside getMessage).
            record = logging.makeLogRecord(record.__dict__)
            record.msg = format_kv(record.msg)
            record.args = ()
        return super().format(record)


def setup_logging(level: int = logging.INFO,
                  structured: bool = False) -> logging.Logger:
    """Idempotent root-logger configuration.

    First call: ``basicConfig`` with the framework format. Later calls:
    instead of basicConfig's silent keep-the-first-config behavior, the
    root LEVEL is always set to ``level`` and the formatter of the
    handlers *this function installed* (tagged at creation) is swapped
    to match ``structured`` — repeated setup converges on the last
    request instead of the first. Handlers other libraries put on the
    root logger are never touched: no ``force=True`` teardown, no
    formatter clobbering.

    ``structured=True`` uses ``KeyValueFormatter`` (key=value lines; the
    event-log mirror's format) instead of the human default.
    """
    root = logging.getLogger()
    formatter: logging.Formatter = (
        KeyValueFormatter() if structured
        else logging.Formatter(_DEFAULT_FORMAT))
    if not root.handlers:
        logging.basicConfig(level=level)
        for handler in root.handlers:
            handler._ntxent_managed = True
            handler.setFormatter(formatter)
    else:
        root.setLevel(level)
        for handler in root.handlers:
            if getattr(handler, "_ntxent_managed", False):
                handler.setFormatter(formatter)
    return logging.getLogger("ntxent_tpu")
