"""Host-side divergence policy: skip → loss-scale backoff → rollback.

The jit-side half lives in training/trainer.py: a step built with
``make_train_step(guard=True)`` (or the sharded equivalent) computes a
cheap ``jnp.isfinite`` reduction over the loss and global grad norm INSIDE
the compiled step and, when either is non-finite, applies no update —
params, optimizer state, and BatchNorm stats keep their pre-step values
while ``state.step`` still advances (the counter stays monotone for
checkpoint cadence and supervisor accounting). The step reports
``metrics["grad_norm"]`` and ``metrics["step_ok"]`` and accepts a trailing
``scale`` operand that multiplies the gradients (traced, so changing it
costs no recompile).

This module is the HOST half: ``DivergenceGuard`` consumes the per-step
``StepOutcome`` (trainer.train_loop feeds it via its ``step_guard`` hook)
and escalates through three tiers:

1. **skip** — a non-finite step was already dropped by the jitted guard;
   count it (one bad augmentation draw or data page should not kill a
   multi-day run).
2. **loss-scale backoff** — ``backoff_after`` CONSECUTIVE skips halve the
   gradient scale (``backoff_factor``); after ``regrow_after`` consecutive
   healthy steps the scale doubles back toward 1.0. This is the classic
   dynamic-loss-scale move, repurposed: persistent near-divergence usually
   means the effective LR is momentarily too hot.
3. **rollback** — ``rollback_after`` TOTAL skips (or the scale collapsing
   below ``min_scale``) raises ``DivergenceError``: the in-memory state is
   presumed poisoned beyond local repair, and the supervisor
   (resilience/supervisor.py) restarts the attempt from the newest VALID
   checkpoint (training/checkpoint.py verifies content checksums).

Either escalation tier can be disabled by passing ``None`` for its
threshold (the CLI's ``--nan-policy skip|backoff|rollback`` maps to
exactly that).
"""

from __future__ import annotations

import logging

from ..obs import events as obs_events
from ..obs.registry import default_registry

logger = logging.getLogger(__name__)

__all__ = ["DivergenceError", "DivergenceGuard"]

# Registry series (ISSUE 3): the guard's decisions were previously
# logger-only; a post-hoc diagnosis needs them countable and scrapeable.
_SKIPS = default_registry().counter(
    "train_divergence_skips_total",
    "non-finite steps skipped by the in-step guard")
_BACKOFFS = default_registry().counter(
    "train_divergence_backoffs_total",
    "gradient-scale backoff escalations")
_ROLLBACKS = default_registry().counter(
    "train_divergence_rollbacks_total",
    "DivergenceError rollbacks raised to the supervisor")
_SCALE = default_registry().gauge(
    "train_grad_scale", "current divergence-guard gradient scale")


class DivergenceError(RuntimeError):
    """Raised by DivergenceGuard when local recovery (skip/backoff) is
    exhausted; the supervisor's rollback tier catches it."""


class DivergenceGuard:
    """Callable step-guard for ``train_loop(step_guard=...)``.

    Receives a ``trainer.StepOutcome`` per step; raises ``DivergenceError``
    to demand a rollback. Exposes ``scale_value()`` — the gradient scale
    the loop passes to guarded steps (jnp scalar: updating it never
    retraces the step).

    Lag tolerance: under ``train_loop(metrics_lag=1)`` every outcome
    arrives exactly ONE step after it was dispatched (``outcome.lag ==
    1``), so each tier fires one step late in wall time but on the same
    skip counts — a NaN is never missed, only reported late. That is safe
    because the jit-side guard already withheld the non-finite update
    from params/opt-state in-step; the host tiers here only decide
    escalation. The backoff scale reaches the step stream up to two steps
    after the diverged step (the next step is already in flight when the
    outcome is read). ``divergence`` events carry the lag so a reader can
    line them up against ``step`` events.
    """

    def __init__(self, backoff_after: int | None = 2,
                 rollback_after: int | None = 8,
                 backoff_factor: float = 0.5,
                 regrow_after: int = 100,
                 min_scale: float = 2.0 ** -10,
                 init_scale: float = 1.0):
        if backoff_after is not None and backoff_after < 1:
            raise ValueError("backoff_after must be >= 1 or None")
        if rollback_after is not None and rollback_after < 1:
            raise ValueError("rollback_after must be >= 1 or None")
        if not 0.0 < backoff_factor < 1.0:
            raise ValueError("backoff_factor must be in (0, 1)")
        self.backoff_after = backoff_after
        self.rollback_after = rollback_after
        self.backoff_factor = backoff_factor
        self.regrow_after = regrow_after
        self.min_scale = min_scale
        self.scale = float(init_scale)
        # Publish the starting scale: a healthy run that never backs
        # off must scrape 1.0 (init_scale), not the gauge's 0.0 default.
        _SCALE.set(self.scale)
        self.consecutive_skips = 0
        self.total_skips = 0
        self._healthy_streak = 0

    def scale_value(self):
        import jax.numpy as jnp

        return jnp.asarray(self.scale, jnp.float32)

    def reset_attempt(self) -> None:
        """Per-attempt counter reset (the supervisor's restart boundary).
        The SCALE survives on purpose: a run that needed backoff before the
        rollback usually still needs it right after."""
        self.consecutive_skips = 0
        self.total_skips = 0
        self._healthy_streak = 0

    def _emit(self, action: str, outcome) -> None:
        # Non-finite loss/grad_norm floats are stringified by the
        # EventLog itself (obs.events._sanitize).
        obs_events.emit(
            "divergence", action=action, step=int(outcome.step),
            loss=outcome.loss, grad_norm=outcome.grad_norm,
            consecutive=self.consecutive_skips,
            total=self.total_skips, scale=self.scale, guarded=True,
            lag=int(getattr(outcome, "lag", 0)))

    def _rollback(self, outcome, message: str) -> None:
        _ROLLBACKS.inc()
        self._emit("rollback", outcome)
        raise DivergenceError(message)

    def __call__(self, outcome) -> None:
        if outcome.ok:
            self.consecutive_skips = 0
            self._healthy_streak += 1
            if self.scale < 1.0 \
                    and self._healthy_streak >= self.regrow_after:
                self.scale = min(1.0, self.scale / self.backoff_factor)
                self._healthy_streak = 0
                _SCALE.set(self.scale)
                logger.info("divergence guard: %d healthy steps — scale "
                            "regrown to %g", self.regrow_after, self.scale)
            return

        self._healthy_streak = 0
        self.consecutive_skips += 1
        self.total_skips += 1
        _SKIPS.inc()
        logger.warning(
            "divergence guard: non-finite step %d skipped (loss=%s, "
            "grad_norm=%s; %d consecutive, %d total)", outcome.step,
            outcome.loss, outcome.grad_norm, self.consecutive_skips,
            self.total_skips)
        if self.rollback_after is not None \
                and self.total_skips >= self.rollback_after:
            self._rollback(outcome, (
                f"{self.total_skips} non-finite steps this attempt "
                f"(budget {self.rollback_after}): rolling back to the "
                "last valid checkpoint"))
        if self.backoff_after is not None \
                and self.consecutive_skips >= self.backoff_after \
                and self.consecutive_skips % self.backoff_after == 0:
            self.scale *= self.backoff_factor
            _BACKOFFS.inc()
            _SCALE.set(self.scale)  # may be re-set below after clamping
            logger.warning("divergence guard: %d consecutive skips — "
                           "gradient scale backed off to %g",
                           self.consecutive_skips, self.scale)
            if self.scale < self.min_scale:
                if self.rollback_after is not None:
                    self._rollback(outcome, (
                        f"gradient scale {self.scale:g} collapsed below "
                        f"{self.min_scale:g}: rolling back to the last "
                        "valid checkpoint"))
                self.scale = self.min_scale
            # Publish AFTER the min_scale clamp: the gauge must report
            # the scale the traced step will actually use.
            _SCALE.set(self.scale)
            self._emit("backoff", outcome)
        else:
            self._emit("skip", outcome)
