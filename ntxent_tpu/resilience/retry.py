"""Retry policies for transient faults on step/IO paths.

The reference's only failure handling was throw-on-CUDA-error and exit(1)
(SURVEY.md §5.3); on a real multi-day run the common IO failures are
TRANSIENT — a GCS blip during a checkpoint write, a flaky NFS read in the
input pipeline, a wedged native-loader submission. ``RetryPolicy`` is the
one retry engine for all of them: exponential backoff with seeded jitter,
exception-class filters (retry only what is plausibly transient), attempt
and wall-clock budget caps so a *persistent* fault still fails fast enough
for the supervisor tier (resilience/supervisor.py) to act.

Wired in by:

* ``training/checkpoint.py`` — ``CheckpointManager(retry_policy=...)``
  retries the native checkpoint write/read;
* ``training/datasets.py`` — ``StreamingLoader(retry_policy=...)`` retries
  per-item source fetches inside the read-ahead pool;
* ``training/native_loader.py`` — ``NativeStreamingLoader`` retries batch
  submissions to the C++ engine.

Fault injection for all three lives in resilience/faults.py.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from collections.abc import Callable
from typing import Any

from ..obs import events as obs_events
from ..obs.registry import default_registry

logger = logging.getLogger(__name__)

# Registry series (ISSUE 3): a retried transient is SURVIVED, which is
# exactly why the log line alone vanishes — after the fact only a
# counter (and the `retry` event) shows a run was limping.
_RETRIES = default_registry().counter(
    "retries_total", "transient faults retried by RetryPolicy")
_EXHAUSTED = default_registry().counter(
    "retries_exhausted_total",
    "RetryPolicy give-ups (attempts or wall-clock budget spent)")

__all__ = ["RetryPolicy", "RetryBudgetExceeded", "DEFAULT_TRANSIENT"]

# What a retry may assume is transient without being told otherwise:
# filesystem/network hiccups (OSError covers ConnectionError and friends)
# and timeouts. NOT RuntimeError — a wedged backend usually stays wedged,
# and retrying it hides the stall the watchdog exists to surface.
DEFAULT_TRANSIENT: tuple[type[BaseException], ...] = (OSError, TimeoutError)


class RetryBudgetExceeded(RuntimeError):
    """Raised when the policy's wall-clock budget ran out mid-retry.

    Carries the last underlying exception as ``__cause__`` so callers (and
    the supervisor's logs) still see the root fault.
    """


@dataclasses.dataclass
class RetryPolicy:
    """Exponential backoff + jitter, exception filters, budget caps.

    ``call(fn, *args)`` runs ``fn`` up to ``max_attempts`` times, sleeping
    ``min(base_delay_s * multiplier**k, max_delay_s) * (1 + U*jitter)``
    between attempts (U uniform in [0, 1) from a ``seed``-derived RNG, so a
    re-run of a failed job backs off identically). Only exceptions that are
    instances of ``retry_on`` are retried — anything else propagates on the
    first throw. ``budget_s`` caps the TOTAL wall clock spent (attempts +
    sleeps); once exceeded the last exception is re-raised wrapped in
    ``RetryBudgetExceeded``.

    The policy object is stateless across ``call``s (the jitter RNG is the
    only mutable member, and it only affects sleep lengths), so one policy
    can be shared by every fetch thread of a loader.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.1
    multiplier: float = 2.0
    max_delay_s: float = 30.0
    jitter: float = 0.1
    retry_on: tuple[type[BaseException], ...] = DEFAULT_TRANSIENT
    budget_s: float | None = None
    seed: int = 0
    # Injectable clock/sleep so tests exercise the schedule without real
    # waiting (resilience tests pin the exact delay sequence).
    sleep: Callable[[float], None] = time.sleep
    monotonic: Callable[[], float] = time.monotonic

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, "
                             f"got {self.max_attempts}")
        if self.base_delay_s < 0 or self.max_delay_s < 0:
            raise ValueError("delays must be non-negative")
        self._rng = random.Random(self.seed)

    def delay_for(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based: the sleep
        after the ``attempt``-th failure)."""
        base = min(self.base_delay_s * self.multiplier ** (attempt - 1),
                   self.max_delay_s)
        return base * (1.0 + self._rng.random() * self.jitter)

    def call(self, fn: Callable, *args, **kwargs) -> Any:
        start = self.monotonic()
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                name = getattr(fn, "__name__", repr(fn))
                if attempt >= self.max_attempts:
                    _EXHAUSTED.inc()
                    raise
                delay = self.delay_for(attempt)
                if self.budget_s is not None and \
                        self.monotonic() - start + delay > self.budget_s:
                    _EXHAUSTED.inc()
                    raise RetryBudgetExceeded(
                        f"retry budget {self.budget_s:.1f}s exhausted after "
                        f"{attempt} attempt(s) of "
                        f"{getattr(fn, '__name__', fn)!r}") from e
                _RETRIES.inc()
                # NB "attempt" is the record's supervisor-attempt id;
                # the retry ordinal ships as call_attempt.
                obs_events.emit(
                    "retry", fn=name, call_attempt=attempt,
                    max_attempts=self.max_attempts,
                    error=f"{type(e).__name__}: {e}",
                    delay_s=round(delay, 4))
                logger.warning(
                    "transient failure in %r (attempt %d/%d): %s — "
                    "retrying in %.2fs",
                    name, attempt, self.max_attempts, e, delay)
                self.sleep(delay)
        raise AssertionError("unreachable")  # pragma: no cover

    def wrap(self, fn: Callable) -> Callable:
        """``fn`` with this policy baked in (for handing to thread pools)."""
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        return wrapped
