"""Deterministic fault injection: the chaos half of the resilience layer.

A recovery path that has never executed is a liability, not a feature — so
every recovery tier in this package (retry, divergence skip, checkpoint
fallback, supervisor restart) has a matching *injectable* fault here, and
``tests/test_resilience.py`` + ``scripts/chaos_smoke.sh`` drive them
end-to-end on CPU. The same plans run in production shape via
``ntxent-train --chaos 'nan@3,sigterm@6,truncate@1'``.

Primitives (each fires exactly once per plan entry, at a deterministic
ordinal — no randomness in WHAT happens, only the seed field for future
schedule randomization):

* ``nan@k``      — NaN-poison the k-th batch served (float leaves only)
                   → exercises the step divergence guard (guard.py);
* ``sigterm@k``  — deliver SIGTERM to this process while serving the k-th
                   batch → exercises PreemptionGuard save-and-stop plus the
                   supervisor's resume-at-k restart;
* ``kill@k``     — deliver SIGKILL to this process while serving the k-th
                   batch: NO cleanup, no atexit, no final checkpoint — the
                   hard-death case (OOM-killer, node loss) the crash-replay
                   audit (crashsim.py / scripts/crash_audit.sh) drives to
                   prove restart is lossless, not merely possible;
* ``crash@k``    — raise ``ChaosError`` while serving the k-th batch
                   → exercises the supervisor's exception-restart path;
* ``fetch@n``    — raise a transient ``OSError`` on the n-th source fetch
                   → exercises the loader's RetryPolicy (retry.py);
* ``diskfull@n`` — raise ``OSError(ENOSPC)`` at the start of the n-th
                   physical checkpoint write (wired through
                   ``CheckpointManager(fault_hook=...)``) → exercises the
                   skip-a-checkpoint contract (failure counter + ok=false
                   event, run continues) on both sync and async writers;
* ``shrink@k``   — raise ``TopologyChange("shrink")`` while serving the
                   k-th batch: the world got smaller (a preemptible pool
                   lost devices). The supervisor's topology hook rebuilds
                   the mesh over FEWER devices before the next attempt
                   and restore re-shards the checkpoint onto it
                   (training/checkpoint.py topology sidecar); crashsim's
                   elastic audit drives the same transition across a
                   subprocess boundary by changing the simulated device
                   count (``XLA_FLAGS``) between incarnations;
* ``grow@k``     — ``TopologyChange("grow")``: the pool came back — the
                   next attempt rebuilds the mesh over the full device
                   set and restore re-shards the shrunken checkpoint up
                   onto it;
* ``truncate@a`` — after attempt number a ends, truncate the newest
                   checkpoint's largest file → exercises checksum
                   verification and newest-VALID fallback (checkpoint.py);
* ``killworker@t`` — SIGKILL one serving-fleet worker on the t-th fleet
                   supervision tick, counted from the first tick where
                   every worker is ready (serving/fleet.py polls health
                   once per tick; targets rotate round-robin over the
                   live workers) → exercises the router's per-request retry
                   budget (zero client-visible 5xx) and the fleet's
                   restart-with-backoff path;
* ``slowworker@t`` — SIGSTOP one worker on the t-th fleet tick for a few
                   seconds (then SIGCONT): the gray failure — a process
                   that is alive but answers nothing → exercises
                   health-probe failure counting and ejection, without
                   the clean signal a death gives;
* ``spike@t``    — fire the fleet's flash-crowd hook on the t-th fleet
                   tick (``ntxent-fleet --autoscale`` wires it to a
                   loadgen burst against the router's own /embed) →
                   exercises the autoscale controller's scale-up path
                   under a deliberately rude arrival burst (ISSUE 16);
* ``drainworker@t`` — force an autoscaler drain-down on the t-th fleet
                   tick, mid-load: the victim stops receiving routes,
                   in-flight completes, SIGTERM only after → exercises
                   the zero-5xx scale-down contract and the
                   below-min-repair path (serving/autoscale.py);
* ``killshard@t`` — SIGKILL one retrieval SHARD worker on the t-th
                   shard-fleet supervision tick (its own ordinal,
                   counted from the shard fleet's all-ready point) →
                   exercises the degraded-recall-never-5xx merge, the
                   insert journal, and journal-drain repair on restart
                   (ISSUE 20);
* ``lagshard@t`` — SIGSTOP one shard worker on the t-th shard-fleet
                   tick (the gray shard: alive, answering nothing) →
                   exercises the ShardClient timeout cooldown + free
                   retry and the fan-out's degraded merge.

``FaultPlan`` is the parsed, immutable spec; ``FaultInjector`` carries the
runtime counters and the wrapping hooks call sites use. Batch-path
ordinals (nan/sigterm/kill/crash/shrink/grow) count served batches;
``fetch``/``diskfull`` count their own IO calls; ``truncate`` counts
supervisor attempts; the fleet actions count supervision ticks.
"""

from __future__ import annotations

import dataclasses
import errno
import logging
import os
import signal
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["ChaosError", "TopologyChange", "FaultPlan", "FaultInjector",
           "truncate_checkpoint_file"]

_KINDS = ("nan", "sigterm", "kill", "crash", "fetch", "diskfull",
          "shrink", "grow", "truncate", "killworker", "slowworker",
          "spike", "drainworker", "killshard", "lagshard")


class ChaosError(RuntimeError):
    """An injected hard failure (the ``crash@k`` primitive)."""


class TopologyChange(RuntimeError):
    """The world changed under the run (``shrink@k`` / ``grow@k``): the
    attempt must die and the next one rebuild its mesh over a different
    device set. Raised out of the batch path; the Supervisor's
    ``topology_hook`` is the handler that actually reshapes the world."""

    def __init__(self, action: str, batch: int):
        super().__init__(f"chaos: injected {action} at batch {batch}")
        self.action = action
        self.batch = batch


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Immutable, seeded chaos plan. Ordinals are 1-based."""

    nan_batches: tuple[int, ...] = ()
    sigterm_batches: tuple[int, ...] = ()
    kill_batches: tuple[int, ...] = ()
    crash_batches: tuple[int, ...] = ()
    fetch_calls: tuple[int, ...] = ()
    diskfull_writes: tuple[int, ...] = ()
    shrink_batches: tuple[int, ...] = ()
    grow_batches: tuple[int, ...] = ()
    truncate_attempts: tuple[int, ...] = ()
    killworker_ticks: tuple[int, ...] = ()
    slowworker_ticks: tuple[int, ...] = ()
    spike_ticks: tuple[int, ...] = ()
    drainworker_ticks: tuple[int, ...] = ()
    killshard_ticks: tuple[int, ...] = ()
    lagshard_ticks: tuple[int, ...] = ()
    seed: int = 0

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultPlan":
        """Parse ``"nan@3,sigterm@6,kill@4,shrink@5,killworker@7"``
        (the --chaos syntax). An unknown action names the full valid
        set — a typo'd chaos plan must fail loud and teachable, not
        with a bare error."""
        buckets: dict[str, list[int]] = {k: [] for k in _KINDS}
        for item in filter(None, (s.strip() for s in spec.split(","))):
            kind, sep, at = item.partition("@")
            if not sep:
                raise ValueError(
                    f"bad fault {item!r}: expected <action>@<ordinal>, "
                    f"e.g. 'nan@3'; valid actions: "
                    f"{', '.join(sorted(_KINDS))}")
            if kind not in buckets:
                raise ValueError(
                    f"unknown fault action {kind!r} in {item!r}; valid "
                    f"actions: {', '.join(sorted(_KINDS))}")
            try:
                ordinal = int(at)
            except ValueError:
                raise ValueError(f"bad fault ordinal in {item!r}") from None
            if ordinal < 1:
                raise ValueError(f"fault ordinal must be >= 1: {item!r}")
            buckets[kind].append(ordinal)
        return cls(nan_batches=tuple(buckets["nan"]),
                   sigterm_batches=tuple(buckets["sigterm"]),
                   kill_batches=tuple(buckets["kill"]),
                   crash_batches=tuple(buckets["crash"]),
                   fetch_calls=tuple(buckets["fetch"]),
                   diskfull_writes=tuple(buckets["diskfull"]),
                   shrink_batches=tuple(buckets["shrink"]),
                   grow_batches=tuple(buckets["grow"]),
                   truncate_attempts=tuple(buckets["truncate"]),
                   killworker_ticks=tuple(buckets["killworker"]),
                   slowworker_ticks=tuple(buckets["slowworker"]),
                   spike_ticks=tuple(buckets["spike"]),
                   drainworker_ticks=tuple(buckets["drainworker"]),
                   killshard_ticks=tuple(buckets["killshard"]),
                   lagshard_ticks=tuple(buckets["lagshard"]),
                   seed=seed)

    def empty(self) -> bool:
        return not (self.nan_batches or self.sigterm_batches
                    or self.kill_batches or self.crash_batches
                    or self.fetch_calls or self.diskfull_writes
                    or self.shrink_batches or self.grow_batches
                    or self.truncate_attempts or self.killworker_ticks
                    or self.slowworker_ticks or self.spike_ticks
                    or self.drainworker_ticks or self.killshard_ticks
                    or self.lagshard_ticks)

    def has_shard_actions(self) -> bool:
        """True when the plan targets the retrieval shard fleet (the
        CLI hands those ticks to the shard fleet's injector channel)."""
        return bool(self.killshard_ticks or self.lagshard_ticks)


def _poison_leaf(x):
    """NaN-fill float leaves; leave integer leaves (e.g. CLIP tokens)
    alone — an integer array has no NaN and the guard watches the loss."""
    import jax.numpy as jnp

    if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
        return jnp.full_like(x, jnp.nan)
    return x


def truncate_checkpoint_file(directory: str | os.PathLike,
                             step: int | None = None) -> Path | None:
    """Truncate the largest file of a checkpoint step dir to half its size
    (simulating a partial write / torn page). ``step=None`` → newest step.
    Returns the truncated path, or None when there was nothing to corrupt.
    """
    root = Path(directory)
    if not root.is_dir():
        return None
    steps = sorted((int(p.name), p) for p in root.iterdir()
                   if p.is_dir() and p.name.isdigit())
    if not steps:
        return None
    if step is None:
        step_dir = steps[-1][1]
    else:
        match = [p for s, p in steps if s == step]
        if not match:
            return None
        step_dir = match[0]
    files = sorted((p for p in step_dir.rglob("*") if p.is_file()),
                   key=lambda p: p.stat().st_size)
    if not files or files[-1].stat().st_size == 0:
        return None
    victim = files[-1]
    size = victim.stat().st_size
    with open(victim, "r+b") as f:
        f.truncate(size // 2)
    logger.warning("chaos: truncated %s from %d to %d bytes",
                   victim, size, size // 2)
    return victim


class FaultInjector:
    """Runtime counters + wrapping hooks for a ``FaultPlan``.

    One injector per supervised run: batch/fetch/attempt ordinals count
    across restarts (a resumed attempt continues the sequence), so a plan
    is a deterministic script for the whole run.
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._batches = 0
        self._fetches = 0
        self._ckpt_writes = 0
        self._attempts = 0
        self._fleet_ticks = 0
        self._shard_ticks = 0
        self.fired: list[str] = []

    # -- batch-path faults (wrap the training data iterator) -------------
    def wrap_iterator(self, data_iter):
        """Chaos-wrap a batch iterator, preserving the checkpointable
        ``state()``/``restore()`` protocol when the inner iterator has it
        (trainer.fit keys on those attributes)."""
        if hasattr(data_iter, "state") and hasattr(data_iter, "restore"):
            return _ChaosBatchesStateful(data_iter, self)
        return _ChaosBatches(data_iter, self)

    def on_batch(self, batch):
        """Apply due batch faults; returns the (possibly poisoned) batch."""
        self._batches += 1
        n = self._batches
        if n in self.plan.nan_batches:
            import jax

            logger.warning("chaos: NaN-poisoning batch %d", n)
            self.fired.append(f"nan@{n}")
            batch = jax.tree.map(_poison_leaf, batch)
        if n in self.plan.sigterm_batches:
            logger.warning("chaos: delivering SIGTERM at batch %d", n)
            self.fired.append(f"sigterm@{n}")
            os.kill(os.getpid(), signal.SIGTERM)
        if n in self.plan.kill_batches:
            # SIGKILL is uncatchable: nothing after this line runs — no
            # cleanup, no final save. Write the marker straight to fd 2
            # (the logger's buffers would die with us) so crash harnesses
            # can still see the fault fired.
            self.fired.append(f"kill@{n}")
            try:
                os.write(2, f"chaos: SIGKILL at batch {n}\n".encode())
            except OSError:
                pass
            os.kill(os.getpid(), signal.SIGKILL)
        if n in self.plan.crash_batches:
            self.fired.append(f"crash@{n}")
            raise ChaosError(f"chaos: injected crash at batch {n}")
        if n in self.plan.shrink_batches:
            logger.warning("chaos: topology shrink at batch %d", n)
            self.fired.append(f"shrink@{n}")
            raise TopologyChange("shrink", n)
        if n in self.plan.grow_batches:
            logger.warning("chaos: topology grow at batch %d", n)
            self.fired.append(f"grow@{n}")
            raise TopologyChange("grow", n)
        return batch

    # -- fetch-path faults (wrap a random-access source) ------------------
    def wrap_source(self, source):
        """A source whose n-th ``__getitem__`` raises a transient OSError
        when the plan says so (StreamingLoader's RetryPolicy target)."""
        return _FlakySource(source, self)

    def on_fetch(self):
        self._fetches += 1
        if self._fetches in self.plan.fetch_calls:
            self.fired.append(f"fetch@{self._fetches}")
            raise OSError(
                f"chaos: injected transient fetch failure "
                f"(call {self._fetches})")

    # -- checkpoint-writer faults (CheckpointManager fault_hook) ----------
    def on_checkpoint_write(self):
        """Raise ENOSPC at the start of the n-th physical checkpoint
        write when the plan says so (the ``diskfull@n`` primitive). Wire
        as ``CheckpointManager(fault_hook=injector.on_checkpoint_write)``
        — the CLI does this whenever a chaos plan is active. NOTE: may be
        called from the AsyncCheckpointer writer thread; counters here
        are only ever touched by one writer at a time."""
        self._ckpt_writes += 1
        if self._ckpt_writes in self.plan.diskfull_writes:
            self.fired.append(f"diskfull@{self._ckpt_writes}")
            raise OSError(
                errno.ENOSPC,
                f"chaos: injected ENOSPC on checkpoint write "
                f"{self._ckpt_writes}")

    # -- fleet faults (serving/fleet.py calls once per supervision tick) --
    def on_fleet_tick(self) -> list[str]:
        """Advance the fleet-tick ordinal; return the fleet actions due
        this tick (``["killworker@3", "slowworker@5"]``-style strings —
        the fleet picks WHICH worker, round-robin over the live set, so
        the plan stays deterministic without naming pids)."""
        self._fleet_ticks += 1
        t = self._fleet_ticks
        due: list[str] = []
        if t in self.plan.killworker_ticks:
            due.append(f"killworker@{t}")
        if t in self.plan.slowworker_ticks:
            due.append(f"slowworker@{t}")
        if t in self.plan.spike_ticks:
            due.append(f"spike@{t}")
        if t in self.plan.drainworker_ticks:
            due.append(f"drainworker@{t}")
        self.fired.extend(due)
        return due

    def on_shard_tick(self) -> list[str]:
        """The SHARD fleet's tick channel: its own ordinal (counted
        from the shard fleet's all-ready point — two fleets booting at
        different speeds must not skew each other's chaos schedules),
        dispensing only the shard actions."""
        self._shard_ticks += 1
        t = self._shard_ticks
        due: list[str] = []
        if t in self.plan.killshard_ticks:
            due.append(f"killshard@{t}")
        if t in self.plan.lagshard_ticks:
            due.append(f"lagshard@{t}")
        self.fired.extend(due)
        return due

    # -- checkpoint faults (supervisor calls between attempts) ------------
    def between_attempts(self, checkpoint_dir: str | os.PathLike | None):
        self._attempts += 1
        if self._attempts in self.plan.truncate_attempts \
                and checkpoint_dir is not None:
            victim = truncate_checkpoint_file(checkpoint_dir)
            if victim is not None:
                self.fired.append(f"truncate@{self._attempts}")


class _ChaosBatches:
    def __init__(self, inner, injector: FaultInjector):
        self._inner = inner
        self._injector = injector
        self._it = None

    def __iter__(self):
        return self

    def __next__(self):
        if self._it is None:
            self._it = iter(self._inner)
        return self._injector.on_batch(next(self._it))


class _ChaosBatchesStateful(_ChaosBatches):
    def state(self) -> dict:
        return self._inner.state()

    def restore(self, state: dict) -> None:
        self._inner.restore(state)
        self._it = None  # re-enter the (repositioned) inner iterator


class _FlakySource:
    """Source wrapper raising planned transient fetch errors."""

    def __init__(self, inner, injector: FaultInjector):
        self._inner = inner
        self._injector = injector

    def __len__(self) -> int:
        return len(self._inner)

    def __getitem__(self, idx: int) -> np.ndarray:
        self._injector.on_fetch()
        return self._inner[idx]
