"""Fault tolerance for long training runs: detect, retry, skip, restart.

The reference's failure model was throw-on-CUDA-error and ``exit(1)``
(SURVEY.md §5.3). A production run dies first from transient faults —
preempted hosts, flaky IO, NaN-ed batches, torn checkpoint writes — so
this package composes the framework's detectors into recovery tiers:

======================  =========================  ========================
fault                   detector                   recovery
======================  =========================  ========================
transient IO error      exception filter           RetryPolicy backoff
                        (retry.py)                 (loader fetch, checkpoint
                                                   save/restore)
NaN/Inf loss or grads   in-step isfinite guard     skip batch → loss-scale
                        (trainer guard=True)       backoff → rollback
                                                   (guard.DivergenceGuard)
SIGTERM / preemption    PreemptionGuard            checkpoint at the step
                                                   boundary; Supervisor
                                                   restarts in-process
hung step / collective  StallWatchdog              stack dumps + one-shot
                        (utils/watchdog.py)        escalation: stop attempt,
                                                   restart
corrupt checkpoint      per-save CRC manifest      restore falls back to the
                        (training/checkpoint.py)   newest VALID step, then
                                                   to the mirror replica
SIGKILL / node loss     nothing can run            atomic checkpoint writes:
                                                   relaunch resumes bit-
                                                   exactly (crashsim.py /
                                                   scripts/crash_audit.sh)
======================  =========================  ========================

Every tier is driven end-to-end by the deterministic fault-injection
harness in ``faults.py`` (tests/test_resilience.py, scripts/chaos_smoke.sh,
``ntxent-train --chaos``), and the checkpoint path's crash-safety is
audited against real SIGKILLs by ``crashsim.CrashAudit`` (deliberately
JAX-free: it orchestrates training subprocesses, so import it without
paying backend init).
"""

from ntxent_tpu.resilience.faults import (
    ChaosError,
    FaultInjector,
    FaultPlan,
    truncate_checkpoint_file,
)
from ntxent_tpu.resilience.guard import DivergenceError, DivergenceGuard
from ntxent_tpu.resilience.retry import (
    DEFAULT_TRANSIENT,
    RetryBudgetExceeded,
    RetryPolicy,
)

__all__ = [
    "ChaosError",
    "FaultInjector",
    "FaultPlan",
    "truncate_checkpoint_file",
    "DivergenceError",
    "DivergenceGuard",
    "DEFAULT_TRANSIENT",
    "RetryBudgetExceeded",
    "RetryPolicy",
    "AttemptRecord",
    "Supervisor",
    "SupervisorResult",
]


def __getattr__(name):
    # Supervisor lazily: it imports the training package (PreemptionGuard)
    # whose checkpoint manager pulls orbax, and orbax import initializes
    # the JAX backends — `import ntxent_tpu.resilience` for a RetryPolicy
    # must not pay (or pin) backend discovery.
    if name in ("Supervisor", "SupervisorResult", "AttemptRecord"):
        from ntxent_tpu.resilience import supervisor as _supervisor

        return getattr(_supervisor, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
